"""Thread-safe arrival-ordered request queue with admission control.

Producers (CLI readers, the bench load generator, RPC handlers) submit
from any thread; the engine drains from its scheduling loop. Admission
applies three typed guards at submit time, so a request that can never
be served (or should not be) fails fast in the producer instead of
wedging or bloating the queue:

- **budget** — the request's whole-lifetime KV footprint must be
  servable: ``prompt_len + max_new_tokens`` within the per-slot token
  budget (:func:`~distributed_training_tpu.inference.sampler.
  cache_budget`), and — paged engine — its worst-case page count
  (``ceil(total / kv_page_size)``) within the page pool. Violations
  raise the typed :class:`~distributed_training_tpu.inference.sampler.
  CacheBudgetError` with page-based accounting (pages needed vs the
  pool/table capacity); it would never become admissible, so queueing
  it would wedge the FIFO head forever.
- **depth** — an optional ``max_depth`` bounds the queue; a submit that
  would exceed it is SHED with :class:`~distributed_training_tpu.
  resilience.errors.QueueFullError` (every queued request's TTFT grows
  with depth — past the SLA horizon, rejecting early beats accepting
  work that is already doomed to time out).
- **drain** — :meth:`close` flips admission off for graceful shutdown;
  subsequent submits raise :class:`~distributed_training_tpu.resilience.
  errors.DrainingError` while the engine finishes what it already
  accepted.
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np

from distributed_training_tpu.inference.sampler import CacheBudgetError
from distributed_training_tpu.resilience.errors import (
    DrainingError,
    QueueFullError,
)
from distributed_training_tpu.serving.request import Request


class RequestQueue:
    """FIFO of :class:`Request` with typed admission guards.

    ``budget`` is the per-slot KV-cache capacity in tokens; ``submit``
    enforces ``prompt_len + max_new_tokens <= budget``. ``depth_max``
    tracks the high-water queue depth for SLA telemetry; ``shed`` /
    ``drain_rejected`` count the load-shedding and drain rejections.
    ``ttft_deadline_ms`` / ``deadline_ms`` stamp every admitted request
    with absolute deadlines (the engine evicts violators with finish
    reason ``timeout``).

    ``trace`` (a TraceSession or None) marks every admission decision on
    the timeline's 'queue' track: arrivals as instants (at the request's
    ARRIVAL time, so queueing spans line up), sheds/drain rejections as
    instants at the rejection.
    """

    def __init__(self, budget: int, default_max_new_tokens: int = 128,
                 max_depth: int | None = None,
                 ttft_deadline_ms: float | None = None,
                 deadline_ms: float | None = None,
                 trace=None, page_size: int | None = None,
                 pool_pages: int | None = None):
        if budget < 2:
            raise ValueError(f"budget must be >= 2, got {budget}")
        if max_depth is not None and max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.budget = int(budget)
        # Paged-KV admission accounting: when set, the fail-fast check
        # (and its error message) is in pages — a request whose
        # worst-case page count exceeds the POOL can never seat, even
        # if its token count fits the per-slot table.
        self.page_size = page_size
        self.pool_pages = pool_pages
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_depth = max_depth
        self.ttft_deadline_ms = ttft_deadline_ms
        self.deadline_ms = deadline_ms
        self.trace = trace
        self._lock = threading.Lock()
        self._q: collections.deque[Request] = collections.deque()
        self._closed = False
        self._next_uid = 0
        self.depth_max = 0
        self.submitted = 0
        self.rejected = 0
        self.shed = 0
        self.drain_rejected = 0

    def submit(self, prompt, max_new_tokens: int | None = None,
               arrival_t: float | None = None) -> Request:
        """Enqueue one request; returns its admission record.

        Raises :class:`CacheBudgetError` when the request can never fit a
        slot, :class:`QueueFullError` when the bounded queue is full, and
        :class:`DrainingError` after :meth:`close`. ``arrival_t``
        defaults to now (perf_counter) — the bench passes its scheduled
        arrival so queueing delay is measured from the intended arrival,
        not from when the host thread got around to the submit call.
        """
        tokens = np.ascontiguousarray(np.asarray(prompt).reshape(-1),
                                      dtype=np.int32)
        if tokens.size < 1:
            raise ValueError("empty prompt (need at least one token)")
        mnt = (self.default_max_new_tokens
               if max_new_tokens is None else int(max_new_tokens))
        if mnt < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {mnt}")
        total = tokens.size + mnt
        if self.page_size is not None:
            # Page-based accounting: the request's worst-case footprint
            # in pages vs what a slot's page table (and the pool) can
            # ever hand one sequence.
            from distributed_training_tpu.serving.pages import pages_for

            need = pages_for(total, self.page_size)
            cap = pages_for(self.budget, self.page_size)
            if self.pool_pages is not None:
                cap = min(cap, self.pool_pages)
            # The token budget stays authoritative (write positions must
            # fit the positional table) even when page-count rounding
            # would cover the overflow.
            if need > cap or total > self.budget:
                with self._lock:
                    self.rejected += 1
                raise CacheBudgetError(
                    f"prompt ({tokens.size}) + max_new_tokens ({mnt}) = "
                    f"{total} tokens needs {need} KV page(s) of "
                    f"{self.page_size}, but at most {cap} page(s) and "
                    f"{self.budget} token positions can ever serve one "
                    f"sequence"
                    + (f" ({self.pool_pages}-page pool)"
                       if self.pool_pages is not None else ""))
        elif total > self.budget:
            with self._lock:
                self.rejected += 1
            raise CacheBudgetError(
                f"prompt ({tokens.size}) + max_new_tokens ({mnt}) = "
                f"{total} exceeds the KV cache (max_len={self.budget})")
        arrival = (time.perf_counter()
                   if arrival_t is None else float(arrival_t))
        with self._lock:
            if self._closed:
                self.drain_rejected += 1
                if self.trace is not None:
                    self.trace.instant("request.drain_rejected",
                                       track="queue")
                raise DrainingError(
                    "engine is draining: admission is closed while "
                    "in-flight requests complete; submit to another "
                    "replica or retry after restart")
            if self.max_depth is not None and len(self._q) >= self.max_depth:
                self.shed += 1
                if self.trace is not None:
                    self.trace.instant("request.shed", track="queue",
                                       depth=len(self._q))
                raise QueueFullError(
                    f"request queue is at max_depth={self.max_depth}; "
                    f"shedding load instead of growing the queue (and "
                    f"every queued request's TTFT) without bound")
            req = Request(
                uid=self._next_uid, prompt=tokens, max_new_tokens=mnt,
                arrival_t=arrival,
                ttft_deadline_t=(arrival + self.ttft_deadline_ms / 1e3
                                 if self.ttft_deadline_ms else None),
                deadline_t=(arrival + self.deadline_ms / 1e3
                            if self.deadline_ms else None))
            self._next_uid += 1
            self._q.append(req)
            self.submitted += 1
            self.depth_max = max(self.depth_max, len(self._q))
            if self.trace is not None:
                self.trace.instant("request.arrival", track="queue",
                                   t=arrival, uid=req.uid,
                                   prompt_len=int(tokens.size))
        return req

    def close(self) -> None:
        """Close admission (idempotent): the graceful-drain gate. Queued
        and slotted requests continue to completion; new submits raise
        the typed :class:`DrainingError`."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def reset_counters(self) -> None:
        """Zero the telemetry counters (depth high-water, submitted,
        rejected, shed, drain_rejected) without touching queued requests
        or the uid sequence — the engine calls this from ``reset_stats``
        so a compile warm-up pass doesn't contaminate the measured SLA
        window."""
        with self._lock:
            self.depth_max = len(self._q)
            self.submitted = 0
            self.rejected = 0
            self.shed = 0
            self.drain_rejected = 0

    def pop(self) -> Request | None:
        """Oldest queued request, or None when empty (never blocks — the
        engine polls at iteration boundaries, it does not park a thread)."""
        with self._lock:
            return self._q.popleft() if self._q else None

    def peek(self) -> Request | None:
        """The queue head without popping it — the page-aware admission
        gate inspects the head's footprint before committing pool pages
        (scheduler.admit's ``can_seat``)."""
        with self._lock:
            return self._q[0] if self._q else None

    def pop_expired(self, now: float) -> list[Request]:
        """Remove and return every queued request already past its TTFT
        or total deadline — they will never make their SLA, so they must
        not consume a prefill (the engine completes them with finish
        reason ``timeout``)."""
        with self._lock:
            expired = [r for r in self._q
                       if (r.ttft_deadline_t is not None
                           and now >= r.ttft_deadline_t)
                       or (r.deadline_t is not None and now >= r.deadline_t)]
            if expired:
                dead = set(id(r) for r in expired)
                self._q = collections.deque(
                    r for r in self._q if id(r) not in dead)
        return expired

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)
