"""Request lifecycle datatypes for the serving engine.

A request moves queue → slot → finished:

- :class:`Request` is the immutable admission record (tokens + budget +
  arrival timestamp).
- :class:`ActiveSequence` is a slot's host-side bookkeeping while the
  sequence decodes (emitted tokens, first/last token timestamps).
- :class:`FinishedRequest` is the completed result with its SLA numbers
  (TTFT from arrival to first emitted token; TPOT as the mean inter-token
  interval over the decode phase).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Why a sequence left its slot.
FINISH_EOS = "eos"        # emitted the configured eos_id
FINISH_LENGTH = "length"  # hit its max_new_tokens budget


@dataclasses.dataclass(frozen=True)
class Request:
    """One admitted generation request (arrival-ordered by ``uid``)."""

    uid: int
    prompt: np.ndarray        # int32 [T], T >= 1
    max_new_tokens: int
    arrival_t: float          # perf_counter at submit


@dataclasses.dataclass
class ActiveSequence:
    """Host-side state of one occupied decode slot."""

    request: Request
    slot: int
    tokens: list = dataclasses.field(default_factory=list)  # emitted ids
    first_token_t: float | None = None
    last_token_t: float | None = None

    def note_token(self, token: int, t: float) -> None:
        self.tokens.append(int(token))
        if self.first_token_t is None:
            self.first_token_t = t
        self.last_token_t = t

    def finish_reason(self, eos_id: int | None) -> str | None:
        """None while the sequence should keep decoding."""
        if eos_id is not None and self.tokens and self.tokens[-1] == eos_id:
            return FINISH_EOS
        if len(self.tokens) >= self.request.max_new_tokens:
            return FINISH_LENGTH
        return None


@dataclasses.dataclass(frozen=True)
class FinishedRequest:
    """A completed request with its per-request SLA measurements."""

    uid: int
    prompt: np.ndarray
    tokens: np.ndarray        # int32 [n], n >= 1 (EOS included when hit)
    finish_reason: str        # FINISH_EOS | FINISH_LENGTH
    ttft_ms: float            # arrival → first emitted token
    tpot_ms: float | None     # mean inter-token ms; None for 1-token outputs
    arrival_t: float          # perf_counter timestamps (fairness audits)
    first_token_t: float

    @staticmethod
    def from_active(seq: ActiveSequence, reason: str) -> "FinishedRequest":
        n = len(seq.tokens)
        tpot = None
        if n > 1:
            tpot = (seq.last_token_t - seq.first_token_t) * 1e3 / (n - 1)
        return FinishedRequest(
            uid=seq.request.uid,
            prompt=seq.request.prompt,
            tokens=np.asarray(seq.tokens, np.int32),
            finish_reason=reason,
            ttft_ms=(seq.first_token_t - seq.request.arrival_t) * 1e3,
            tpot_ms=tpot,
            arrival_t=seq.request.arrival_t,
            first_token_t=seq.first_token_t,
        )
