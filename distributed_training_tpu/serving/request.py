"""Request lifecycle datatypes for the serving engine.

A request moves queue → slot → finished — and, under overload, may take
the preemption detour slot → queue → slot again:

- :class:`Request` is the immutable admission record (tokens + budget +
  arrival timestamp + SLO tier + tenant).
- :class:`ActiveSequence` is a slot's host-side bookkeeping while the
  sequence decodes (emitted tokens, first/last token timestamps). When a
  higher-tier request needs its slot or pages, :meth:`prepare_resume`
  turns it into a queued *resumption*: the emitted tokens ride along and
  are re-prefilled on the next seat, so the preemption is LOSSLESS —
  the continued token stream is bitwise identical to an uninterrupted
  run (see docs/SERVING.md "Tiered scheduling & preemption").
- :class:`FinishedRequest` is the completed result with its SLA numbers
  (TTFT from arrival to first emitted token; TPOT as the mean inter-token
  interval over the decode phase).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from distributed_training_tpu.serving.ledger import LatencyLedger

# Why a sequence left its slot (or the queue).
FINISH_EOS = "eos"        # emitted the configured eos_id
FINISH_LENGTH = "length"  # hit its max_new_tokens budget
FINISH_TIMEOUT = "timeout"  # missed its TTFT/total deadline (evicted)
# Tier-aware load shedding: a queued lower-tier request dropped to make
# room for a higher-tier arrival on a full queue (serving/queue.py).
FINISH_SHED = "shed"
# A preempted-and-requeued sequence whose deadline expired before it
# could re-seat (or finish after re-seating). Kept distinct from plain
# ``timeout`` so telemetry attributes the miss to preemption pressure,
# not to the request's own service time.
FINISH_PREEMPT_TIMEOUT = "preempted_timeout"
# The client hung up (broken pipe on an SSE write): the frontend asks
# the engine to cancel, the engine evicts at its next step boundary and
# frees the pages — decoding to completion for a dead socket would burn
# slots and skew every latency percentile with tokens nobody received.
FINISH_CANCELLED = "cancelled"


@dataclasses.dataclass(frozen=True)
class Request:
    """One admitted generation request (arrival-ordered by ``uid``).

    ``priority`` is the SLO tier: 0 is the highest (interactive) tier,
    larger numbers degrade first under load (``ServeConfig.num_tiers``
    bounds it). ``tenant`` names the submitting principal for the
    per-tenant quota/weighted-fair admission in
    :class:`~distributed_training_tpu.serving.queue.RequestQueue`.

    ``ttft_deadline_t`` / ``deadline_t`` are absolute ``perf_counter``
    deadlines (None = none): a request past its TTFT deadline with no
    first token yet (still queued, or seated mid-chunked-prefill), or
    still decoding past its total deadline, is evicted with finish
    reason ``timeout`` instead of holding a slot or queue position
    forever under overload. The clock keeps running while a preempted
    sequence waits requeued — that eviction reports
    ``preempted_timeout`` instead, so the miss is attributed to
    preemption pressure.
    """

    uid: int
    prompt: np.ndarray        # int32 [T], T >= 1
    max_new_tokens: int
    arrival_t: float          # perf_counter at submit
    ttft_deadline_t: float | None = None
    deadline_t: float | None = None
    priority: int = 0         # SLO tier, 0 = highest
    tenant: str = "default"
    # Distributed-tracing correlation id (docs/OBSERVABILITY.md "Fleet
    # tracing"): minted by the front door (or the queue, from the uid)
    # and carried on every trace span/instant this request emits, so
    # tools/fleet_trace.py can stitch one request's timeline across the
    # door and replica processes. Deterministic by construction — never
    # derived from the wall clock — and excluded from equality (it is
    # correlation metadata, not part of the admission record).
    trace_id: str | None = dataclasses.field(default=None, compare=False)
    # Per-request latency ledger (serving/ledger.py): the append-only
    # (cause, start, end) interval list whose causes partition the
    # request's wall lifetime. It travels WITH the request through
    # every state change — queue → slot → (preempt) → queue → slot →
    # finished — so attribution survives requeues and the finished
    # record carries the full decomposition. Mutable by design (the
    # frozen dataclass pins the admission record; the ledger is
    # telemetry riding along) and excluded from equality.
    ledger: LatencyLedger | None = dataclasses.field(
        default=None, compare=False, repr=False)

    def __post_init__(self):
        if self.ledger is None:
            object.__setattr__(self, "ledger",
                               LatencyLedger(self.arrival_t))


@dataclasses.dataclass
class ActiveSequence:
    """Host-side state of one occupied decode slot (or, after a
    preemption, of one requeued resumption awaiting a slot)."""

    request: Request
    slot: int
    tokens: list = dataclasses.field(default_factory=list)  # emitted ids
    # When the scheduler seated the request into its slot (perf_counter):
    # arrival→seated is the queueing span, seated→first token the prefill
    # span on the trace timeline (serving/engine.py). A re-seat after
    # preemption re-stamps it, so the TTFT decomposition
    # (queue_wait + prefill == TTFT) stays telescoping.
    seated_t: float | None = None
    first_token_t: float | None = None
    last_token_t: float | None = None
    # Chunked-prefill progress (paged engine): prefill tokens already
    # written to the KV pool. A seated sequence decodes only once
    # prefill_pos reaches the prefill length AND its first token landed;
    # until then it occupies its slot as "prefilling".
    prefill_pos: int = 0
    # Wall-time a live weight hot-swap barrier blocked this sequence's
    # decode between two of its tokens (serving/hotswap.py). Billed to
    # the engine-level swap_blocked_s stat and SUBTRACTED from the
    # request's TPOT: TPOT reports decode compute per token, and the
    # swap pause is deployment cost the engine attributes explicitly
    # rather than smearing over whichever requests were in flight.
    swap_pause_s: float = 0.0
    # Lossless preemption state: how many times this sequence was
    # evicted mid-flight to make room for a higher tier, and — when it
    # had already emitted tokens — the token prefix (prompt + emitted
    # minus the uncached last token) the next seat must re-prefill.
    # The re-prefill recomputes exactly the cache positions the
    # eviction freed, and the continuation samples the same
    # fold_in(rng, position) stream, so the final output is bitwise
    # identical to an uninterrupted run.
    preempts: int = 0
    resume_prefix: np.ndarray | None = None
    # Ledger token-attribution debt (serving/ledger.py): cache
    # positions freed by preemptions/crashes that the next prefill
    # chunks will write AGAIN. Each re-prefill chunk consumes this
    # before billing to 'prefill' — a request preempted mid-prefill
    # bills only the positions it had actually written as recompute;
    # the never-written tail of its prompt stays first-time 'prefill'
    # work. When every evicted request re-seats, the summed ledger
    # counter equals preempted_token_recompute +
    # tokens_recomputed_on_recovery; a resumption shed or expired
    # from the queue dies with its debt unconsumed (nothing was
    # recomputed, so nothing is billed).
    recompute_owed: int = 0
    # Prefix-cache state (serving/prefix_cache.py). kv_epoch stamps
    # WHICH weights wrote this seat's KV pages (the engine bumps its
    # epoch at every hot-swap barrier): a sequence whose pages predate
    # the serving weights must not index them into the trie at finish —
    # old-weight KV must never seed a new-epoch request.
    # prefix_hit_tokens is the resident prefix this seat aliased
    # instead of prefilling (0 = cold); re-stamped at every re-seat.
    kv_epoch: int = 0
    prefix_hit_tokens: int = 0
    # The portion of recompute_owed that was charged to the RECOVERY
    # counter (tokens_recomputed_on_recovery, billed up front by
    # Engine.recover()) rather than to preempted_token_recompute: a
    # prefix-cache hit that covers debt credits each counter back by
    # what it was actually charged. Maintained as recovery-first on
    # hits and clamped under recompute_owed when chunks genuinely
    # recompute (a recomputed position's charge legitimately stands).
    recovery_owed: int = 0

    @property
    def prefill_tokens(self) -> np.ndarray:
        """What prefill must write: the original prompt, or — resuming
        after a preemption — prompt + emitted tokens except the last
        (the last emitted token is never cached; it re-enters as the
        next decode step's incoming token, exactly as it would have
        uninterrupted)."""
        return (self.request.prompt if self.resume_prefix is None
                else self.resume_prefix)

    @property
    def prefilling(self) -> bool:
        """Seated but not yet decoding (paged engine's chunked prefill);
        always False on the legacy path, whose batch-1 prefill emits the
        first token before the sequence ever reaches the slot state."""
        return (self.prefill_pos < self.prefill_tokens.size
                or not self.tokens)

    @staticmethod
    def from_journal(req: Request, tokens, *, preempts: int = 0,
                     first_token_t: float | None = None,
                     last_token_t: float | None = None
                     ) -> "ActiveSequence":
        """Reconstruct a crash-interrupted sequence from its journaled
        state (serving/journal.py) as a queued resumption — the SAME
        shape :meth:`prepare_resume` leaves behind, so the re-seat path
        (re-prefill prompt + emitted-minus-last, continue the
        ``fold_in(rng, position)`` stream) needs no recovery-specific
        branch and the continued output is bitwise identical to the
        uninterrupted run. Tokens emitted after the journal's last
        durable flush are simply recomputed by the same induction.
        ``first_token_t``/``last_token_t`` are the journal's wall
        anchors mapped into the new process's clock: TTFT stays "met"
        across the restart and deadline attribution keeps working."""
        seq = ActiveSequence(
            request=req, slot=-1, tokens=[int(t) for t in tokens],
            first_token_t=first_token_t,
            last_token_t=last_token_t, preempts=int(preempts))
        if seq.tokens:
            seq.resume_prefix = np.concatenate([
                req.prompt, np.asarray(seq.tokens[:-1], np.int32)])
            # The recovery re-prefill rewrites exactly the positions
            # the crash lost — the same count Engine.recover() reports
            # as tokens_recomputed_on_recovery (recovery_owed tracks
            # that attribution so a prefix-cache hit covering the debt
            # credits the recovery counter, not the preemption one —
            # even when the journal also restored pre-crash preempts).
            seq.recompute_owed = req.prompt.size + len(seq.tokens) - 1
            seq.recovery_owed = seq.recompute_owed
        return seq

    def prepare_resume(self) -> None:
        """Preemption bookkeeping: snapshot the re-prefill prefix from
        the tokens emitted so far and rewind the prefill cursor. The
        snapshot is taken NOW (not derived lazily) because ``tokens``
        keeps growing after the re-seat — the prefill target must stay
        what was cached at eviction time."""
        if self.tokens:
            self.resume_prefix = np.concatenate([
                self.request.prompt,
                # graftlint: disable=hot-path-transfer -- emitted tokens are host ints by contract (note_token casts at landing); no device value involved
                np.asarray(self.tokens[:-1], np.int32)])
        # else: preempted mid-prefill — restart from the original prompt
        # (resume_prefix stays None; nothing was emitted, so nothing to
        # carry).
        self.prefill_pos = 0
        self.preempts += 1
        self.slot = -1

    def note_token(self, token: int, t: float) -> None:
        self.tokens.append(int(token))
        if self.first_token_t is None:
            self.first_token_t = t
        self.last_token_t = t

    def finish_reason(self, eos_id: int | None,
                      now: float | None = None) -> str | None:
        """None while the sequence should keep decoding.

        EOS and budget win over a deadline landing on the same token (a
        naturally-finished request is not a timeout); ``now`` enables the
        total-deadline check — callers without deadlines pass nothing.
        A deadline miss on a sequence that was ever preempted reports
        ``preempted_timeout``: its clock kept running while it sat
        requeued, so the miss belongs to preemption pressure, not to the
        request's own service time.
        """
        if eos_id is not None and self.tokens and self.tokens[-1] == eos_id:
            return FINISH_EOS
        if len(self.tokens) >= self.request.max_new_tokens:
            return FINISH_LENGTH
        timeout = (FINISH_PREEMPT_TIMEOUT if self.preempts
                   else FINISH_TIMEOUT)
        dl = self.request.deadline_t
        if now is not None and dl is not None and now >= dl:
            return timeout
        # TTFT deadline, mid-prefill: chunked prefill holds a slot for
        # ceil(prompt/chunk) iterations before the first token, so a
        # request can now miss its TTFT SLA while SEATED (impossible on
        # the legacy path, whose seat and first token share an
        # iteration). Past the deadline with no first token it will
        # never make its SLA — evict so the chunk lane and its pool
        # pages go to a request that still can. A first token landing on
        # the deadline tick wins (first_token_t set → not a timeout),
        # matching the EOS/length-beat-deadline rule above.
        tdl = self.request.ttft_deadline_t
        if (now is not None and tdl is not None and now >= tdl
                and self.first_token_t is None):
            return timeout
        return None


@dataclasses.dataclass(frozen=True)
class FinishedRequest:
    """A completed request with its per-request SLA measurements.

    A queue-side deadline eviction completes with zero tokens and no
    latency samples (``ttft_ms``/``first_token_t`` None): the request
    never produced a first token, so it contributes to the timeout
    counter, not to the TTFT percentiles. A shed or expired resumption
    (preempted, then dropped from the queue) DOES carry the tokens it
    had emitted before eviction.
    """

    uid: int
    prompt: np.ndarray
    tokens: np.ndarray        # int32 [n]; n >= 1 except queue evictions
    finish_reason: str        # FINISH_* above
    ttft_ms: float | None     # arrival → first emitted token
    tpot_ms: float | None     # mean inter-token ms; None for <2 tokens
    arrival_t: float          # perf_counter timestamps (fairness audits)
    first_token_t: float | None
    # Trace-timeline fields (None for queue-side evictions): the slot
    # the request decoded in and its last token's landing time — the
    # engine closes the slot track's decode span from these at eviction.
    last_token_t: float | None = None
    slot: int | None = None
    priority: int = 0         # SLO tier (per-tier SLA histograms)
    tenant: str = "default"
    # The request's latency ledger (closed by the engine at completion;
    # None for results redelivered verbatim from the journal — their
    # wall detail belongs to the process that served them).
    ledger: "object | None" = dataclasses.field(
        default=None, compare=False, repr=False)
    # The request's trace correlation id (see Request.trace_id): rides
    # into the done frame and the slowest-request views so an SLA
    # outlier can be looked up on the merged fleet timeline.
    trace_id: str | None = dataclasses.field(default=None, compare=False)

    @staticmethod
    def from_active(seq: ActiveSequence, reason: str,
                    slot: int | None = -1) -> "FinishedRequest":
        """``slot`` defaults to the sequence's own; queue-side evictions
        of a requeued resumption pass ``slot=None`` (it holds no slot,
        so its trace marks belong on the queue track)."""
        n = len(seq.tokens)
        tpot = None
        if n > 1:
            span_s = max(
                seq.last_token_t - seq.first_token_t - seq.swap_pause_s,
                0.0)
            tpot = span_s * 1e3 / (n - 1)
        # A deadline eviction can now land mid-prefill (chunked prefill
        # holds a slot across iterations): no first token, no TTFT
        # sample — same contract as a queue-side timeout.
        ttft = (None if seq.first_token_t is None
                else (seq.first_token_t - seq.request.arrival_t) * 1e3)
        return FinishedRequest(
            uid=seq.request.uid,
            prompt=seq.request.prompt,
            tokens=np.asarray(seq.tokens, np.int32),
            finish_reason=reason,
            ttft_ms=ttft,
            tpot_ms=tpot,
            arrival_t=seq.request.arrival_t,
            first_token_t=seq.first_token_t,
            last_token_t=seq.last_token_t,
            slot=seq.slot if slot == -1 else slot,
            priority=seq.request.priority,
            tenant=seq.request.tenant,
            ledger=seq.request.ledger,
            trace_id=seq.request.trace_id,
        )

    @staticmethod
    def rejected_in_queue(req: Request, reason: str) -> "FinishedRequest":
        """A request evicted from the queue (deadline expiry or a
        tier-aware shed) — it never reached a slot, so it carries no
        tokens and no latency samples."""
        return FinishedRequest(
            uid=req.uid,
            prompt=req.prompt,
            tokens=np.zeros((0,), np.int32),
            finish_reason=reason,
            ttft_ms=None,
            tpot_ms=None,
            arrival_t=req.arrival_t,
            first_token_t=None,
            priority=req.priority,
            tenant=req.tenant,
            ledger=req.ledger,
            trace_id=req.trace_id,
        )

    @staticmethod
    def timed_out_in_queue(req: Request) -> "FinishedRequest":
        """A request evicted from the queue past its deadline — it never
        reached a slot, so it carries no tokens and no latency samples."""
        return FinishedRequest.rejected_in_queue(req, FINISH_TIMEOUT)
