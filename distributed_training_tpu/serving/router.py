"""Network front door, router half: cache-aware multi-replica routing.

Puts N engine replicas (separate processes on one host, each one a
:class:`~distributed_training_tpu.serving.frontend.ServingFrontend` —
the engines themselves are unchanged) behind a single HTTP front door.
Each ``POST /generate`` is routed to the replica whose radix prefix
trie holds the request's longest resident prefix (SGLang-style
cache-aware routing: the replica answers a cheap read-only
``POST /probe``), falling back to the least ledger ``queue_wait`` p95
when no replica holds any of the prompt. The policy is deterministic:
ties break to the lowest replica index, so the same probe answers
always produce the same route.

Counters (``router_snapshot``, scraped at ``GET /metrics`` and
``/router/stats`` and merged into the serve_net SLA row):
``router_requests_routed`` / ``router_prefix_routed`` /
``router_fallback_routed`` plus per-replica routed/error counts — the
bench_compare zero-drift gate holds them at 0 on single-engine rows.

**Zero-downtime rolling deploys** ride the existing drain + hot-swap
machinery, one replica at a time: take it out of rotation → ``POST
/admin/drain`` (admission closes; accepted work finishes) → wait for
phase ``drained`` → ``POST /admin/deploy`` (the replica's serve loop
arms + applies the swap at the empty-engine boundary) → ``POST
/admin/reopen`` → back into rotation. Requests never see the draining
replica (it leaves rotation first), so a mid-load deploy completes
with zero failed and zero duplicated requests — the CI chaos drill.

Scrape-safety: the front door's handler threads route, proxy bytes,
and read counters — they never touch an engine, a device, or a trie
(the graftlint scrape-safety rule covers these handlers and the
``router_snapshot`` provider).
"""

from __future__ import annotations

import contextlib
import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from distributed_training_tpu.serving.httpbody import (
    NoBodyLength,
    read_body,
)
from distributed_training_tpu.serving.ledger import (
    CAUSE_FAILOVER_RESUME,
    CAUSE_RELAY,
    CAUSE_RETRY_BACKOFF,
    CAUSE_ROUTE,
    FLEET_CAUSES,
    FLEET_SKEW_SLACK_MS,
    LatencyLedger,
)

# Phases a request must never be routed to: admission is closed (or
# not open yet). "overloaded" stays routable — shedding is the
# replica's own tier-aware decision.
UNROUTABLE_PHASES = {"draining", "drained", "recovering"}

# Numeric encoding of the per-replica breaker state for the Prometheus
# gauge (text expositions carry numbers; the JSON snapshots keep the
# string). Ordered healthy → tripped so an alert threshold reads
# naturally (``state >= 2`` == open).
BREAKER_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}

# Cap on the door's slowest-proxied-requests view (``fleet_ledger_top``
# in ``fleet_snapshot``) — the fleet twin of the replica telemetry's
# ledger_top.
FLEET_TOP_N = 8


class HttpReplica:
    """One replica endpoint (a ServingFrontend, usually in another
    process). Thin stdlib-urllib client: probe, generate (streaming
    passthrough), admin, healthz."""

    def __init__(self, url: str, *, name: str | None = None,
                 timeout_s: float = 30.0):
        self.url = url.rstrip("/")
        self.name = name or self.url
        self.timeout_s = float(timeout_s)

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.url + path,
            data=json.dumps(payload, allow_nan=False).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    def probe(self, prompt: list[int] | None) -> dict:
        """The routing probe: resident-prefix tokens + queue-wait
        fallback signal + phase (Engine.probe_snapshot over HTTP)."""
        return self._post("/probe", {"prompt": prompt})

    def generate_raw(self, body: bytes,
                     headers: dict[str, str] | None = None):
        """Open a streaming /generate against this replica; returns the
        live HTTPResponse (SSE bytes relay through unparsed). ``headers``
        adds request headers on top of the JSON content type — the door
        injects ``X-Graft-Trace``/``X-Graft-Hop`` here so the replica's
        spans carry the fleet trace id."""
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        req = urllib.request.Request(
            self.url + "/generate", data=body, headers=hdrs)
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def admin(self, cmd: str) -> dict:
        return self._post(f"/admin/{cmd}", {})

    def healthz(self) -> dict:
        with urllib.request.urlopen(self.url + "/healthz",
                                    timeout=self.timeout_s) as resp:
            return json.loads(resp.read())

    # Read-only scrape helpers (the /fleet/* fan-out): plain GETs, no
    # admin verb, no POST — a federated scrape can never perturb the
    # replica it reads (the graftlint scrape-safety rule additionally
    # pins that a scrape error never trips the breaker).
    def scrape_text(self, path: str) -> str:
        with urllib.request.urlopen(self.url + path,
                                    timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    def scrape_json(self, path: str) -> dict:
        with urllib.request.urlopen(self.url + path,
                                    timeout=self.timeout_s) as resp:
            return json.loads(resp.read())


class Router:
    """Deterministic cache-aware routing policy over N replicas.

    ``policy``: ``"prefix"`` (the default — longest resident prefix,
    least-queue-wait fallback) or ``"round_robin"`` (the CI drill's
    baseline: prefix-blind rotation over in-rotation replicas).
    """

    def __init__(self, replicas: list, *, policy: str = "prefix",
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 5.0,
                 trace=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy not in ("prefix", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r} "
                             f"(have: prefix, round_robin)")
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        self.replicas = list(replicas)
        self.policy = policy
        # Optional TraceSession (observability/trace.py): breaker-skip
        # decisions land as instants on the router pid's trace so a
        # failover request's merged timeline shows WHY the dead replica
        # was never re-probed. None (the default) keeps every route
        # pass span-free.
        self.trace = trace
        self._lock = threading.Lock()
        self._in_rotation = [True] * len(self.replicas)
        self._rr_next = 0
        self.requests_routed = 0
        self.prefix_routed = 0
        self.fallback_routed = 0
        self.routed_by_replica = [0] * len(self.replicas)
        self.errors_by_replica = [0] * len(self.replicas)
        self.retries = 0
        self.deploys_completed = 0
        self.deploy_errors = 0
        # Per-replica circuit breaker: closed → open after
        # ``breaker_threshold`` CONSECUTIVE connection/5xx failures →
        # (cooldown elapses) half_open, ONE trial → closed on success,
        # straight back to open on failure. An open replica is skipped
        # before its probe, so a dead process costs the route pass
        # nothing — no probe timeout, no burned fallback slot.
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._brk_state = ["closed"] * len(self.replicas)
        self._brk_failures = [0] * len(self.replicas)
        self._brk_opened_t = [0.0] * len(self.replicas)
        self.breaker_opens = 0
        self.breaker_closes = 0
        self.breaker_reopens = 0   # half-open trial failed
        self.breaker_opens_by_replica = [0] * len(self.replicas)
        self.failover_resumes = 0  # mid-stream relays re-issued

    # -- rotation ------------------------------------------------------------
    def set_rotation(self, index: int, in_rotation: bool) -> None:
        with self._lock:
            self._in_rotation[index] = bool(in_rotation)

    def in_rotation(self) -> list[int]:
        with self._lock:
            return [i for i, ok in enumerate(self._in_rotation) if ok]

    # -- circuit breaker -----------------------------------------------------
    def _brk_open_locked(self, index: int) -> None:
        self._brk_state[index] = "open"
        self._brk_opened_t[index] = time.monotonic()
        self._brk_failures[index] = 0

    def note_replica_failure(self, index: int) -> None:
        """One connection/5xx failure against a replica (probe,
        connect, or a relay dying mid-stream). Consecutive failures
        open the breaker; a half-open trial failure re-opens it
        immediately (the single trial is spent)."""
        with self._lock:
            state = self._brk_state[index]
            if state == "half_open":
                self.breaker_reopens += 1
                self._brk_open_locked(index)
                return
            if state == "open":
                return  # already open; the cooldown clock keeps running
            self._brk_failures[index] += 1
            if self._brk_failures[index] >= self.breaker_threshold:
                self.breaker_opens += 1
                self.breaker_opens_by_replica[index] += 1
                self._brk_open_locked(index)

    def note_replica_success(self, index: int) -> None:
        """A completed interaction closes the breaker (the half-open
        trial succeeding is the canonical path) and resets the
        consecutive-failure count."""
        with self._lock:
            if self._brk_state[index] != "closed":
                self.breaker_closes += 1
                self._brk_state[index] = "closed"
            self._brk_failures[index] = 0

    def breaker_state(self, index: int) -> str:
        with self._lock:
            return self._brk_state[index]

    def note_failover_resume(self) -> None:
        """One mid-stream relay death turned into a resume re-issue
        (counted once per client request, not per retry)."""
        with self._lock:
            self.failover_resumes += 1

    def _brk_admit(self, candidates: list[int],
                   trace_id: str | None = None) -> tuple[list[int],
                                                         set[int]]:
        """Breaker gate for one route pass: open replicas whose
        cooldown has not elapsed are dropped WITHOUT a probe; expired
        ones transition to half_open and are admitted as trials (the
        caller orders them last). Returns (admitted, half_open set).
        Skipped replicas land as ``breaker_skip`` instants on the
        router trace (when tracing) so the merged fleet timeline shows
        the probe-free drop."""
        now = time.monotonic()
        admitted: list[int] = []
        trials: set[int] = set()
        skipped: list[int] = []
        with self._lock:
            for i in candidates:
                state = self._brk_state[i]
                if state == "open":
                    if now - self._brk_opened_t[i] < \
                            self.breaker_cooldown_s:
                        skipped.append(i)
                        continue
                    self._brk_state[i] = state = "half_open"
                if state == "half_open":
                    trials.add(i)
                admitted.append(i)
        if self.trace is not None:
            for i in skipped:
                self.trace.instant("breaker_skip", track="breaker_skip",
                                   trace=trace_id,
                                   replica=self.replicas[i].name)
        return admitted, trials

    # -- policy --------------------------------------------------------------
    def route(self, prompt: list[int] | None,
              trace_id: str | None = None) -> list[tuple[int, bool]]:
        """``(replica_index, by_prefix)`` pairs to try, best first —
        ``by_prefix`` marks candidates whose trie holds part of the
        prompt (so the winner's counter attribution is decided here,
        not by a second probe). Probes every in-rotation replica whose
        breaker admits it (open → skipped probe-free; half-open →
        probed, ordered last as the single trial); unreachable or
        unroutable (draining/recovering) ones are skipped.
        Deterministic: ties break to the lowest index. ``trace_id``
        tags the breaker-skip instants when the router is tracing."""
        candidates, trials = self._brk_admit(self.in_rotation(),
                                             trace_id=trace_id)
        if self.policy == "round_robin":
            if not candidates:
                return []
            solid = [i for i in candidates if i not in trials]
            if not solid:
                return [(i, False) for i in candidates]
            with self._lock:
                self._rr_next += 1
                k = self._rr_next % len(solid)
            return ([(i, False) for i in solid[k:] + solid[:k]]
                    + [(i, False) for i in candidates if i in trials])
        probes: list[tuple[int, dict]] = []
        for i in candidates:
            try:
                snap = self.replicas[i].probe(prompt)
            except (urllib.error.URLError, OSError, ValueError):
                with self._lock:
                    self.errors_by_replica[i] += 1
                self.note_replica_failure(i)
                continue
            if snap.get("phase") in UNROUTABLE_PHASES \
                    or snap.get("draining"):
                continue
            probes.append((i, snap))
        # Longest resident prefix wins outright; with no residency
        # anywhere, least queue-wait (then least occupancy, then lowest
        # index — all deterministic). Half-open trials sort strictly
        # after every closed-breaker candidate regardless of their
        # probe signals: a recovering replica gets ONE chance, never
        # priority.
        probes.sort(key=lambda p: (
            p[0] in trials,
            -int(p[1].get("hit_tokens", 0)),
            float(p[1].get("queue_wait_p95_ms", 0.0)),
            int(p[1].get("queue_depth", 0))
            + int(p[1].get("active_slots", 0)),
            p[0]))
        return [(i, int(s.get("hit_tokens", 0)) > 0) for i, s in probes]

    def note_routed(self, index: int, *, by_prefix: bool,
                    retried: bool = False) -> None:
        with self._lock:
            self.requests_routed += 1
            self.routed_by_replica[index] += 1
            if self.policy == "prefix":
                if by_prefix:
                    self.prefix_routed += 1
                else:
                    self.fallback_routed += 1
            if retried:
                self.retries += 1

    # -- observability -------------------------------------------------------
    def router_snapshot(self) -> dict[str, Any]:
        """Read-only counter view (scrape-safe: host ints under one
        lock) — the /router/stats payload, the front door's /metrics
        families, and the serve_net SLA-row merge all read this."""
        with self._lock:
            return {
                "policy": self.policy,
                "router_requests_routed": self.requests_routed,
                "router_prefix_routed": self.prefix_routed,
                "router_fallback_routed": self.fallback_routed,
                "router_retries": self.retries,
                "router_deploys_completed": self.deploys_completed,
                "router_deploy_errors": self.deploy_errors,
                # Fleet fault tolerance: deterministic breaker
                # transitions (opens/closes are schedule-driven under
                # seeded chaos; reopens count spent half-open trials)
                # and mid-stream failover re-issues.
                "router_breaker_opens": self.breaker_opens,
                "router_breaker_closes": self.breaker_closes,
                "router_breaker_reopens": self.breaker_reopens,
                "router_failover_resumes": self.failover_resumes,
                "replicas": [
                    {"name": self.replicas[i].name,
                     "in_rotation": self._in_rotation[i],
                     "requests_routed": self.routed_by_replica[i],
                     "probe_errors": self.errors_by_replica[i],
                     "breaker_state": self._brk_state[i],
                     "breaker_opens": self.breaker_opens_by_replica[i]}
                    for i in range(len(self.replicas))],
            }

    # -- rolling deploy ------------------------------------------------------
    def rolling_deploy(self, *, poll_s: float = 0.05,
                       timeout_s: float = 120.0) -> dict[str, Any]:
        """Drain → deploy → reopen each replica in turn (zero-downtime:
        the replica leaves rotation before its admission closes, so no
        request is ever routed into a drain). Returns a per-replica
        report; raises TimeoutError when a replica wedges mid-phase."""
        report = []
        for i, rep in enumerate(self.replicas):
            self.set_rotation(i, False)
            try:
                epoch0 = int(rep.healthz().get("weights_epoch", -1))
                rep.admin("drain")
                self._wait(rep, lambda h: h.get("phase") == "drained",
                           poll_s, timeout_s,
                           what=f"{rep.name}: drain")
                rep.admin("deploy")
                self._wait(rep,
                           lambda h: int(h.get("weights_epoch", -1))
                           > epoch0,
                           poll_s, timeout_s,
                           what=f"{rep.name}: deploy")
                rep.admin("reopen")
                self._wait(rep,
                           lambda h: h.get("phase") not in
                           UNROUTABLE_PHASES,
                           poll_s, timeout_s,
                           what=f"{rep.name}: reopen")
            except Exception:
                with self._lock:
                    self.deploy_errors += 1
                raise
            finally:
                # Back into rotation even on failure: a wedged deploy
                # must not silently halve capacity forever.
                self.set_rotation(i, True)
            with self._lock:
                self.deploys_completed += 1
            report.append({"replica": rep.name, "from_epoch": epoch0,
                           "to_epoch": int(
                               rep.healthz().get("weights_epoch", -1))})
        return {"deployed": report}

    @staticmethod
    def _wait(rep, pred, poll_s: float, timeout_s: float,
              what: str) -> None:
        t0 = time.monotonic()
        while True:
            try:
                if pred(rep.healthz()):
                    return
            except (urllib.error.URLError, OSError, ValueError):
                pass  # replica mid-restart: keep polling to timeout
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(
                    f"rolling deploy wedged waiting for {what} "
                    f"(> {timeout_s:.0f}s)")
            time.sleep(poll_s)


class RouterFrontDoor:
    """The router's own HTTP server: routes + proxies ``POST
    /generate`` byte-for-byte (SSE streams relay through live), serves
    the router counters, and exposes the rolling-deploy trigger.

    - ``POST /generate`` — route (probe fan-out) then proxy to the
      chosen replica; a replica that refuses (503 / connection error)
      falls through to the next candidate, so a drain race never fails
      a request. 502 only when every replica refused.
    - ``GET /router/stats`` — :meth:`Router.router_snapshot` JSON.
    - ``GET /metrics`` — the router counters in Prometheus text (plus
      the per-replica breaker gauges and the fleet-ledger counters).
    - ``GET /healthz`` — aggregate: front-door status + each replica's
      /healthz under its name.
    - ``GET /fleet/metrics`` — federated scrape: the door's own
      families + supervisor gauges + every reachable replica's
      ``/metrics`` exposition relabeled with ``replica="<name>"``.
      Breaker-open or unreachable replicas are NOT probed/blocked on —
      they surface as ``fleet_replica_stale{replica=...} 1``.
    - ``GET /fleet/vars`` — the JSON twin: door + supervisor snapshots
      + each replica's ``/vars`` (``{"stale": true}`` when skipped).
    - ``GET /fleet/replicas`` — one row per replica: rotation, breaker
      state, routing counters, supervisor restart counts.
    - ``POST /admin/rolling_deploy`` — start a background rolling
      deploy; poll ``/router/stats`` (``router_deploys_completed``)
      for completion.

    Every proxied request carries a fleet trace id (client-supplied
    ``X-Graft-Trace`` or minted ``req-<seq>`` from the door's own
    deterministic request sequence — NEVER wall clock), propagated to
    the replica as a request header, echoed back to the client as a
    response header, and stamped on the door's ``route``/``relay``/
    ``retry_backoff``/``failover_resume`` spans so
    ``tools/fleet_trace.py`` can merge the per-process files into one
    timeline. The door also keeps its own conserved
    :class:`~distributed_training_tpu.serving.ledger.LatencyLedger`
    per request and joins the replica's ledger from the ``done`` frame
    — the cross-hop conservation audit behind the
    ``fleet_ledger_*`` counters (zero-tolerance CI gate).
    """

    def __init__(self, router: Router, *, port: int = 0,
                 host: str = "127.0.0.1",
                 route_wait_s: float = 10.0,
                 failover_wait_s: float = 60.0,
                 chaos_hook=None, trace=None,
                 trace_path: str | None = None,
                 supervisor_snapshot=None):
        self.router = router
        self._route_wait_s = float(route_wait_s)
        self._failover_wait_s = float(failover_wait_s)
        # Fleet tracing: one TraceSession for the door process
        # (observability/trace.fleet_session). The router shares it
        # unless it was given its own — one wiring point for the CLIs.
        self._trace = trace
        self._trace_path = trace_path
        if trace is not None and router.trace is None:
            router.trace = trace
        # ``supervisor_snapshot``: zero-arg callable returning the
        # ReplicaSupervisor counter view, merged into /fleet/* when the
        # deployment runs under supervision (serve_net wires it).
        self._supervisor_snapshot = supervisor_snapshot
        # Fleet ledger accounting (see _fleet_account): conserved
        # router-side intervals per proxied request, joined with the
        # replica ledger from the done frame and audited zero-tolerance.
        self._fleet_lock = threading.Lock()
        self.fleet_ledger_requests = 0
        self.fleet_ledger_conservation_violations = 0
        self.fleet_ledger_violation_last = ""
        self.fleet_replica_ledger_joined = 0
        self.fleet_replica_ledger_absent = 0
        self._fleet_cause_ms = {c: 0.0 for c in FLEET_CAUSES}
        self._fleet_top: list[dict] = []
        # Chaos injection (tools/serve_net.py drills):
        # ``chaos_hook(request_seq, tokens_relayed, replica_index)``
        # fires after every relayed frame — the kill-replica-at-
        # request-N drill SIGKILLs the serving replica mid-stream from
        # exactly this callback.
        self._chaos_hook = chaos_hook
        self._seq_lock = threading.Lock()
        self._gen_seq = 0
        self._deploy_thread: threading.Thread | None = None
        self.proxy_errors = 0
        front = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:
                pass

            def do_GET(self) -> None:
                front._handle_get(self)

            def do_POST(self) -> None:
                front._handle_post(self)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host = self._server.server_address[0]
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="router-front-door", daemon=True)
        self._started = False
        self._closed = False

    def start(self) -> "RouterFrontDoor":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def stop(self) -> None:
        """Shut the front door down (idempotent). Named ``stop`` for
        the same lint-call-graph reason as ``ServingFrontend.stop``."""
        if self._closed:
            return
        self._closed = True
        if self._started:
            self._server.shutdown()
            self._thread.join(timeout=5.0)
        self._server.server_close()
        self._trace_checkpoint()

    def _trace_checkpoint(self) -> None:
        """Persist the door trace (atomic replace). The door is never a
        chaos target, so — unlike the replica frontend's per-stream
        checkpoints — one save at stop() suffices; the CLIs save again
        at exit for belt-and-braces."""
        if self._trace is not None and self._trace_path:
            self._trace.checkpoint(self._trace_path)

    def url(self, path: str = "/generate") -> str:
        return f"http://{self.host}:{self.port}{path}"

    # -- handlers ------------------------------------------------------------
    def _handle_get(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0]
        snap = self.router.router_snapshot()
        if path == "/router/stats":
            self._send(req, 200, "application/json",
                       json.dumps(snap, allow_nan=False) + "\n")
        elif path == "/metrics":
            self._send(req, 200, "text/plain; version=0.0.4; "
                       "charset=utf-8",
                       "\n".join(self._metrics_lines(snap)) + "\n")
        elif path == "/fleet/metrics":
            self._send(req, 200, "text/plain; version=0.0.4; "
                       "charset=utf-8", self._fleet_metrics_text(snap))
        elif path == "/fleet/vars":
            self._send(req, 200, "application/json",
                       json.dumps(self._fleet_vars(snap),
                                  allow_nan=False) + "\n")
        elif path == "/fleet/replicas":
            self._send(req, 200, "application/json",
                       json.dumps(self._fleet_replicas(snap),
                                  allow_nan=False) + "\n")
        elif path == "/healthz":
            payload = {"status": "ok", "policy": self.router.policy,
                       "replicas": {}}
            for rep in self.router.replicas:
                try:
                    payload["replicas"][rep.name] = rep.healthz()
                except (urllib.error.URLError, OSError, ValueError) as e:
                    payload["replicas"][rep.name] = {
                        "status": "unreachable",
                        "error": str(e)}
            self._send(req, 200, "application/json",
                       json.dumps(payload, allow_nan=False) + "\n")
        else:
            self._send(req, 404, "application/json", json.dumps(
                {"error": "not found",
                 "endpoints": ["/generate", "/router/stats", "/metrics",
                               "/healthz", "/fleet/metrics",
                               "/fleet/vars", "/fleet/replicas",
                               "/admin/rolling_deploy"]}) + "\n")

    # -- federated telemetry plane -------------------------------------------
    def fleet_snapshot(self) -> dict[str, Any]:
        """Read-only fleet-ledger counter view (host ints/floats under
        one lock) — the door's half of the /fleet/* surface and the
        serve_net SLA-row merge. A snapshot PROVIDER under the
        graftlint scrape-safety rule: it must never trip a breaker,
        kill a replica, or drive an engine."""
        with self._fleet_lock:
            return {
                "fleet_ledger_requests": self.fleet_ledger_requests,
                "fleet_ledger_conservation_violations":
                    self.fleet_ledger_conservation_violations,
                "fleet_ledger_violation_last":
                    self.fleet_ledger_violation_last,
                "fleet_replica_ledger_joined":
                    self.fleet_replica_ledger_joined,
                "fleet_replica_ledger_absent":
                    self.fleet_replica_ledger_absent,
                "fleet_cause_ms": dict(self._fleet_cause_ms),
                "fleet_ledger_top": [dict(e) for e in self._fleet_top],
            }

    def _metrics_lines(self, snap: dict) -> list[str]:
        """The door's own /metrics families: router counters, per-
        replica routing + breaker gauges, fleet-ledger counters."""
        lines: list[str] = []
        for k, v in snap.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f"# TYPE {k} counter")
                lines.append(f"{k} {v}")
        lines.append("# TYPE router_replica_requests_routed counter")
        lines.append("# TYPE router_replica_probe_errors counter")
        lines.append("# TYPE router_replica_breaker_state gauge")
        lines.append("# TYPE router_replica_breaker_opens counter")
        for r in snap["replicas"]:
            tag = f'{{replica="{r["name"]}"}}'
            lines.append(
                f"router_replica_requests_routed{tag} "
                f"{r['requests_routed']}")
            lines.append(f"router_replica_probe_errors{tag} "
                         f"{r['probe_errors']}")
            lines.append(
                f"router_replica_breaker_state{tag} "
                f"{BREAKER_STATE_CODES.get(r['breaker_state'], -1)}")
            lines.append(f"router_replica_breaker_opens{tag} "
                         f"{r['breaker_opens']}")
        fleet = self.fleet_snapshot()
        for k, v in fleet.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f"# TYPE {k} counter")
                lines.append(f"{k} {v}")
        lines.append("# TYPE fleet_ledger_cause_ms_total counter")
        for cause, ms in sorted(fleet["fleet_cause_ms"].items()):
            lines.append(
                f'fleet_ledger_cause_ms_total{{cause="{cause}"}} {ms:g}')
        return lines

    def _fleet_scrape(self, path: str) -> dict[str, Any]:
        """Fan one read-only GET out to every replica. Breaker-open
        replicas are NOT contacted — a federated scrape must never
        block on (or re-probe) a replica the proxy path already
        declared dead; they come back as ``{"stale": True}``, the
        deterministic staleness marker. Scrape errors also mark stale —
        and deliberately do NOT call ``note_replica_failure``: a scrape
        observes the fleet, it never trips a breaker (lint-enforced
        from the do_GET roots)."""
        out: dict[str, Any] = {}
        for i, rep in enumerate(self.router.replicas):
            if self.router.breaker_state(i) == "open":
                out[rep.name] = {"stale": True, "reason": "breaker_open"}
                continue
            try:
                out[rep.name] = {"stale": False,
                                 "body": rep.scrape_text(path)}
            except (urllib.error.URLError, OSError, ValueError) as e:
                out[rep.name] = {"stale": True,
                                 "reason": f"unreachable: {e}"}
        return out

    def _fleet_metrics_text(self, snap: dict) -> str:
        """The federated exposition: door families + supervisor gauges
        + every reachable replica's /metrics relabeled with
        ``replica="<name>"`` (TYPE/HELP once per family), + the
        per-replica staleness marker."""
        from distributed_training_tpu.observability.prometheus import (
            merge_labeled_expositions,
        )

        lines = self._metrics_lines(snap)
        sup = (self._supervisor_snapshot()
               if self._supervisor_snapshot is not None else None)
        if sup:
            for k in ("replica_restarts", "deaths_detected",
                      "wedged_kills", "kills_injected"):
                if k in sup:
                    lines.append(f"# TYPE supervisor_{k} counter")
                    lines.append(f"supervisor_{k} {sup[k]}")
        scraped = self._fleet_scrape("/metrics")
        lines.append("# TYPE fleet_replica_stale gauge")
        for name in sorted(scraped):
            stale = 1 if scraped[name]["stale"] else 0
            lines.append(f'fleet_replica_stale{{replica="{name}"}} '
                         f"{stale}")
        lines.extend(merge_labeled_expositions(
            [(f'replica="{name}"', entry["body"])
             for name, entry in sorted(scraped.items())
             if not entry["stale"]]))
        return "\n".join(lines) + "\n"

    def _fleet_vars(self, snap: dict) -> dict[str, Any]:
        """The JSON twin of /fleet/metrics: one document holding the
        door's router + fleet-ledger snapshots, the supervisor counter
        view, and each replica's /vars (stale marker when skipped)."""
        replicas: dict[str, Any] = {}
        for name, entry in self._fleet_scrape("/vars").items():
            if entry["stale"]:
                replicas[name] = {"stale": True,
                                  "reason": entry["reason"]}
            else:
                try:
                    replicas[name] = json.loads(entry["body"])
                except ValueError:
                    replicas[name] = {"stale": True,
                                      "reason": "unparseable /vars"}
        return {
            "router": snap,
            "fleet": self.fleet_snapshot(),
            "supervisor": (self._supervisor_snapshot()
                           if self._supervisor_snapshot is not None
                           else None),
            "replicas": replicas,
        }

    def _fleet_replicas(self, snap: dict) -> dict[str, Any]:
        """One row per replica: the router's rotation/breaker/routing
        view joined with the supervisor's restart accounting."""
        sup = (self._supervisor_snapshot()
               if self._supervisor_snapshot is not None else None)
        rows = []
        for i, r in enumerate(snap["replicas"]):
            row = dict(r)
            row["breaker_state_code"] = BREAKER_STATE_CODES.get(
                r["breaker_state"], -1)
            if sup is not None:
                restarts = sup.get("restarts_by_replica", [])
                gave_up = sup.get("gave_up", [])
                row["restarts"] = (restarts[i]
                                   if i < len(restarts) else None)
                row["gave_up"] = (gave_up[i]
                                  if i < len(gave_up) else None)
            rows.append(row)
        return {"replicas": rows}

    def _handle_post(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0]
        if path == "/admin/rolling_deploy":
            if self._deploy_thread is not None \
                    and self._deploy_thread.is_alive():
                self._send(req, 409, "application/json",
                           json.dumps({"error": "rolling deploy already "
                                       "in progress"}) + "\n")
                return
            self._deploy_thread = threading.Thread(
                target=self._run_deploy, name="rolling-deploy",
                daemon=True)
            self._deploy_thread.start()
            self._send(req, 202, "application/json",
                       json.dumps({"started": True}) + "\n")
            return
        if path != "/generate":
            self._send(req, 404, "application/json",
                       json.dumps({"error": "not found"}) + "\n")
            return
        try:
            raw = read_body(req.headers, req.rfile)
            body = json.loads(raw or b"{}")
            prompt = body.get("prompt")
            if prompt is None and body.get("text") is not None:
                prompt = [b for b in str(body["text"]).encode("utf-8")]
        except NoBodyLength:
            # 411 ONLY here: neither Content-Length nor chunked
            # framing (same contract as the replica frontend).
            self._send(req, 411, "application/json", json.dumps(
                {"error": "Content-Length or Transfer-Encoding: "
                          "chunked required"}) + "\n")
            return
        except (ValueError, OSError) as e:
            self._send(req, 400, "application/json",
                       json.dumps({"error": f"bad body: {e}"}) + "\n")
            return
        self._proxy_generate(req, raw, body, prompt)

    def _proxy_generate(self, req: BaseHTTPRequestHandler, raw: bytes,
                        body: dict, prompt) -> None:
        """Route, relay, and fail over. Candidate replicas are tried
        best-first; a refusal (503/conn error — e.g. a drain racing
        the probe) falls through to the next. The rotation can be
        momentarily empty mid-deploy, so an empty route re-polls
        briefly before giving up. A relay that dies MID-STREAM (the
        replica was SIGKILLed under it) re-issues against the next
        healthy replica with a resume cursor — the client keeps one
        socket and one seamless stream.

        Fleet observability rides the same loop: the request's trace
        id (client ``X-Graft-Trace`` or the minted ``req-<seq>`` —
        deterministic, the door's own request sequence, never wall
        clock) tags every door span and travels to each replica as a
        request header, with a monotonically increasing ``X-Graft-Hop``
        so the merge tool pairs each door-side ``hop.send`` with the
        replica-side ``hop.recv``. In parallel the door stamps its own
        conserved :class:`LatencyLedger` — ``route``, ``relay``
        (which CONTAINS the replica's lifetime), ``retry_backoff``,
        ``failover_resume`` — audited cross-hop in _fleet_account."""
        with self._seq_lock:
            self._gen_seq += 1
            seq = self._gen_seq
        client_trace = req.headers.get("X-Graft-Trace")
        tid = client_trace if client_trace else f"req-{seq:06d}"
        # Mutable relay state, shared across failover attempts: the
        # client headers go out once, the delivered-token cursor and
        # upstream uid survive a dead upstream. ``trace`` rides along
        # so _relay can echo the id on the client response headers and
        # capture the replica ledger off the terminal done frame.
        state = {"seq": seq, "uid": None, "delivered": 0,
                 "headers_sent": False, "done": False,
                 "client_gone": False, "trace": tid, "ledger": None}
        t0 = time.perf_counter()
        led = LatencyLedger(t0)
        trace = self._trace
        attempt = 0
        hops = 0
        resumed = False
        while True:
            r0 = time.perf_counter()
            order = self.router.route(prompt, trace_id=tid)
            r1 = time.perf_counter()
            # Post-death route passes bill to failover_resume — the
            # tail the dead replica's SIGKILL added to this request.
            cause = CAUSE_FAILOVER_RESUME if resumed else CAUSE_ROUTE
            led.stamp(cause, r1)
            if trace is not None:
                trace.complete(cause, r0, r1, track=cause, trace=tid,
                               seq=seq, candidates=len(order))
            for idx, by_prefix in order:
                rep = self.router.replicas[idx]
                send_raw = raw
                if resumed:
                    resume_body = dict(body)
                    resume_body["resume"] = {
                        "uid": state["uid"],
                        "delivered": state["delivered"]}
                    send_raw = json.dumps(
                        resume_body, allow_nan=False).encode()
                hops += 1
                h0 = time.perf_counter()
                if trace is not None:
                    # One half of the hop handshake: the replica stamps
                    # the matching ``hop.recv`` with the SAME
                    # (trace, hop) args — tools/fleet_trace.py pairs
                    # them to bound cross-file clock offsets.
                    trace.instant("hop.send", track="relay", t=h0,
                                  trace=tid, hop=hops, replica=rep.name,
                                  resume=resumed)
                try:
                    resp = rep.generate_raw(send_raw, headers={
                        "X-Graft-Trace": tid, "X-Graft-Hop": str(hops)})
                except urllib.error.HTTPError as e:
                    if e.code in (503, 429):
                        attempt += 1
                        continue  # draining/shedding: try the next
                    if e.code >= 500:
                        self.router.note_replica_failure(idx)
                    self.proxy_errors += 1
                    if state["headers_sent"]:
                        return  # mid-stream: nothing more we can send
                    self._send(req, e.code, "application/json",
                               e.read().decode("utf-8", "replace")
                               or json.dumps({"error": str(e)}) + "\n")
                    return
                except (urllib.error.URLError, OSError):
                    self.router.note_replica_failure(idx)
                    attempt += 1
                    continue
                self.router.note_routed(idx, by_prefix=by_prefix,
                                        retried=attempt > 0)
                state["replica"] = idx
                upstream_died = self._relay(req, resp, state)
                rel1 = time.perf_counter()
                # The relay span opens at h0 (the connect): the replica
                # admits the request while generate_raw blocks on the
                # response headers, so "relay CONTAINS the replica's
                # lifetime" holds and the cross-hop slack check in
                # _fleet_account is one-sided.
                led.stamp(CAUSE_RELAY, rel1)
                if trace is not None:
                    trace.complete("relay", h0, rel1, track="relay",
                                   trace=tid, hop=hops,
                                   replica=rep.name,
                                   died=bool(upstream_died))
                if state["client_gone"]:
                    return  # the replica's cancel/ack gate handles it
                if not upstream_died:
                    self.router.note_replica_success(idx)
                    led.seal(CAUSE_RELAY)
                    self._fleet_account(led, state)
                    return
                # Upstream died mid-stream: penalize its breaker and
                # re-issue with the resume cursor. The route pass is
                # re-run fresh — the dead replica's breaker is open
                # now, so it is skipped without burning anything.
                self.router.note_replica_failure(idx)
                if not resumed:
                    resumed = True
                    self.router.note_failover_resume()
                    if trace is not None:
                        trace.instant("failover_resume",
                                      track="failover_resume",
                                      trace=tid, replica=rep.name,
                                      delivered=state["delivered"])
                break  # back to the outer loop for a fresh route
            wait = (self._failover_wait_s if resumed
                    else self._route_wait_s)
            if time.perf_counter() - t0 > wait:
                self.proxy_errors += 1
                if not state["headers_sent"]:
                    self._send(req, 502, "application/json", json.dumps(
                        {"error": "no replica accepted the request"})
                        + "\n")
                return
            b0 = time.perf_counter()
            time.sleep(0.02)
            b1 = time.perf_counter()
            led.stamp(CAUSE_RETRY_BACKOFF, b1)
            if trace is not None:
                trace.complete("retry_backoff", b0, b1,
                               track="retry", trace=tid, seq=seq)

    def _fleet_account(self, led: LatencyLedger, state: dict) -> None:
        """The cross-hop conservation audit, run once per COMPLETED
        proxied request: the door's own intervals must tile the client
        wall time exactly (LatencyLedger.violations — EPSILON-exact by
        the telescoping-cursor construction), and the replica ledger
        joined from the done frame must fit inside the relay span(s)
        up to FLEET_SKEW_SLACK_MS (both are perf_counter DURATIONS on
        one host; the slack covers scheduling between the door's
        connect and the replica's admission stamp). Requests
        redelivered verbatim from a journal carry ``ledger: null`` —
        the replica-side check is skipped, total conservation still
        applies. Zero-tolerance: any violation bumps the CI-gated
        counter."""
        problems = led.violations()
        rep_led = state.get("ledger")
        if isinstance(rep_led, dict):
            relay_ms = led.total_s(CAUSE_RELAY) * 1e3
            rep_ms = float(rep_led.get("lifetime_ms", 0.0))
            if rep_ms > relay_ms + FLEET_SKEW_SLACK_MS:
                problems.append(
                    f"replica lifetime {rep_ms:.3f}ms exceeds relay "
                    f"total {relay_ms:.3f}ms + "
                    f"{FLEET_SKEW_SLACK_MS:.0f}ms slack")
            if not rep_led.get("conserved", True):
                problems.append("replica-side ledger not conserved")
        totals = led.totals_ms()
        entry = {
            "trace_id": state["trace"], "seq": state["seq"],
            "uid": state["uid"], "lifetime_ms": led.lifetime_ms,
            "causes_ms": totals,
            "replica_lifetime_ms": (rep_led.get("lifetime_ms")
                                    if isinstance(rep_led, dict)
                                    else None),
            "conserved": not problems,
        }
        with self._fleet_lock:
            self.fleet_ledger_requests += 1
            if isinstance(rep_led, dict):
                self.fleet_replica_ledger_joined += 1
            else:
                self.fleet_replica_ledger_absent += 1
            if problems:
                self.fleet_ledger_conservation_violations += 1
                self.fleet_ledger_violation_last = problems[0]
            for cause, ms in totals.items():
                self._fleet_cause_ms[cause] = \
                    self._fleet_cause_ms.get(cause, 0.0) + ms
            self._fleet_top.append(entry)
            self._fleet_top.sort(
                key=lambda e: (-e["lifetime_ms"], str(e["trace_id"])))
            del self._fleet_top[FLEET_TOP_N:]
        if self._trace is not None:
            self._trace.instant("fleet.audit", track="route",
                                trace=state["trace"],
                                conserved=not problems)

    def _relay(self, req: BaseHTTPRequestHandler, resp,
               state: dict) -> bool:
        """Relay one upstream response into the client socket,
        SSE-frame-aligned. Forwards only COMPLETE frames (a failover
        must splice at a frame boundary or the client's SSE parse
        breaks), tracks the resume cursor (upstream uid + tokens
        delivered + terminal ``done``), and fires the chaos hook after
        every forwarded frame. Returns True iff the upstream died
        before its stream finished (the failover trigger); client
        hangups set ``state['client_gone']`` instead.
        ``contextlib.closing`` releases the upstream socket on every
        exit path."""
        with contextlib.closing(resp):
            ctype = resp.headers.get("Content-Type", "application/json")
            streaming = ctype.startswith("text/event-stream")
            try:
                if not state["headers_sent"]:
                    req.send_response(resp.status)
                    req.send_header("Content-Type", ctype)
                    if state.get("trace") is not None:
                        # The fleet trace id the door minted (or passed
                        # through), echoed so the client can join its
                        # own logs to the merged timeline.
                        req.send_header("X-Graft-Trace",
                                        str(state["trace"]))
                    clen = resp.headers.get("Content-Length")
                    if clen is not None and not streaming:
                        req.send_header("Content-Length", clen)
                    else:
                        req.send_header("Connection", "close")
                    req.end_headers()
                    state["headers_sent"] = True
            except (BrokenPipeError, ConnectionResetError):
                state["client_gone"] = True
                return False
            if not streaming:
                # Unary JSON (stream=false or an error body): plain
                # byte relay, no resume framing to track.
                try:
                    while True:
                        chunk = resp.read1(65536)
                        if not chunk:
                            break
                        req.wfile.write(chunk)
                except (BrokenPipeError, ConnectionResetError):
                    state["client_gone"] = True
                except OSError:
                    return True
                return False
            buf = b""
            while True:
                try:
                    chunk = resp.read1(65536)
                except (OSError, http.client.HTTPException):
                    return not state["done"]
                if not chunk:
                    return not state["done"]
                buf += chunk
                while True:
                    cut = buf.find(b"\n\n")
                    if cut < 0:
                        break
                    frame, buf = buf[:cut + 2], buf[cut + 2:]
                    event, payload = _parse_sse_frame(frame)
                    if event == "tokens":
                        if state["uid"] is None:
                            state["uid"] = payload.get("uid")
                        state["delivered"] += len(
                            payload.get("tokens", ()))
                    elif event == "done":
                        if state["uid"] is None:
                            state["uid"] = payload.get("uid")
                        state["done"] = True
                        # The replica's conserved interval detail rides
                        # the terminal frame (null when the result was
                        # journal-redelivered) — _fleet_account joins
                        # it with the door's own ledger.
                        state["ledger"] = payload.get("ledger")
                    try:
                        req.wfile.write(frame)
                    except (BrokenPipeError, ConnectionResetError):
                        state["client_gone"] = True
                        return False
                    if self._chaos_hook is not None:
                        self._chaos_hook(state["seq"],
                                         state["delivered"],
                                         state.get("replica"))

    def _run_deploy(self) -> None:
        try:
            self.router.rolling_deploy()
        except Exception:
            pass  # counted in router.deploy_errors; surfaced on /stats

    @staticmethod
    def _send(req: BaseHTTPRequestHandler, code: int, ctype: str,
              body: str) -> None:
        data = body.encode("utf-8")
        try:
            req.send_response(code)
            req.send_header("Content-Type", ctype)
            req.send_header("Content-Length", str(len(data)))
            req.end_headers()
            req.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass


def _parse_sse_frame(frame: bytes) -> tuple[str | None, dict]:
    """Parse ONE complete SSE frame ("event: NAME\\ndata: {...}\\n\\n")
    into (event, payload). Unparseable frames (comments, keepalives)
    come back as (None, {}) and relay through untouched."""
    event, data = None, []
    for line in frame.decode("utf-8", "replace").split("\n"):
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            data.append(line[len("data: "):])
    if event is None or not data:
        return None, {}
    try:
        return event, json.loads("\n".join(data))
    except ValueError:
        return None, {}


# -- SSE client helpers (traffic.py client mode + tests) ---------------------
def sse_events(resp):
    """Parse a live SSE byte stream into ``(event, payload)`` pairs —
    the client half of the frontend's framing (event: NAME / data: one
    JSON object / blank line)."""
    event, data = None, []
    for raw in resp:
        line = raw.decode("utf-8").rstrip("\n")
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            data.append(line[len("data: "):])
        elif not line and (event is not None or data):
            yield event, json.loads("\n".join(data))
            event, data = None, []


def generate_over_http(url: str, payload: dict, *,
                       timeout_s: float = 60.0,
                       trace_id: str | None = None) -> dict:
    """One streamed /generate round-trip: POST, consume the SSE stream,
    return the terminal ``done`` payload with the streamed-token
    concatenation under ``streamed_tokens`` (the bitwise pin compares
    both against the batch engine's output). ``trace_id`` rides out as
    ``X-Graft-Trace``; whatever the server echoed back on its response
    header comes back under ``trace_header`` — the client half of the
    trace round-trip check (tools/traffic.py client mode)."""
    headers = {"Content-Type": "application/json"}
    if trace_id is not None:
        headers["X-Graft-Trace"] = trace_id
    req = urllib.request.Request(
        url, data=json.dumps(payload, allow_nan=False).encode(),
        headers=headers)
    streamed: list[int] = []
    done: dict | None = None
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        trace_header = resp.headers.get("X-Graft-Trace")
        ctype = resp.headers.get("Content-Type", "")
        if not ctype.startswith("text/event-stream"):
            done = json.loads(resp.read())
        else:
            for event, data in sse_events(resp):
                if event == "tokens":
                    streamed.extend(data["tokens"])
                elif event == "done":
                    done = data
    if done is None:
        raise RuntimeError(f"stream from {url} ended without a "
                           f"'done' event")
    done["streamed_tokens"] = streamed
    done["trace_header"] = trace_header
    return done
