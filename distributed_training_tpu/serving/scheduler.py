"""Slot scheduler: iteration-level (continuous) batching over fixed slots.

Orca's scheduling insight, restated for XLA: the decode step's shapes
must never change (a retrace costs seconds), so the batch is
``max_batch`` fixed SLOTS rather than a dynamic list of sequences. At
every iteration boundary the scheduler

- **admits**: pops queued requests FIFO into however many slots are free
  (each admission triggers one prefill that scatters into the freed
  slot's cache rows), and
- **evicts**: returns finished sequences (EOS emitted, or completion
  budget spent) to the caller and marks their slots free.

Mid-iteration the slot set is immutable — the decode step sees a boolean
active mask and per-slot cache write heads, nothing else. All state here
is host-side Python; no jax imports.

Speculative decoding is invisible to the scheduler: a slot may emit
several tokens per iteration (the engine's verify window,
``serving/speculative.py``), but membership still only changes at
boundaries, and :meth:`SlotScheduler.evict_finished` reads the same
``tokens``/EOS/budget state — a mid-window EOS is truncated by the
engine before it lands here, so ``tokens[-1]`` remains the finishing
token exactly as in one-token decode.
"""

from __future__ import annotations

import time

import numpy as np

from distributed_training_tpu.serving.request import (
    ActiveSequence,
    FinishedRequest,
    Request,
)


class SlotScheduler:
    """Fixed decode slots, FIFO refill, boundary eviction."""

    def __init__(self, num_slots: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = int(num_slots)
        self._slots: list[ActiveSequence | None] = [None] * self.num_slots

    # -- views ---------------------------------------------------------------
    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    def active(self) -> list[ActiveSequence]:
        """Occupied slots, slot-index order."""
        return [s for s in self._slots if s is not None]

    def active_mask(self) -> np.ndarray:
        """bool [num_slots] — the decode step's per-slot active mask."""
        return np.asarray([s is not None for s in self._slots], bool)

    def sequence(self, slot: int) -> ActiveSequence:
        seq = self._slots[slot]
        if seq is None:
            raise KeyError(f"slot {slot} is free")
        return seq

    # -- iteration boundaries ------------------------------------------------
    def admit(self, queue, can_seat=None) -> list[ActiveSequence]:
        """Fill free slots from ``queue`` in strict arrival order.

        Lowest free slot first — slot choice is cosmetic (slots are
        independent lanes), but a deterministic rule keeps batched runs
        reproducible. Returns the newly seated sequences; the engine
        prefills each one.

        ``can_seat`` (paged engine) is the page-aware admission gate: a
        predicate over the queue HEAD, consulted before each pop. When
        the head's worst-case page commitment does not fit the pool,
        admission stops — strictly FIFO, never skipping ahead to a
        smaller request, so a long-context request cannot starve behind
        a stream of short ones (the legacy ``max_len``-sum behavior,
        restated in pages).
        """
        seated: list[ActiveSequence] = []
        for slot in range(self.num_slots):
            if self._slots[slot] is not None:
                continue
            if can_seat is not None:
                head = queue.peek()
                if head is None or not can_seat(head):
                    break
            req: Request | None = queue.pop()
            if req is None:
                break
            # seated_t closes the request's queueing interval (arrival →
            # seat); the engine's trace emits it as the 'queued' span.
            seq = ActiveSequence(request=req, slot=slot,
                                 seated_t=time.perf_counter())
            self._slots[slot] = seq
            seated.append(seq)
        return seated

    def evict_finished(self, eos_id: int | None,
                       now: float | None = None) -> list[FinishedRequest]:
        """Free every slot whose sequence has finished; returns results.

        Called after tokens land (post-prefill and post-decode-step): a
        one-token request or an instant EOS finishes without ever joining
        a decode iteration. ``now`` additionally evicts slots past their
        total deadline (partial tokens returned) — and, chunked prefill,
        slots past their TTFT deadline with no first token yet — with
        finish reason ``timeout``: a slot is serving capacity, and a
        request that already missed its SLA must hand it to one that can
        still make its own.
        """
        done: list[FinishedRequest] = []
        for slot in range(self.num_slots):
            seq = self._slots[slot]
            if seq is None:
                continue
            reason = seq.finish_reason(eos_id, now)
            if reason is not None:
                done.append(FinishedRequest.from_active(seq, reason))
                self._slots[slot] = None
        return done
