"""Slot scheduler: iteration-level (continuous) batching over fixed slots.

Orca's scheduling insight, restated for XLA: the decode step's shapes
must never change (a retrace costs seconds), so the batch is
``max_batch`` fixed SLOTS rather than a dynamic list of sequences. At
every iteration boundary the scheduler

- **admits**: seats queue candidates (highest SLO tier first, weighted
  tenant-fair within a tier — :meth:`RequestQueue.next_candidate` owns
  that order) into however many slots are free, gated by the engine's
  page-commitment predicate,
- **preempts**: when a candidate outranks active work and cannot seat
  (no slot, reserved headroom, or no pages), the WORST active sequence
  of a strictly lower tier is evicted and requeued — losslessly: its
  emitted tokens ride back to the queue and are re-prefilled on the
  next seat, continuing the same ``fold_in(rng, position)`` stream, so
  the final output is bitwise identical to an uninterrupted run
  (vLLM-style preempt-and-recompute; docs/SERVING.md), and
- **evicts**: returns finished sequences (EOS emitted, completion
  budget spent, or deadline missed) to the caller and marks their
  slots free.

Mid-iteration the slot set is immutable — the decode step sees a boolean
active mask and per-slot cache write heads, nothing else. All state here
is host-side Python; no jax imports.

Speculative decoding is invisible to the scheduler: a slot may emit
several tokens per iteration (the engine's verify window,
``serving/speculative.py``), but membership still only changes at
boundaries, and :meth:`SlotScheduler.evict_finished` reads the same
``tokens``/EOS/budget state — a mid-window EOS is truncated by the
engine before it lands here, so ``tokens[-1]`` remains the finishing
token exactly as in one-token decode. It composes with preemption the
same way: a preempted slot's drafts simply never happen, and the
resumption drafts again from its (identical) token stream.
"""

from __future__ import annotations

import time

import numpy as np

from distributed_training_tpu.serving.ledger import (
    CAUSE_PREEMPT_REQUEUE,
    CAUSE_QUEUE_WAIT,
)
from distributed_training_tpu.serving.request import (
    ActiveSequence,
    FinishedRequest,
    Request,
)


class SlotScheduler:
    """Fixed decode slots; tier-aware refill + preemption, boundary
    eviction.

    ``reserved_slots`` holds that many slots back from non-top tiers
    (``priority > 0``): a best-effort request only seats while MORE than
    ``reserved_slots`` slots are free, so a high-tier arrival always
    finds headroom without even needing a preemption. Tier 0 ignores
    the reserve. ``preempt=False`` disables mid-flight eviction (tiers
    then only order the queue).
    """

    def __init__(self, num_slots: int, *, reserved_slots: int = 0,
                 preempt: bool = True):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if not 0 <= reserved_slots < num_slots:
            raise ValueError(
                f"reserved_slots must be in [0, num_slots-1], got "
                f"{reserved_slots} of {num_slots}")
        self.num_slots = int(num_slots)
        self.reserved_slots = int(reserved_slots)
        self.preempt = bool(preempt)
        self._slots: list[ActiveSequence | None] = [None] * self.num_slots

    # -- views ---------------------------------------------------------------
    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    def active(self) -> list[ActiveSequence]:
        """Occupied slots, slot-index order."""
        return [s for s in self._slots if s is not None]

    def active_mask(self) -> np.ndarray:
        """bool [num_slots] — the decode step's per-slot active mask."""
        return np.asarray([s is not None for s in self._slots], bool)

    def sequence(self, slot: int) -> ActiveSequence:
        seq = self._slots[slot]
        if seq is None:
            raise KeyError(f"slot {slot} is free")
        return seq

    def evict_uid(self, uid: int) -> ActiveSequence | None:
        """Clear and return the seated sequence with ``uid`` (the
        cancellation path — the caller finishes it with reason
        ``cancelled`` and the normal finish sweep frees its pages), or
        None when the uid holds no slot."""
        for slot, seq in enumerate(self._slots):
            if seq is not None and seq.request.uid == uid:
                self._slots[slot] = None
                return seq
        return None

    def tenant_active(self) -> dict[str, int]:
        """tenant -> seated-sequence count (the queue's quota input)."""
        counts: dict[str, int] = {}
        for s in self._slots:
            if s is not None:
                t = s.request.tenant
                counts[t] = counts.get(t, 0) + 1
        return counts

    # -- iteration boundaries ------------------------------------------------
    def _victim_slot(self, priority: int) -> int | None:
        """The slot to preempt for a ``priority`` candidate: the active
        sequence of the numerically LARGEST (worst) tier strictly below
        the candidate, newest (largest uid) first — the least sunk cost
        within the worst tier, and a deterministic rule either way.
        None when nothing outrankable is active."""
        best: int | None = None
        for slot, seq in enumerate(self._slots):
            if seq is None or seq.request.priority <= priority:
                continue
            if best is None or (
                    (seq.request.priority, seq.request.uid)
                    > (self._slots[best].request.priority,
                       self._slots[best].request.uid)):
                best = slot
        return best

    def admit(self, queue, can_seat=None, *, on_seat=None,
              on_preempt=None, preempt_helps=None, prefix_probe=None
              ) -> list[ActiveSequence]:
        """One admission pass; returns the newly seated sequences (the
        engine prefills each — resumptions re-prefill their carried
        prefix).

        ``can_seat`` is the engine's resource gate (page commitment +
        reserved-page headroom), consulted per candidate; ``on_seat``
        runs engine-side seat bookkeeping (commit pages, slot RNG);
        ``on_preempt`` runs eviction bookkeeping (free pages, counters)
        BEFORE the sequence is requeued. Candidate order is the queue's
        (tier-strict, tenant-fair). A resource-blocked candidate first
        tries to PREEMPT the worst strictly-lower-tier active sequence —
        but only when ``preempt_helps(cand, victims)`` (the engine's
        futility bound: could evicting EVERY strictly-lower-tier active
        ever free enough?) says yes, so a candidate too large for its
        preemptible pool cannot throw away best-effort progress for
        nothing. When nothing is (usefully) preemptible, admission
        STOPS — lower tiers never skip past a blocked higher tier (the
        anti-starvation / anti-priority-inversion rule), and within a
        (tier, tenant) lane order stays strictly FIFO.

        Every loop step either seats a candidate (queue shrinks) or
        preempts a strictly-lower-tier active (num_active shrinks, and
        the victim can only re-seat after this candidate), so the pass
        terminates; preemption cannot cycle because it is strictly
        rank-ordered. (A candidate vanishing between the queue's
        ``next_candidate`` and ``take`` — a producer-side tier-aware
        shed racing this pass — just re-polls.)

        ``prefix_probe`` threads through to ``queue.next_candidate``
        (cache-aware seat ordering): among equal-fairness tenant heads,
        the one with the larger resident prefix seats first.
        """
        seated: list[ActiveSequence] = []
        while True:
            cand = queue.next_candidate(self.tenant_active(),
                                        prefix_probe=prefix_probe)
            if cand is None:
                break
            req: Request = (cand.request
                            if isinstance(cand, ActiveSequence) else cand)
            free = [i for i, s in enumerate(self._slots) if s is None]
            slot_ok = bool(free) and (
                req.priority == 0 or len(free) > self.reserved_slots)
            if not slot_ok or (can_seat is not None
                              and not can_seat(cand)):
                victim = (self._victim_slot(req.priority)
                          if self.preempt else None)
                if victim is None:
                    break
                if preempt_helps is not None:
                    victims = [s for s in self._slots
                               if s is not None
                               and s.request.priority > req.priority]
                    if not preempt_helps(cand, victims):
                        break
                seq = self._slots[victim]
                self._slots[victim] = None
                if on_preempt is not None:
                    on_preempt(seq)
                seq.prepare_resume()
                queue.requeue(seq)
                continue
            if not queue.take(cand):
                continue  # candidate shed concurrently: re-poll
            slot = free[0]
            # seated_t closes (or re-opens, after a preemption) the
            # request's queueing interval; the engine's trace emits it
            # as the 'queued' span.
            now = time.perf_counter()
            if isinstance(cand, ActiveSequence):
                seq = cand
                seq.slot = slot
                seq.seated_t = now
            else:
                seq = ActiveSequence(request=cand, slot=slot,
                                     seated_t=now)
            # Ledger seat stamp (serving/ledger.py): the wait that just
            # ended is 'queue_wait' for a first seat and
            # 'preempt_requeue' for a resumption's re-seat (preemption
            # OR crash-recovery restore — both ride the resume path).
            if seq.request.ledger is not None:
                seq.request.ledger.stamp(
                    CAUSE_PREEMPT_REQUEUE if isinstance(
                        cand, ActiveSequence) else CAUSE_QUEUE_WAIT,
                    now)
            self._slots[slot] = seq
            if on_seat is not None:
                on_seat(seq)
            seated.append(seq)
        return seated

    def evict_finished(self, eos_id: int | None,
                       now: float | None = None) -> list[FinishedRequest]:
        """Free every slot whose sequence has finished; returns results.

        Called after tokens land (post-prefill and post-decode-step): a
        one-token request or an instant EOS finishes without ever joining
        a decode iteration. ``now`` additionally evicts slots past their
        total deadline (partial tokens returned) — and, chunked prefill,
        slots past their TTFT deadline with no first token yet — with
        finish reason ``timeout`` (``preempted_timeout`` when the
        sequence's clock ran down while it sat preempted): a slot is
        serving capacity, and a request that already missed its SLA must
        hand it to one that can still make its own.
        """
        done: list[FinishedRequest] = []
        for slot in range(self.num_slots):
            seq = self._slots[slot]
            if seq is None:
                continue
            reason = seq.finish_reason(eos_id, now)
            if reason is not None:
                done.append(FinishedRequest.from_active(seq, reason))
                self._slots[slot] = None
        return done
