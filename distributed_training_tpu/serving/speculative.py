"""Speculative decoding: draft-and-verify over the slot scheduler.

Leviathan et al. 2023 ("Fast Inference from Transformers via Speculative
Decoding") restated for this engine's invariants: a cheap **drafter**
proposes up to ``spec_k`` tokens per decode slot per iteration, and the
target model verifies all ``spec_k + 1`` positions in ONE dispatch — the
decode step generalized from a ``[max_batch, 1]`` batch to a fixed-width
``[max_batch, spec_k + 1]`` verify window (``serving/engine.py``). When
the drafter is right, one target dispatch lands several tokens; when it
is wrong, the iteration degrades to exactly the non-speculative step
(one token), never worse than one token per dispatch.

**Acceptance is lossless by construction — the engine's own twist.**
The textbook rejection-sampling correction (accept draft x with
probability ``min(1, p(x)/q(x))``, resample the residual on reject)
preserves the output *distribution* in aggregate. This engine pins a
stronger contract: sampling RNG is already a pure function of the
request and position (``fold_in(fold_in(seed, uid), position)``), so the
verify window simply computes, at every position ``i``, the token the
sequential decode loop *would have sampled there* —
``t_i = sample(fold_in(rng, pos_i), target_logits_i)`` — and accepts
draft position ``i`` iff every draft up to it matched the target stream
(``d_1..d_i == t_0..t_{i-1}``). Accepted prefixes emit the **target's
own samples** ``t_0..t_a`` (the last one is the free correction/bonus
token: its prefix is fully verified, so it is always emitted). Every
emitted token is therefore bitwise identical to the sequential path —
greedy (argmax) and sampled alike — which implies distribution-identity
and makes the round-8 bitwise oracle extend unchanged: drafts only
decide how many positions one dispatch computes, never what any of them
is. This is the rejection-sampling correction degenerated to a
deterministic proposal with the target's RNG stream pinned: acceptance
probability collapses to an exact token match and the residual
resample IS the target sample the window already drew.

**Static shapes.** The window width is a compile-time constant
(``spec_k + 1``); per-slot accept length is an argmax over a mismatch
mask inside the compiled step (first mismatching draft position, with a
sentinel column so an all-match window accepts ``k``); rows past a
slot's useful draft count (budget clamp, short proposals, inactive
lanes) are validity-masked, never shape changes. Rollback of the
rejected suffix is host-side bookkeeping only: the write head simply
does not advance past the accepted prefix, and the next window's
leading rows overwrite the stale K/V before any valid query can attend
it (every attended position is either verified history or written by
the current window's own valid rows — see docs/SERVING.md for the
induction).

Two drafter backends behind one protocol:

- :class:`NGramDrafter` (default) — prompt-lookup / self-speculation
  (Saxena-style): match the context's longest recent suffix n-gram
  earlier in the context and propose the tokens that followed it. Zero
  extra parameters, zero device work, no new compiled program; shines
  on repetitive continuations (code, extraction, cycles).
- :class:`GPTDrafter` — a small GPT draft model proposing greedily over
  a fixed right-aligned token window via one jitted ``lax.scan``
  program (ONE compiled shape: ``k`` and the window width are static).
  Restorable from a checkpoint via ``inference/restore.py``; with
  ``mirror_target=True`` it self-drafts with the serving model's own
  weights and the engine's hot-swap barrier rolls its params snapshot
  too (``on_weights_swap``), so there is no stale-drafter window after
  a live weight swap.

Drafters are *proposal* machinery: a wrong, stale, or empty proposal
costs acceptance rate, never correctness.

**Latency attribution** (serving/ledger.py): per request, the verify
window bills to the ledger's ``decode`` cause (the window IS the decode
dispatch), the host accept/rewind bookkeeping after tokens land bills
to ``spec_rollback``, and the per-request draft economics ride the
deterministic ``spec_draft``/``spec_accept`` token counters — the
request-level split of the engine-global ``drafted_tokens``/
``accepted_tokens`` zero-drift pair.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

import numpy as np

_EMPTY = np.zeros((0,), np.int32)


@runtime_checkable
class Drafter(Protocol):
    """Per-slot draft proposer for the engine's verify window.

    Implementations must be deterministic pure functions of the
    context (plus their own params): the engine's drafted/accepted
    telemetry is gated zero-drift by ``tools/bench_compare.py`` on the
    strength of that determinism, and acceptance itself is pinned
    batch-composition-independent because proposals depend only on the
    slot's own token stream.
    """

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        """Up to ``k`` proposed continuation tokens (int32 [<=k]) for
        ``context`` (prompt + emitted tokens, host-side int32 [n]).
        Fewer (or zero) proposals shrink the window's valid width —
        cheaper than wrong guesses, never incorrect."""
        ...

    def on_weights_swap(self, params: Any, epoch: int) -> None:
        """Hot-swap barrier notification (engine thread, inside the
        swap barrier): the target model now serves ``params``. Drafters
        holding target-derived state must roll it here."""
        ...

    def compiled_programs(self) -> dict:
        """``{name: compiled-shape count}`` of any jit programs this
        drafter owns — merged into ``Engine.compiled_programs()`` so
        the recompile sanitizer pins the drafter's inventory too."""
        ...


class NGramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most
    recent earlier occurrence of the context's suffix n-gram.

    Backs off from ``max_ngram`` down to ``min_ngram``: the longest
    suffix with an earlier match wins; within one ``n``, the MOST
    RECENT match wins (recency tracks the current phrase).

    ``fallback_repeat`` (default on) pads short or empty lookups to the
    full ``k`` by repeating the last proposed (else last context)
    token. The verify window is fixed-width, so an empty draft row is
    compute the engine pays for while carrying no bet — a
    low-confidence guess strictly dominates it on throughput (token
    runs like ``15 15 15`` are common decode attractors), at the cost
    of diluting the ``spec_acceptance_rate`` *metric* with cheap
    guesses. Turn it off to read acceptance as a pure lookup-quality
    signal.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 fallback_repeat: bool = True):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.fallback_repeat = bool(fallback_repeat)

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        # graftlint: disable=hot-path-transfer -- context is host numpy by protocol (the engine's slot bookkeeping); input normalization only
        ctx = np.asarray(context, np.int32).reshape(-1)
        out = _EMPTY
        n_ctx = ctx.size
        for n in range(min(self.max_ngram, n_ctx - 1),
                       self.min_ngram - 1, -1):
            pat = ctx[n_ctx - n:]
            # Candidate starts: every EARLIER position whose n-gram
            # equals the suffix (the suffix's own start is excluded so
            # the proposal is a real continuation, not the suffix).
            # Vectorized — this runs per decoding slot per iteration,
            # and a python matching loop here measurably drags the
            # whole engine (the drafter must stay far cheaper than the
            # verify dispatch it feeds).
            grams = np.lib.stride_tricks.sliding_window_view(
                ctx, n)[: n_ctx - n]
            hits = np.flatnonzero((grams == pat).all(axis=1))
            if hits.size:
                s = int(hits[-1])  # most recent match wins
                out = ctx[s + n: s + n + k].astype(np.int32)
                break
        if self.fallback_repeat and out.size < k and n_ctx:
            last = out[-1] if out.size else ctx[-1]
            out = np.concatenate(
                [out, np.full((k - out.size,), last, np.int32)])
        return out

    def on_weights_swap(self, params: Any, epoch: int) -> None:
        pass  # context-only: nothing derived from the target weights

    def compiled_programs(self) -> dict:
        return {}  # host-side only


class GPTDrafter:
    """GPT draft model: greedy proposals over a fixed token window.

    One jitted program (the ``draft`` entry of the engine's compiled-
    program inventory), one shape: the context's last ``window`` tokens
    sit right-aligned in a pad-filled ``[window]`` buffer and a
    ``lax.scan`` of ``k`` steps re-runs the draft model's full forward
    on the rolling window, appending the argmax each step. Proposal
    positions are window-local (0..window-1) — an approximation the
    acceptance math is immune to (a mispositioned draft just gets
    rejected).

    ``model``/``params`` may be any :class:`TransformerLM` + matching
    tree — a separate small draft checkpoint restored via
    ``inference/restore.py::build_lm_and_restore``, or (the
    ``mirror_target=True`` default the engine wires for
    ``spec_drafter='gpt'``) the serving model itself, window-truncated:
    self-drafting spends a cheap short-window forward per draft token
    to win the per-dispatch overhead of the full-length verify. In
    mirror mode :meth:`on_weights_swap` re-points the params snapshot
    at the engine's freshly swapped tree inside the swap barrier, so a
    mid-speculation deploy leaves no stale-drafter window (pinned by
    tests/test_speculative.py).
    """

    def __init__(self, model: Any, params: Any, *, window: int = 16,
                 pad_id: int = 0, mirror_target: bool = False):
        import jax

        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window > int(model.max_len):
            raise ValueError(
                f"draft window {window} exceeds the draft model's "
                f"positional table (max_len={model.max_len})")
        self.model = model
        self.params = params
        self.window = int(window)
        self.pad_id = int(pad_id)
        self.mirror_target = bool(mirror_target)
        # k is static (the engine always asks for its fixed spec_k), so
        # the scan length is baked and the program holds one shape.
        self._propose = jax.jit(self._propose_impl, static_argnums=(2,))

    def _propose_impl(self, params, window_tokens, k: int):
        import jax
        import jax.numpy as jnp
        from jax import lax

        def step(win, _):
            logits = self.model.apply({"params": params}, win[None],
                                      train=False)
            nxt = jnp.argmax(
                logits[0, -1, :].astype(jnp.float32)).astype(jnp.int32)
            return jnp.concatenate([win[1:], nxt[None]]), nxt

        _, toks = lax.scan(step, window_tokens, None, length=k)
        return toks

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        import jax.numpy as jnp

        # graftlint: disable=hot-path-transfer -- context is host numpy by protocol; input normalization only
        ctx = np.asarray(context, np.int32).reshape(-1)[-self.window:]
        win = np.full((self.window,), self.pad_id, np.int32)
        win[self.window - ctx.size:] = ctx
        # graftlint: disable=hot-path-transfer -- the draft landing: proposals must reach the host to assemble the verify window (docs/SERVING.md "Speculative decoding")
        return np.asarray(self._propose(self.params, jnp.asarray(win),
                                        int(k)))

    def on_weights_swap(self, params: Any, epoch: int) -> None:
        """Roll the params snapshot at the engine's swap barrier when
        self-drafting (mirror mode): same shapes/dtypes (the barrier
        already validated the tree), so the draft program binds the new
        argument without a retrace — exactly the target step's
        contract. A separate draft model keeps its own weights."""
        if self.mirror_target:
            self.params = params

    def compiled_programs(self) -> dict:
        from distributed_training_tpu.observability.sanitizer import (
            jit_cache_size,
        )

        return {"draft": jit_cache_size(self._propose)}


def make_drafter(cfg, model: Any, params: Any):
    """Build ``ServeConfig.spec_drafter``'s backend for an engine.

    ``ngram`` needs nothing beyond the config; ``gpt`` self-drafts with
    the serving model's own weights (mirror mode — hot-swap keeps it
    fresh). A separate small draft model bypasses this factory:
    ``Engine(model, params, cfg, drafter=GPTDrafter(draft_model,
    draft_params, window=...))``.
    """
    if cfg.spec_drafter == "ngram":
        return NGramDrafter(max_ngram=cfg.spec_ngram)
    if cfg.spec_drafter == "gpt":
        return GPTDrafter(
            model, params,
            window=min(int(cfg.spec_draft_window), int(model.max_len)),
            pad_id=cfg.pad_id, mirror_target=True)
    raise ValueError(f"unknown spec_drafter {cfg.spec_drafter!r}")


def accept_counts(window_tokens: np.ndarray, targets: np.ndarray,
                  valid: np.ndarray) -> np.ndarray:
    """Host/numpy mirror of the compiled accept formulation (the test
    oracle for the device argmax-over-mismatch-mask): per batch row,
    the number of leading drafts (``window_tokens[:, 1:]``) that match
    the target stream (``targets[:, :-1]``) within the valid width.
    """
    mismatch = (window_tokens[:, 1:] != targets[:, :-1]) | ~valid[:, 1:]
    sentinel = np.ones((mismatch.shape[0], 1), bool)
    return np.argmax(np.concatenate([mismatch, sentinel], axis=1), axis=1)


def truncate_at_eos(tokens: np.ndarray, eos_id: int | None) -> np.ndarray:
    """Cut an accepted token run at its first EOS (inclusive): the
    sequential loop would have stopped there, so tokens past a
    mid-window EOS were never part of the sequential output."""
    if eos_id is None:
        return tokens
    hits = np.flatnonzero(tokens == eos_id)
    return tokens[: hits[0] + 1] if hits.size else tokens
