"""Replica supervision: restart-with-journal fleet fault tolerance.

The round-22 front door made the engine a multi-replica service; this
module adds the layer every production fleet assumes (vLLM/DistServe
deployments run under systemd/k8s equivalents): something that OWNS
the replica processes, notices when one dies or wedges, and brings it
back — with its ``--journal-dir``, so the round-17 recovery replay
runs before the port reopens and the replica rejoins the fleet with
every accepted request intact.

Detection is two-channel:

- **death** — ``proc.poll()`` (the waitpid channel) catches a SIGKILL
  or crash immediately; a run of consecutive failed ``/healthz``
  probes catches a process that is technically alive but no longer
  accepting connections.
- **wedge** — a replica whose HTTP plane answers but whose serve loop
  stopped advancing (deadlocked engine thread, hung dispatch). The
  frontend exports a per-pass ``serve_loop_heartbeat`` epoch on
  ``/healthz``; a reachable replica whose heartbeat is FROZEN for
  ``wedge_timeout_s`` is force-killed (SIGKILL — a wedged process
  ignores SIGTERM by definition) and restarted.

Restarts are bounded (``max_restarts`` per replica — a crash-looping
replica eventually stays down and the router's circuit breaker keeps
traffic off it) with bounded exponential backoff between consecutive
restarts of the SAME replica. Counters are deterministic functions of
the fault schedule: one injected SIGKILL is exactly one death, one
restart — the CI failover drill pins them bitwise across kill cycles.

The supervisor never touches an Engine, a device, or a trie: it holds
subprocess handles and talks HTTP. ``spawn_fn(index)`` returns a
handle exposing ``proc`` (a Popen), ``url``, ``name`` and ``stop()``;
the handle's constructor must block until the replica printed its
port line — which the serve_net replica prints only AFTER
``engine.recover()`` returned, so "recovery replays before the port
reopens" holds by construction.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable

# Consecutive failed /healthz probes on an ALIVE process before it is
# declared unreachable and force-restarted (one flaky probe must not
# bounce a healthy replica).
PROBE_FAILURE_THRESHOLD = 3


class ReplicaSupervisor:
    """Owns ``count`` replica processes spawned via ``spawn_fn``.

    >>> sup = ReplicaSupervisor(lambda i: ReplicaProc(i, args), 2)
    >>> sup.start()          # spawns all replicas, starts the monitor
    >>> sup.kill(0)          # chaos: SIGKILL; the monitor restarts it
    >>> sup.stop()           # stops monitoring AND the replicas

    ``on_restart(index, handle)`` runs after every successful restart
    (the serve_net wiring points the router's ``HttpReplica.url`` at
    the replacement port there). ``wedge_timeout_s=None`` disables the
    wedge detector.
    """

    def __init__(self, spawn_fn: Callable[[int], Any], count: int, *,
                 probe_interval_s: float = 0.25,
                 probe_timeout_s: float = 2.0,
                 max_restarts: int = 5,
                 backoff_base_s: float = 0.25,
                 backoff_max_s: float = 5.0,
                 wedge_timeout_s: float | None = None,
                 on_restart: Callable[[int, Any], None] | None = None,
                 trace=None):
        if count < 1:
            raise ValueError("supervisor needs at least one replica")
        # Optional TraceSession: death/wedge detections and restart
        # completions land as instants on the supervisor's own lane of
        # the door-process trace, so the merged fleet timeline shows
        # the supervision cause between a victim's last span and its
        # successor's first. None (default) keeps the monitor span-free.
        self.trace = trace
        self._spawn_fn = spawn_fn
        self._count = int(count)
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self.max_restarts = int(max_restarts)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._wedge_timeout_s = (None if wedge_timeout_s is None
                                 else float(wedge_timeout_s))
        self._on_restart = on_restart
        self.handles: list[Any] = []
        self._lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(
            target=self._monitor, name="replica-supervisor", daemon=True)
        # Deterministic fault accounting (the CI drill pins these
        # bitwise across independent kill cycles).
        self.replica_restarts = 0
        self.restarts_by_replica = [0] * self._count
        self.deaths_detected = 0
        self.wedged_kills = 0
        self.kills_injected = 0
        self.gave_up = [False] * self._count
        # Probe bookkeeping (per replica): consecutive failures, last
        # observed heartbeat epoch + when it last ADVANCED.
        self._probe_failures = [0] * self._count
        self._beat = [-1] * self._count
        self._beat_t = [0.0] * self._count

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        """Spawn every replica (sequential, index order — deterministic
        port/journal assignment) and start the monitor thread."""
        if self.handles:
            return self
        self.handles = [self._spawn_fn(i) for i in range(self._count)]
        now = time.monotonic()
        for i in range(self._count):
            self._beat_t[i] = now
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop monitoring, then stop the replicas (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        for h in self.handles:
            try:
                h.stop()
            except Exception:
                pass  # already dead is fine — that's the business here

    # -- chaos ---------------------------------------------------------------
    def kill(self, index: int) -> None:
        """SIGKILL a replica (the drill's fault injection handle). The
        monitor detects the death and restarts it like any crash."""
        with self._lock:
            self.kills_injected += 1
        self.handles[index].proc.kill()

    # -- observability -------------------------------------------------------
    def supervisor_snapshot(self) -> dict[str, Any]:
        """Read-only counter view (host ints under one lock) — merged
        into the serve_net SLA row and the drill's bitwise gate."""
        with self._lock:
            return {
                "replica_restarts": self.replica_restarts,
                "restarts_by_replica": list(self.restarts_by_replica),
                "deaths_detected": self.deaths_detected,
                "wedged_kills": self.wedged_kills,
                "kills_injected": self.kills_injected,
                "gave_up": list(self.gave_up),
            }

    # -- monitor thread ------------------------------------------------------
    def _monitor(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            for i in range(self._count):
                if self.gave_up[i]:
                    continue
                if self.handles[i].proc.poll() is not None:
                    with self._lock:
                        self.deaths_detected += 1
                    if self.trace is not None:
                        self.trace.instant(
                            "replica.death", track="supervisor",
                            replica=i,
                            pid=int(self.handles[i].proc.pid))
                    self._restart(i)
                    continue
                self._probe(i)
            time.sleep(self._probe_interval_s)

    def _probe(self, i: int) -> None:
        """One /healthz probe: liveness + the wedge detector's
        heartbeat-advance check."""
        h = self.handles[i]
        try:
            with urllib.request.urlopen(
                    h.url.rstrip("/") + "/healthz",
                    timeout=self._probe_timeout_s) as resp:
                payload = json.loads(resp.read())
        except Exception:
            self._probe_failures[i] += 1
            if self._probe_failures[i] >= PROBE_FAILURE_THRESHOLD:
                # Alive but unreachable: force the waitpid channel.
                with self._lock:
                    self.deaths_detected += 1
                h.proc.kill()
                h.proc.wait()
                self._restart(i)
            return
        self._probe_failures[i] = 0
        beat = int(payload.get("serve_loop_heartbeat", -1))
        now = time.monotonic()
        if beat != self._beat[i]:
            self._beat[i] = beat
            self._beat_t[i] = now
        elif (self._wedge_timeout_s is not None
              and now - self._beat_t[i] > self._wedge_timeout_s):
            # Reachable, answering, NOT progressing: wedged. SIGKILL
            # (a wedged serve loop won't run atexit anyway) + restart.
            with self._lock:
                self.wedged_kills += 1
            if self.trace is not None:
                self.trace.instant("replica.wedged", track="supervisor",
                                   replica=i, pid=int(h.proc.pid),
                                   frozen_beat=beat)
            h.proc.kill()
            h.proc.wait()
            self._restart(i)

    def _restart(self, i: int) -> None:
        """Restart replica ``i`` with bounded exponential backoff. The
        spawn blocks until the replacement printed its port line —
        i.e. until journal recovery replayed — so the router never
        reaches a half-recovered replica."""
        n = self.restarts_by_replica[i]
        if n >= self.max_restarts:
            with self._lock:
                self.gave_up[i] = True
            return
        if n > 0:
            time.sleep(min(self._backoff_base_s * (2 ** (n - 1)),
                           self._backoff_max_s))
        try:
            self.handles[i].stop()   # reap + release the old handle
        except Exception:
            pass
        handle = self._spawn_fn(i)
        self.handles[i] = handle
        with self._lock:
            self.restarts_by_replica[i] += 1
            self.replica_restarts += 1
        if self.trace is not None:
            self.trace.instant("replica.restarted", track="supervisor",
                               replica=i, pid=int(handle.proc.pid),
                               restarts=self.restarts_by_replica[i])
        self._probe_failures[i] = 0
        self._beat[i] = -1
        self._beat_t[i] = time.monotonic()
        if self._on_restart is not None:
            self._on_restart(i, handle)
