"""Telemetry time-series: a fixed-capacity, fixed-cadence sample ring.

The serving plane's existing instruments answer "what is happening right
now" (the live exporter scrapes `Engine.stats()`), "where did this
request's time go" (the latency ledger), and "what happened inside one
iteration" (traces). None of them can answer "what changed over the
last N iterations" — the question every SLO burn-rate alert and every
post-incident review starts from. This module is that history: the
engine appends one flat sample of its host-side counters and gauges at
a fixed **iteration-count** cadence (``ServeConfig.sample_every``), and
the ring answers windowed delta / rate / mean / quantile queries over
the retained tail.

Design constraints, in order:

- **Iteration cadence, never wall time.** Sampling at "every K
  iterations" makes the sample sequence — and therefore every alert
  decision derived from deterministic counters — a pure function of the
  (virtual-dt) schedule: two ``serve_bench --virtual-dt`` runs of the
  same scenario produce bitwise-identical sample indices and counter
  columns. A wall-clock cadence would make even the *number* of samples
  run-dependent. (Wall-derived columns — ledger ms totals, histogram
  bucket counts over wall latencies — ride along for operators but are
  not what the deterministic alert drill gates on.)
- **O(1) append, no allocation growth.** One list assignment per
  sample; the schema (field order) is pinned by the first append and
  every later sample is flattened into a plain ``list[float]``.
- **Bounded memory** (the flight recorder's contract): the ring holds
  ``capacity`` rows of ``len(fields)`` floats — with the engine's
  ~100-field sample and the default ``capacity=1024`` that is under a
  megabyte of host memory regardless of run length. Nothing in this
  module ever touches a device or the filesystem.

Windowed quantiles come from **histogram snapshot deltas**: the engine
samples each ``FixedHistogram``'s cumulative ``le`` bucket counts as
ordinary counter columns, so "p95 TTFT over the last W samples" is the
bucket-interpolated quantile of ``counts[t] - counts[t-W]`` — exactly
the Prometheus ``histogram_quantile(rate(...))`` idiom, computed from
the same fixed bounds.
"""

from __future__ import annotations

from typing import Any, Sequence

from distributed_training_tpu.observability.histogram import FixedHistogram

FORMAT_VERSION = 1

# How many newest samples a flight dump / incident bundle / scrape
# carries: covers the default slow alert window (60 samples) with
# margin while keeping dumps a quick read. The full retained ring is
# available via TelemetryRing.to_dict(last_n=None).
TIMESERIES_DUMP_SAMPLES = 64


def hist_fields(prefix: str, bounds: Sequence[float]) -> list[str]:
    """Column names for one histogram's cumulative bucket counts:
    ``<prefix>_le_00 .. _le_<n-1>`` (one per finite bound) plus
    ``<prefix>_le_inf`` — the order :meth:`FixedHistogram.cumulative`
    emits."""
    names = [f"{prefix}_le_{i:02d}" for i in range(len(bounds))]
    names.append(f"{prefix}_le_inf")
    return names


class TelemetryRing:
    """Fixed-capacity ring of flat float samples with windowed queries.

    ``record_sample`` is the ONLY mutator, called by the engine thread
    at the iteration-cadence boundary; every other method is a read
    (the ``/timeseries`` scrape path and the alert engine run on
    reads + one engine-thread evaluation — the scrape-safety rule
    treats ``record_sample`` as telemetry mutation).
    """

    def __init__(self, capacity: int, sample_every: int):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}")
        self.capacity = int(capacity)
        self.sample_every = int(sample_every)
        self._fields: tuple[str, ...] | None = None
        self._index: dict[str, int] = {}
        self._rows: list[list[float] | None] = [None] * self.capacity
        self._head = 0   # next write slot
        self._count = 0  # samples ever recorded

    # -- append (engine thread only) -----------------------------------------
    def record_sample(self, sample: dict[str, float]) -> None:
        """Append one sample. The first call pins the schema; later
        calls must carry the same keys (the engine builds every sample
        from one code path, so a mismatch is a programming error)."""
        if self._fields is None:
            self._fields = tuple(sample.keys())
            self._index = {k: i for i, k in enumerate(self._fields)}
        elif len(sample) != len(self._fields):
            raise ValueError(
                f"sample schema changed: {len(sample)} fields, "
                f"expected {len(self._fields)}")
        self._rows[self._head] = [float(sample[k]) for k in self._fields]
        self._head = (self._head + 1) % self.capacity
        self._count += 1

    # -- views ---------------------------------------------------------------
    def __len__(self) -> int:
        return min(self._count, self.capacity)

    @property
    def fields(self) -> tuple[str, ...]:
        return self._fields or ()

    @property
    def samples_recorded_total(self) -> int:
        return self._count

    def _row(self, back: int) -> list[float]:
        """Row ``back`` samples before the newest (0 = newest). ``back``
        must be < len(self)."""
        return self._rows[(self._head - 1 - back) % self.capacity]

    def value(self, field: str, back: int = 0) -> float:
        """``field`` of the sample ``back`` positions before the newest."""
        return self._row(back)[self._index[field]]

    def window(self, field: str, window: int) -> list[float]:
        """The last ``min(window, len)`` values of ``field``, oldest
        first."""
        n = min(int(window), len(self))
        i = self._index[field]
        return [self._row(back)[i] for back in range(n - 1, -1, -1)]

    def delta(self, field: str, window: int) -> float:
        """Counter increase over the last ``window`` samples: newest
        minus the value ``window`` samples earlier (clamped to the
        oldest retained sample). 0.0 with fewer than two samples."""
        n = len(self)
        if n < 2:
            return 0.0
        back = min(int(window), n - 1)
        return self.value(field) - self.value(field, back)

    def rate(self, field: str, window: int,
             denominator: str | None = None) -> float:
        """Windowed rate of a counter: its delta per ``denominator``
        delta (e.g. shed requests per submitted request), or per sample
        when no denominator is given. A non-positive denominator delta
        yields 0.0 — no events to take a fraction of."""
        num = self.delta(field, window)
        if denominator is None:
            back = min(int(window), max(len(self) - 1, 1))
            return num / back
        den = self.delta(denominator, window)
        return num / den if den > 0 else 0.0

    def mean(self, field: str, window: int) -> float:
        """Mean of a gauge over the last ``window`` samples (clamped);
        0.0 when empty."""
        xs = self.window(field, window)
        return sum(xs) / len(xs) if xs else 0.0

    def window_quantile(self, prefix: str, bounds: Sequence[float],
                        q: float, window: int) -> float:
        """Bucket-interpolated quantile of the observations that landed
        in the last ``window`` samples, from the cumulative histogram
        columns ``hist_fields(prefix, bounds)``. 0.0 when the window saw
        no observations (an empty window cannot burn an SLO)."""
        names = hist_fields(prefix, bounds)
        cum = [self.delta(f, window) for f in names]
        hist = FixedHistogram(bounds)
        prev = 0.0
        for i, c in enumerate(cum):
            hist.counts[i] = max(int(round(c - prev)), 0)
            prev = c
        hist.total = sum(hist.counts)
        return hist.quantile(q) if hist.total else 0.0

    def to_dict(self, last_n: int | None = None) -> dict[str, Any]:
        """JSON view for dumps and the ``/timeseries`` endpoint: the
        schema, cadence, bound, and the newest ``last_n`` samples
        (oldest first; all retained samples when None). Read-only — a
        scrape copies, it never mutates."""
        n = len(self) if last_n is None else min(int(last_n), len(self))
        return {
            "format_version": FORMAT_VERSION,
            "capacity": self.capacity,
            "sample_every": self.sample_every,
            "samples_recorded_total": self._count,
            "fields": list(self.fields),
            "samples": [list(self._row(back))
                        for back in range(n - 1, -1, -1)],
        }
