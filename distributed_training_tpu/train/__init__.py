from distributed_training_tpu.train.precision import (  # noqa: F401
    LossScaleState,
    Policy,
    all_finite,
)
from distributed_training_tpu.train.optim import make_optimizer, make_schedule  # noqa: F401
from distributed_training_tpu.train.step import (  # noqa: F401
    cross_entropy_loss,
    make_eval_step,
    make_shard_map_train_step,
    make_train_step,
)
from distributed_training_tpu.train.train_state import (  # noqa: F401
    TrainState,
    init_train_state,
)
from distributed_training_tpu.train.trainer import Trainer  # noqa: F401
