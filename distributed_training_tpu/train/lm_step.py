"""Sequence-parallel LM train step (context parallelism over the mesh).

The long-context training path the reference lacks entirely (SURVEY.md §5
"Long-context / sequence parallelism": absent — no attention model, no
sequence dimension). Design:

- the token batch [B, T] is sharded over BOTH mesh axes: ``data`` on the
  batch dim and ``sequence`` on the time dim, so a sequence 8× longer than
  one chip's HBM budget trains by adding devices to the ``sequence`` axis;
- the step is a ``shard_map`` over the mesh: each device runs the model on
  its [B/dp, T/sp] activation shard, with ring attention rotating K/V blocks
  via ``lax.ppermute`` (see ``parallel/ring_attention.py``) — the only
  communication the sequence axis needs;
- every device computes grads for the full (replicated) parameter set from
  its local tokens; the true gradient of the global mean loss is the mean of
  shard grads over ``(data, sequence)`` — one fused ``lax.pmean``, the
  direct generalization of DDP's all-reduce to context parallelism;
- global token positions come from ``lax.axis_index('sequence')`` so learned
  positional embeddings and causal masks are exact across shards.

Next-token targets are produced host-side (``targets[t] = tokens[t+1]``)
*before* sharding, so the shift crosses shard boundaries correctly without
any halo exchange.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.runtime.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQUENCE,
)
from distributed_training_tpu.train.precision import commit_gradients
from distributed_training_tpu.train.train_state import TrainState
from distributed_training_tpu.utils.compat import axis_size, shard_map

_GRAD_AXES = (AXIS_DATA, AXIS_SEQUENCE)

SP_BATCH_SPEC = {"tokens": P(AXIS_DATA, AXIS_SEQUENCE),
                 "targets": P(AXIS_DATA, AXIS_SEQUENCE)}


def _sp_axis_names(mesh: Mesh):
    """shard_map manual axes for the sequence strategy: partial-manual over
    (data, sequence) only when a model or expert axis is actually in play —
    full-manual is semantically identical when every non-manual axis is
    size 1, and it keeps the plain SP path working on jax versions without
    axis_names. With ``model`` > 1 the megatron psums, and with ``expert``
    > 1 the MoE all-to-alls, are inserted by GSPMD inside the shards."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return ((AXIS_DATA, AXIS_SEQUENCE)
            if shape.get("model", 1) > 1 or shape.get("expert", 1) > 1
            else None)


def _global_positions(t_local: int):
    """Global token positions of this shard's [*, t_local] slice (the
    sequence axis must be bound)."""
    seq_idx = lax.axis_index(AXIS_SEQUENCE)
    return (seq_idx * t_local + jnp.arange(t_local))[None, :]


def model_logits_dtype(model):
    """Head compute dtype of ``model`` (fp32 when absent/None) — the single
    resolver for every step/eval builder, so a bf16-logits model gets the
    same CE math on the chunked, unchunked, train, and eval paths."""
    return getattr(model, "logits_dtype", jnp.float32)


def parse_logits_dtype(name: str):
    """The ONE config-string → dtype mapping for the logits-dtype surface
    (LMConfig, bench, profiler). Unknown spellings raise — a silent fp32
    fallback would let e.g. ``"bfloat16"`` pass while quietly dropping the
    measured +7% lever the user asked for."""
    table = {"fp32": jnp.float32, "bf16": jnp.bfloat16}
    if name not in table:
        raise ValueError(
            f"logits_dtype must be one of {sorted(table)}, got {name!r}")
    return table[name]


def _fused_softmax_ce(logits, targets):
    """Mean CE as ``logsumexp − label_logit``, fusion-friendly.

    ``optax.softmax_cross_entropy_with_integer_labels`` goes through
    ``log_softmax``, which materializes a full fp32 [B, T, vocab] log-prob
    tensor — at GPT-2-small B16 T1024 a 3.3 GB HBM round-trip the profiler
    shows as its own 7.6 ms convert/loop fusion
    (profiles/gpt_t1024_r4b.json, fusion.1592). This form reduces straight
    out of the (bf16 or fp32) logits: the max and sum-exp passes fuse with
    the upcast in registers, and only [B, T] rows land in HBM. Same math,
    fp32 accumulation; the backward rematerializes ``softmax − onehot``
    into the head-matmul fusions instead of reading saved log-probs.
    """
    return _fused_ce_rows(logits, targets).mean()


def _fused_ce_rows(logits, targets, with_correct: bool = False):
    """Per-row CE ([..., vocab] logits → [...] fp32), fusion-friendly.

    Max and gather read the logits in their STORED dtype (a gather's
    operand cannot fuse, so gathering from an fp32 cast would materialize
    the full cast tensor — the exact round-trip this form removes); only
    the sum-exp reduction sees the in-register fp32 upcast.

    ``with_correct=True`` also returns per-row top-1 correctness derived
    from values the CE already has in hand: the label is top-1 iff its
    logit equals the row max (``lab >= m``; it cannot exceed it). This is
    tie-inclusive top-1 — identical to ``argmax(logits) == target`` except
    when the label logit exactly ties a different index's max. Under fp32
    logits such ties are measure-zero (continuously distributed values
    collide with probability ~0); under bf16 logits — the default since
    round 6 — the 8-bit mantissa makes collisions merely RARE, not
    impossible, so the metric can overcount top-1 by the (tiny) tie rate.
    Either way it deletes the separate argmax reduction, a full extra HBM
    pass over the [B, T, vocab] tensor (measured 4.4 ms / +3.8% tok/s on
    the GPT-2-small B16 T1024 step, BASELINE.md round 4).
    """
    m = lax.stop_gradient(
        jnp.max(logits, axis=-1, keepdims=True)).astype(jnp.float32)
    lse = jnp.log(jnp.sum(
        jnp.exp(logits.astype(jnp.float32) - m), axis=-1)) + m[..., 0]
    lab = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    rows = lse - lab
    if not with_correct:
        return rows
    return rows, (lab >= m[..., 0]).astype(jnp.float32)


def _ce_rows_saved_probs(logits, targets, with_correct: bool = False):
    """CE rows via a custom VJP that saves bf16 softmax probabilities.

    The default backward rematerializes ``softmax(logits)`` into BOTH
    lm_head backward matmul fusions: each re-reads the stored logits and
    re-runs the exp on the VPU, which stalls the MXU pipeline (the dx
    matmul measures 56% of bf16 peak, profiles/gpt_t1024_r4e.json).
    Saving ``p = softmax(logits)`` once in bf16 at forward makes both
    backward matmuls clean consumers: ``dlogits = (p − onehot)·g`` fuses
    from a bf16 read with no transcendentals, and under fp32 logits the
    backward reads halve. The trade is one extra forward pass over the
    logits (read + exp + bf16 write). Loss/accuracy math is bit-identical
    to :func:`_fused_ce_rows`; only the *gradient* sees bf16-rounded
    probabilities (~2^-8 relative, the same rounding the measured
    bf16-logits lever applies to the logits themselves).

    Measured (B16 T1024 GPT-2-small, one v5e): fp32 logits 117.2k →
    119.4k tok/s; bf16 logits 125.2k → 123.7k (the backward reads are
    already bf16, so the extra forward pass isn't paid back) — use under
    fp32 logits only.
    """
    rows, correct = _saved_probs_vjp(logits, targets)
    return (rows, correct) if with_correct else rows


@jax.custom_vjp
def _saved_probs_vjp(lg, tg):
    rows, correct, _ = _saved_probs_fwd(lg, tg)
    return rows, correct


def _saved_probs_vjp_fwd(lg, tg):
    rows, correct, p = _saved_probs_fwd(lg, tg)
    # The empty array carries lg's dtype to bwd (residual leaves must be
    # arrays; a bare dtype object is not a valid pytree leaf here).
    return (rows, correct), (p, tg, jnp.zeros((0,), lg.dtype))


def _saved_probs_vjp_bwd(res, ct):
    import numpy as np

    p, tg, dt = res
    g = ct[0][..., None]  # rows cotangent; correct has no gradient
    onehot = (lax.broadcasted_iota(jnp.int32, p.shape, p.ndim - 1)
              == tg[..., None])
    dlg = jnp.where(onehot, p.astype(jnp.float32) - 1,
                    p.astype(jnp.float32)) * g
    return dlg.astype(dt.dtype), np.zeros(tg.shape, jax.dtypes.float0)


_saved_probs_vjp.defvjp(_saved_probs_vjp_fwd, _saved_probs_vjp_bwd)


def _saved_probs_fwd(lg, tg):
    # A normalized-p residual written in its own pass measures FASTER
    # (119.4k tok/s at the fp32-logits gate config) than the "free"
    # alternative of emitting bf16 exp(logits − max) as a second output
    # of the exp-sum reduce fusion (117.2k — no better than not saving
    # probs at all): the extra fusion output deoptimizes the vocab
    # reduction more than one extra elementwise pass costs.
    m = lax.stop_gradient(
        jnp.max(lg, axis=-1, keepdims=True)).astype(jnp.float32)
    ex = jnp.exp(lg.astype(jnp.float32) - m)
    s = jnp.sum(ex, axis=-1)
    lse = jnp.log(s) + m[..., 0]
    lab = jnp.take_along_axis(
        lg, tg[..., None], axis=-1)[..., 0].astype(jnp.float32)
    rows = lse - lab
    correct = (lab >= m[..., 0]).astype(jnp.float32)
    p = (ex / s[..., None]).astype(jnp.bfloat16)
    return rows, correct, p


def _ce_rows_and_correct(logits, targets, accuracy_metric: bool,
                         save_probs: bool):
    """Dispatch between the remat CE backward (default) and the
    saved-probs variant; returns ``(rows, correct-or-None)``."""
    impl = _ce_rows_saved_probs if save_probs else _fused_ce_rows
    if accuracy_metric:
        return impl(logits, targets, with_correct=True)
    return impl(logits, targets), None


def chunked_ce_and_accuracy(hidden, head_params, targets, chunk: int,
                            accuracy_metric: bool = True,
                            logits_dtype=jnp.float32):
    """CE + token accuracy WITHOUT materializing the [B, T, vocab] logits.

    For long contexts × large vocabs the logits tensor dominates memory
    (B8·T16384·V50304 fp32 = 26 GB — measured OOM on v5e, BASELINE.md):
    scan over time chunks, apply the lm_head to one [B, C, D] slice at a
    time, and reduce CE/accuracy to scalars. The body is
    ``jax.checkpoint``-ed so the backward also recomputes each chunk's
    logits instead of saving softmax residuals (which would re-create the
    full tensor). Math matches ``make_lm_head`` exactly: callers pass the
    model's ``logits_dtype`` so the per-chunk matmul runs in the same
    dtype the unchunked head would (the CE reduction is fp32 either way,
    :func:`_fused_ce_rows`).
    """
    b, t, d = hidden.shape
    if t % chunk:
        raise ValueError(f"ce_chunk {chunk} must divide sequence length {t}")
    n = t // chunk
    w = head_params["kernel"].astype(logits_dtype)
    bias = (head_params["bias"].astype(logits_dtype)
            if "bias" in head_params else None)
    hs = jnp.swapaxes(hidden.reshape(b, n, chunk, d), 0, 1)  # [n, B, C, D]
    ts = jnp.swapaxes(targets.reshape(b, n, chunk), 0, 1)    # [n, B, C]

    @jax.checkpoint
    def body(carry, xs):
        ce_sum, acc_sum = carry
        hc, tc = xs
        logits = hc.astype(logits_dtype) @ w
        if bias is not None:
            logits = logits + bias
        rows, correct = _ce_rows_and_correct(
            logits, tc, accuracy_metric, save_probs=False)
        acc = correct.sum() if accuracy_metric else jnp.float32(0)
        return (ce_sum + rows.sum(), acc_sum + acc), None

    (ce_sum, acc_sum), _ = lax.scan(
        body, (jnp.float32(0), jnp.float32(0)), (hs, ts))
    denom = jnp.float32(b * t)
    return ce_sum / denom, (acc_sum / denom if accuracy_metric else None)


def _lm_loss_and_grads(state: TrainState, tokens, targets, rng,
                       positions=None, ce_chunk: int | None = None,
                       accuracy_metric: bool = True,
                       logits_dtype=jnp.float32,
                       ce_save_probs: bool = False):
    """Scaled-CE (+ MoE aux) value-and-grad shared by every LM step variant.

    Returns ``(grads, ce, aux, accuracy)`` — CE and the MoE load-balancing
    term separately, so metrics can report perplexity as ``exp(CE)``
    (comparable to the CE-only eval loss) while the gradient flows through
    ``CE + aux``. ``ce_chunk`` computes the CE through
    :func:`chunked_ce_and_accuracy` (the model returns hidden states and
    the head applies per chunk). ``accuracy_metric=False`` returns
    ``accuracy=None`` and drops the metric key; since round 5 the metric
    derives from the CE's own max (see :func:`_fused_ce_rows`) so keeping
    it on is nearly free — the flag remains for exact parity with the
    reference's loss-only trainers.
    """
    def sown_aux(mutated):
        return sum(jax.tree.leaves(dict(mutated).get("aux_loss", {})),
                   jnp.float32(0))

    def loss_fn(params):
        rngs = dict(zip(("dropout", "gate"), jax.random.split(rng)))
        if ce_chunk:
            out = state.apply_fn(
                {"params": params}, tokens, positions=positions, train=True,
                rngs=rngs, mutable=["aux_loss"], return_hidden=True)
            if isinstance(out, tuple):  # flax apply with mutable collection
                hidden, mutated = out
                aux = sown_aux(mutated)
            else:  # PipelinedLM.apply_fn (no collections)
                hidden, aux = out, jnp.float32(0)
            ce, accuracy = chunked_ce_and_accuracy(
                hidden, params["lm_head"], targets, ce_chunk,
                accuracy_metric=accuracy_metric, logits_dtype=logits_dtype)
            return state.loss_scale.scale_loss(ce + aux), (ce, aux, accuracy)
        out = state.apply_fn(
            {"params": params}, tokens, positions=positions, train=True,
            rngs=rngs, mutable=["aux_loss"])
        if isinstance(out, tuple):  # flax apply with a mutable collection
            logits, mutated = out
            aux = sown_aux(mutated)
        else:  # PipelinedLM.apply_fn (no collections)
            logits, aux = out, jnp.float32(0)
        rows, correct = _ce_rows_and_correct(
            logits, targets, accuracy_metric, ce_save_probs)
        ce = rows.mean()
        accuracy = correct.mean() if accuracy_metric else None
        return state.loss_scale.scale_loss(ce + aux), (ce, aux, accuracy)

    grads, (ce, aux, accuracy) = jax.grad(loss_fn, has_aux=True)(state.params)
    return grads, ce, aux, accuracy


def _lm_metrics(new_state: TrainState, ce, aux, accuracy, finite,
                pmean_axes=None, grad_norm=None):
    """The LM metrics contract; ``pmean_axes`` averages shard-local values
    (the GSPMD path computes global values already). ``loss`` is the full
    objective (CE + MoE aux); ``perplexity`` is ``exp(CE)`` so it stays
    comparable to eval perplexity. ``accuracy=None`` (metrics_accuracy off)
    drops the key, ``grad_norm`` (the observability knob, already a global
    scalar) adds one — the dict is static per compile. Keep this dict the
    single source of the metric key set."""
    if pmean_axes:
        ce = lax.pmean(ce, pmean_axes)
        aux = lax.pmean(aux, pmean_axes)
        if accuracy is not None:
            accuracy = lax.pmean(accuracy, pmean_axes)
    out = {
        "loss": (ce + aux).astype(jnp.float32),
        "aux_loss": jnp.asarray(aux, jnp.float32),
        "accuracy": accuracy,
        "perplexity": jnp.exp(ce).astype(jnp.float32),
        "loss_scale": new_state.loss_scale.scale,
        "grads_finite": finite.astype(jnp.float32),
    }
    if accuracy is None:
        del out["accuracy"]
    if grad_norm is not None:
        out["grad_norm"] = grad_norm
    return out


def _lm_accum_grads(state: TrainState, batch, rng, accum: int,
                    mesh, ce_chunk: int | None, positions=None,
                    accuracy_metric: bool = True,
                    logits_dtype=jnp.float32,
                    ce_save_probs: bool = False):
    """Shared LM accumulation wrapper over ``accumulate_grads``: scan
    microbatches through fwd/bwd, average grads and metrics. ``mesh=None``
    runs shard-locally (the sequence step's partial-manual body);
    a real mesh adds the GSPMD microbatch sharding constraint.
    Returns ``(avg_grads, ce, aux, accuracy)``."""
    from distributed_training_tpu.train.step import accumulate_grads

    def micro_fn(params, mbatch, r, carry):
        g, ce, aux, acc = _lm_loss_and_grads(
            state.replace(params=params), mbatch["tokens"],
            mbatch["targets"], r, positions=positions, ce_chunk=ce_chunk,
            accuracy_metric=accuracy_metric, logits_dtype=logits_dtype,
            ce_save_probs=ce_save_probs)
        return g, carry, (ce, aux, acc)

    grads, _, (ces, auxs, accs) = accumulate_grads(
        state.params, {"tokens": batch["tokens"], "targets": batch["targets"]},
        rng, accum, mesh, micro_fn, init_carry=jnp.zeros(()))
    return (grads, ces.mean(), auxs.mean(),
            accs.mean() if accs is not None else None)


def _lm_grads_body(gstate: TrainState, batch, rng,
                   ce_chunk: int | None = None, accum: int = 1,
                   accuracy_metric: bool = True,
                   logits_dtype=jnp.float32,
                   ce_save_probs: bool = False,
                   tp_overlap: bool = False):
    """The manual (shard_map) half of the sequence-parallel step: compute
    the globally-averaged, unscaled gradient and the shard-averaged metric
    scalars. The optimizer commit deliberately happens OUTSIDE the manual
    region (see :func:`make_lm_train_step`) so ZeRO placements of the
    optimizer state stay in GSPMD-land; ``gstate`` is the train state with
    ``opt_state`` stripped — the body must not touch it.

    ``tp_overlap=True`` runs the forward/backward under the ring-overlapped
    megatron schedule (``parallel/collective_matmul.py``): params enter as
    model-axis shards, the decoder stack's activations are time-sharded over
    ``model``, and the per-layer collectives are ppermute rings. The loss is
    computed on this rank's time chunk (targets sliced below), so metrics
    and replicated-leaf grads additionally reduce over ``model``.
    """
    import contextlib

    tokens = batch["tokens"]
    targets = batch["targets"]
    positions = _global_positions(tokens.shape[1])
    # Decorrelate dropout across shards; no-op when the model has none.
    fold = (lax.axis_index(AXIS_SEQUENCE) * axis_size(AXIS_DATA)
            + lax.axis_index(AXIS_DATA))
    if tp_overlap:
        import flax.linen as nn

        from distributed_training_tpu.parallel.collective_matmul import (
            seq_overlap_interceptor,
        )

        tp = axis_size(AXIS_MODEL)
        fold = fold * tp + lax.axis_index(AXIS_MODEL)
        # The stack's logits come out time-sharded over model (the overlap
        # layout never re-gathers them); slice the targets to match. The
        # loss/accuracy means then cover this rank's chunk only — the
        # model-axis pmeans below complete them.
        t_loc = targets.shape[1] // tp
        targets = lax.dynamic_slice_in_dim(
            targets, lax.axis_index(AXIS_MODEL) * t_loc, t_loc, axis=1)
        ctx = nn.intercept_methods(seq_overlap_interceptor(AXIS_MODEL))
    else:
        ctx = contextlib.nullcontext()
    shard_rng = jax.random.fold_in(rng, fold)

    with ctx:
        if accum > 1:
            # Long-context accumulation: the local batch dim is the
            # EFFECTIVE micro×accum slice; the shared scan runs
            # shard-locally (mesh=None), then one collective + one update.
            # Equal-sized microbatches ⇒ mean of micro-means is the full
            # mean.
            grads, ce, aux, accuracy = _lm_accum_grads(
                gstate, {"tokens": tokens, "targets": targets}, shard_rng,
                accum, None, ce_chunk, positions=positions,
                accuracy_metric=accuracy_metric, logits_dtype=logits_dtype,
                ce_save_probs=ce_save_probs)
        else:
            grads, ce, aux, accuracy = _lm_loss_and_grads(
                gstate, tokens, targets, shard_rng, positions=positions,
                ce_chunk=ce_chunk, accuracy_metric=accuracy_metric,
                logits_dtype=logits_dtype, ce_save_probs=ce_save_probs)
    metric_axes = _GRAD_AXES
    if tp_overlap:
        from distributed_training_tpu.parallel.collective_matmul import (
            overlap_finalize_grads,
        )

        grads = overlap_finalize_grads(grads)
        metric_axes = _GRAD_AXES + (AXIS_MODEL,)
    grads = lax.pmean(grads, _GRAD_AXES)
    grads = gstate.loss_scale.unscale_grads(grads)
    ce = lax.pmean(ce, metric_axes)
    aux = lax.pmean(aux, metric_axes)
    if accuracy is not None:
        accuracy = lax.pmean(accuracy, metric_axes)
    return grads, (ce, aux, accuracy)


def make_lm_train_step(
    mesh: Mesh, *, model=None, max_len: int | None = None,
    donate: bool = True, ce_chunk: int | None = None,
    grad_accum_steps: int = 1, zero_stage: int = 0,
    accuracy_metric: bool = True, cpu_offload: bool = False,
    logits_dtype=None, ce_save_probs: bool = False,
    tp_overlap: bool = False, grad_norm_metric: bool = False,
) -> Callable:
    """Build the (data × sequence)-parallel jitted LM train step.

    Returns ``step(state, batch, rng) -> (state, metrics)`` where ``batch``
    is ``{'tokens': i32[B, T], 'targets': i32[B, T]}`` as *global* arrays,
    plus ``.state_shardings(state)`` / ``.batch_shardings`` attributes like
    the GSPMD steps.

    ``zero_stage`` composes DeepSpeed-style state sharding with the ring:
    the step is split in two — the shard_map computes the pmean'd gradient
    only (params and loss scale in, grads out; the optimizer state never
    enters the manual region), and ``commit_gradients`` runs under plain
    GSPMD where the ZeRO placement of Adam moments (sharded over the
    data × sequence replica group, ``parallel/sharding.zero_stage_axes``)
    propagates automatically: each device updates its slice of the moments
    and XLA all-gathers the updated params — reduce-scatter/all-gather
    ZeRO-1 semantics without hand-written collectives. Stage 3 additionally
    stores params sharded; the shard_map's replicated in_spec makes GSPMD
    all-gather them once at step entry (gather-on-use).

    ``model`` or ``max_len`` (exactly one): the positional-table bound.
    Global positions are traced values inside shard_map, so the model cannot
    bound-check them itself, and JAX gathers clamp out-of-range indices —
    an oversized T would silently reuse the last positional embedding. The
    global sequence length is checked here, at the only place it is
    statically known. Pass ``model=`` (the :class:`TransformerLM`) to derive
    the bound from the table itself; a hand-passed ``max_len`` that
    disagrees with the model's would re-open the silent-clamp gap.

    The shard_map is *partial-manual* over ``(data, sequence)`` only: every
    other mesh axis stays automatic, so a state placed by the megatron TP
    rule table (weights sharded over ``model``) composes transparently —
    inside each sequence shard, GSPMD inserts the row-parallel psums over
    ``model`` while the ring hops K/V blocks over ``sequence`` (TP shards
    heads, SP shards positions; the two are orthogonal dims of attention).

    ``tp_overlap=True`` selects the ring-overlapped megatron schedule
    instead: the shard_map goes FULL-manual (model included), params enter
    as rule-table shards, and the per-layer TP collectives become
    ``collective_matmul`` ppermute rings overlapped with the partial
    matmuls (see ``parallel/collective_matmul.py``). Composes with ZeRO
    stages (the commit still runs in GSPMD-land), gradient accumulation,
    and a sequence axis (the K/V ring over ``sequence`` and the matmul
    rings over ``model`` rotate orthogonally). MoE models are refused —
    expert dispatch needs the GSPMD expert axis the manual region unbinds.
    """
    from distributed_training_tpu.parallel.collective_matmul import (
        overlap_param_specs,
    )
    from distributed_training_tpu.parallel.tensor_parallel import (
        tp_state_shardings,
    )

    if (model is None) == (max_len is None):
        raise ValueError("pass exactly one of model= or max_len=")
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp_size = mesh_shape.get(AXIS_MODEL, 1)
    sp_size = mesh_shape.get(AXIS_SEQUENCE, 1)
    if tp_overlap:
        if model is None:
            raise ValueError(
                "tp_overlap needs model= (the overlap schedule derives its "
                "head/mlp shard shapes from the model config)")
        experts = model.moe_num_experts
        moe_on = (any(int(e) > 0 for e in experts)
                  if isinstance(experts, (tuple, list))
                  else int(experts) > 0)
        if moe_on:
            raise NotImplementedError(
                "tp_overlap does not compose with MoE models: expert "
                "dispatch relies on GSPMD's expert axis, which the "
                "full-manual overlap region unbinds — run MoE with the "
                "declarative TP schedule (tp_overlap=False)")
        if mesh_shape.get("expert", 1) > 1:
            raise NotImplementedError(
                "tp_overlap does not compose with an expert mesh axis")
        for what, dim in (("num_heads", model.num_heads),
                          ("mlp dim", model.hidden_dim * model.mlp_ratio)):
            if dim % tp_size:
                raise ValueError(
                    f"tp_overlap: tensor-parallel size {tp_size} must "
                    f"divide {what} (= {dim})")
    if logits_dtype is None:
        if model is None and ce_chunk:
            # The chunked CE re-applies the head OUTSIDE the model, so it
            # must know the head's compute dtype; with only max_len= there
            # is no model to read it from, and silently assuming fp32
            # would diverge from a bf16-logits model's own head/eval math.
            raise ValueError(
                "ce_chunk with max_len= needs an explicit logits_dtype= "
                "(pass model= to derive it, or logits_dtype=jnp.float32/"
                "bfloat16 matching the model's head)")
        logits_dtype = model_logits_dtype(model)
    if model is not None:
        max_len = model.max_len
    batch_spec = SP_BATCH_SPEC
    # Overlap runs FULL-manual (the model-axis collectives are hand-written
    # rings, and full-manual works on every jax with shard_map at all);
    # otherwise partial-manual keeps `model`/`expert` automatic for GSPMD.
    axis_names = None if tp_overlap else _sp_axis_names(mesh)

    if grad_accum_steps < 1:
        raise ValueError(
            f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    _check_ce_options(ce_chunk, ce_save_probs, logits_dtype)

    def state_shardings_fn(state: TrainState):
        return tp_state_shardings(state, mesh, zero_stage=zero_stage,
                                  cpu_offload=cpu_offload,
                                  overlap=tp_overlap)

    batch_sh = {k: NamedSharding(mesh, s) for k, s in batch_spec.items()}

    def body(state: TrainState, batch, rng):
        if cpu_offload:
            from distributed_training_tpu.train.step import (
                fetch_offloaded_opt_state,
            )

            # The manual region never touches opt_state (gstate strips it);
            # the on-device copy only feeds the GSPMD commit below.
            state = fetch_offloaded_opt_state(state)
        gstate = state.replace(opt_state=None)
        gstate_specs = jax.tree.map(lambda _: P(), gstate)
        grads_specs = jax.tree.map(lambda _: P(), state.params)
        if tp_overlap:
            gstate_specs = gstate_specs.replace(
                params=overlap_param_specs(state.params))
            grads_specs = overlap_param_specs(state.params)
        sharded = shard_map(
            functools.partial(_lm_grads_body, ce_chunk=ce_chunk,
                              accum=grad_accum_steps,
                              accuracy_metric=accuracy_metric,
                              logits_dtype=logits_dtype,
                              ce_save_probs=ce_save_probs,
                              tp_overlap=tp_overlap), mesh,
            in_specs=(gstate_specs, batch_spec, P()),
            out_specs=(grads_specs, P()),
            axis_names=axis_names,
        )
        grads, (ce, aux, accuracy) = sharded(gstate, batch, rng)
        grad_norm = None
        if grad_norm_metric:
            # Outside the manual region the grads are GSPMD-global (the
            # ring body already pmean'd and unscaled them), so one fused
            # norm reduction yields the global value on every shard.
            from distributed_training_tpu.train.step import global_grad_norm

            grad_norm = global_grad_norm(grads)
        new_state, finite = commit_gradients(state, grads)
        return new_state, _lm_metrics(new_state, ce, aux, accuracy, finite,
                                      grad_norm=grad_norm)

    def extra_check(batch):
        if not tp_overlap:
            return
        t_shard = batch["tokens"].shape[1] // sp_size
        if t_shard % tp_size:
            raise ValueError(
                f"tp_overlap: the per-sequence-shard length (= {t_shard}) "
                f"must divide by the model-axis size {tp_size} (the overlap "
                f"schedule time-shards activations over `model`); pick a "
                f"divisible seq_len or disable tp_overlap")

    return _lazy_jit_step(mesh, state_shardings_fn, body,
                          batch_sh=batch_sh, max_len=max_len, donate=donate,
                          extra_check=extra_check)


def _check_ce_options(ce_chunk, ce_save_probs, logits_dtype=jnp.float32):
    """The two CE levers solve opposite problems and do not compose:
    ce_chunk remats per-chunk logits under ``jax.checkpoint`` for
    long-context memory (which would discard saved probabilities and
    silently fall back to the remat backward), while ce_save_probs spends
    memory to delete the remat's exp from the short-T backward. Refuse
    loudly rather than let the flag silently not engage.

    ce_save_probs × bf16 logits *works* but is a measured perf loss
    (123.7k vs 125.2k tok/s — the backward reads are already bf16, so
    the extra forward pass isn't paid back): warn, don't refuse, so the
    combination stays measurable."""
    if ce_chunk and ce_save_probs:
        raise ValueError(
            "ce_save_probs does not compose with ce_chunk (the chunked CE "
            "rematerializes each chunk's logits, discarding saved probs) — "
            "use ce_chunk for long-context memory or ce_save_probs for "
            "fp32-logits throughput, not both")
    if ce_save_probs and jnp.dtype(logits_dtype) == jnp.dtype(jnp.bfloat16):
        import warnings

        warnings.warn(
            "ce_save_probs under bf16 logits is a measured throughput "
            "LOSS (123.7k vs 125.2k tok/s at GPT-2-small B16 T1024; "
            "BASELINE.md round 5) — its win is fp32 logits only",
            stacklevel=3)


def _lazy_jit_step(
    mesh: Mesh,
    state_shardings_fn: Callable,
    body: Callable,
    *,
    batch_sh: dict,
    max_len: int | None,
    donate: bool,
    extra_check: Callable | None = None,
) -> Callable:
    """Shared step scaffold for every LM step builder: global-length guard,
    lazy jit with explicit in/out placements once a concrete state's pytree
    is known, and the ``.state_shardings`` / ``.batch_shardings``
    attributes for placing host-built states and batches. ``extra_check``
    runs on every (eager) batch beside the length guard — e.g. the
    tp_overlap time-divisibility refusal."""
    jitted = None  # built lazily: shardings need a concrete state's pytree

    def ensure_jitted(state: TrainState):
        nonlocal jitted
        if jitted is None:
            repl = NamedSharding(mesh, P())
            jitted = jax.jit(
                body,
                in_shardings=(state_shardings_fn(state), batch_sh, repl),
                out_shardings=(state_shardings_fn(state), repl),
                donate_argnums=(0,) if donate else ())
        return jitted

    def check_len(batch):
        if max_len is not None and batch["tokens"].shape[1] > max_len:
            raise ValueError(
                f"global sequence length {batch['tokens'].shape[1]} exceeds "
                f"the positional table max_len={max_len}")
        if extra_check is not None:
            extra_check(batch)

    def step(state: TrainState, batch, rng):
        check_len(batch)
        return ensure_jitted(state)(state, batch, rng)

    def lower(state, batch, rng):
        # AOT hook for collective accounting (utils/hlo.py): lower the
        # exact step program without executing it. Same silent-clamp guard
        # as step() — a lowered program can also be compiled and run.
        check_len(batch)
        return ensure_jitted(state).lower(state, batch, rng)

    step.state_shardings = state_shardings_fn
    step.batch_shardings = batch_sh
    step.lower = lower
    return step


def make_lm_eval_fn(
    mesh: Mesh, *, model, ce_chunk: int | None = None,
    tp_overlap: bool = False,
) -> Callable:
    """Sharded eval forward for the sequence strategy: ``eval_fn(params,
    batch) -> mean token CE`` over a (data × sequence)-sharded batch.

    The ring-attention model only applies inside shard_map (its sequence
    axis must be bound), so eval reuses the train step's sharded forward —
    global positions from ``axis_index``, ring hops for K/V — with
    ``train=False`` and no gradient. This is what makes eval possible at
    contexts that only *fit* sharded (e.g. T16384 on 8 chips): the
    alternative unsharded twin would need the full [T, T] attention on one
    device. ``ce_chunk`` composes exactly as in training (the logits tensor
    never materializes).

    ``tp_overlap=True`` (the overlap trainer's SP×TP eval) goes
    FULL-manual with params replicated over ``model``: each model rank
    duplicates the eval forward — eval is a tiny fraction of a run, and
    this keeps the ring-attention eval working on jax versions without
    partial-manual shard_map.
    """
    axis_names = None if tp_overlap else _sp_axis_names(mesh)
    batch_spec = SP_BATCH_SPEC

    def body(params, batch):
        tokens = batch["tokens"]
        targets = batch["targets"]
        positions = _global_positions(tokens.shape[1])
        if ce_chunk:
            hidden = model.apply(
                {"params": params}, tokens, positions=positions,
                train=False, return_hidden=True)
            ce, _ = chunked_ce_and_accuracy(
                hidden, params["lm_head"], targets, ce_chunk,
                logits_dtype=model_logits_dtype(model))
        else:
            logits = model.apply(
                {"params": params}, tokens, positions=positions, train=False)
            ce = _fused_softmax_ce(logits, targets)
        return lax.pmean(ce, _GRAD_AXES)

    @jax.jit
    def jitted(params, batch):
        sharded = shard_map(
            body, mesh,
            in_specs=(jax.tree.map(lambda _: P(), params), batch_spec),
            out_specs=P(), axis_names=axis_names)
        return sharded(params, batch)

    def eval_fn(params, batch):
        # Same silent-clamp guard as the train factories: positions are
        # traced inside shard_map, so the global length is only checkable
        # here (an oversized T would silently reuse the last pos-embed row).
        if batch["tokens"].shape[1] > model.max_len:
            raise ValueError(
                f"global sequence length {batch['tokens'].shape[1]} exceeds "
                f"the positional table max_len={model.max_len}")
        return jitted(params, batch)

    return eval_fn


def _make_gspmd_lm_step(
    mesh: Mesh,
    state_shardings_fn: Callable,
    *,
    max_len: int | None = None,
    donate: bool = True,
    grad_accum_steps: int = 1,
    ce_chunk: int | None = None,
    accuracy_metric: bool = True,
    logits_dtype=jnp.float32,
    cpu_offload: bool = False,
    ce_save_probs: bool = False,
    batch_spec: P | None = None,
    grad_norm_metric: bool = False,
) -> Callable:
    """Shared GSPMD LM step builder (the TP and PP steps differ only in how
    the train state is placed): batch over ``data`` (or ``batch_spec`` —
    the SP×PP step shards tokens over data × sequence), lazy jit once a
    concrete state's pytree is known, placements from ``state_shardings_fn``.

    ``grad_accum_steps > 1`` scans microbatches through fwd/bwd inside the
    compiled step before the single update (DeepSpeed
    ``gradient_accumulation_steps`` semantics; see ``train/step.py``).
    """
    if grad_accum_steps < 1:
        raise ValueError(
            f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    _check_ce_options(ce_chunk, ce_save_probs, logits_dtype)
    spec = P(AXIS_DATA, None) if batch_spec is None else batch_spec
    batch_sh = {"tokens": NamedSharding(mesh, spec),
                "targets": NamedSharding(mesh, spec)}

    def body(state: TrainState, batch, rng):
        if cpu_offload:
            from distributed_training_tpu.train.step import (
                fetch_offloaded_opt_state,
            )

            state = fetch_offloaded_opt_state(state)
        if grad_accum_steps > 1:
            grads, ce, aux, accuracy = _lm_accum_grads(
                state, batch, rng, grad_accum_steps, mesh, ce_chunk,
                accuracy_metric=accuracy_metric, logits_dtype=logits_dtype,
                ce_save_probs=ce_save_probs)
        else:
            grads, ce, aux, accuracy = _lm_loss_and_grads(
                state, batch["tokens"], batch["targets"], rng,
                ce_chunk=ce_chunk, accuracy_metric=accuracy_metric,
                logits_dtype=logits_dtype, ce_save_probs=ce_save_probs)
        grads = state.loss_scale.unscale_grads(grads)
        grad_norm = None
        if grad_norm_metric:
            from distributed_training_tpu.train.step import global_grad_norm

            grad_norm = global_grad_norm(grads)
        new_state, finite = commit_gradients(state, grads)
        return new_state, _lm_metrics(new_state, ce, aux, accuracy, finite,
                                      grad_norm=grad_norm)

    return _lazy_jit_step(mesh, state_shardings_fn, body,
                          batch_sh=batch_sh, max_len=max_len, donate=donate)


def make_tp_lm_train_step(
    mesh: Mesh, *, model, zero_stage: int = 0, donate: bool = True,
    grad_accum_steps: int = 1, ce_chunk: int | None = None,
    accuracy_metric: bool = True, cpu_offload: bool = False,
    ce_save_probs: bool = False, tp_overlap: bool = False,
    grad_norm_metric: bool = False,
) -> Callable:
    """Tensor-parallel (megatron-style) LM train step via GSPMD placement.

    The conjugate of :func:`make_lm_train_step`: instead of sharding the
    sequence and replicating weights, this shards the *weights* over the
    ``model`` mesh axis (per ``parallel/tensor_parallel.py``'s rule table)
    and the batch over ``data``. No collective is written by hand — the
    row-parallel psums (attn/out, mlp/fc2, the vocab-sharded softmax-CE
    reduction) and the gradient all-reduce over ``data`` all come from GSPMD
    propagating the annotated placements, overlapped by XLA's scheduler.
    ``zero_stage`` composes DeepSpeed-style optimizer/param sharding on the
    dims TP left free (SURVEY.md §2.3 TP row: "natural extension via pjit
    with a ``model`` mesh axis").

    The model must be built with ``seq_axis=None`` (full attention; TP
    shards heads, which is orthogonal to — and composable with — the ring
    path, but the GSPMD step runs under plain ``jit``, where no ring axis is
    bound).

    ``tp_overlap=True`` swaps the declarative schedule for the
    ring-overlapped collective matmul (``parallel/collective_matmul.py``):
    the step is rebuilt on the shard_map scaffold of
    :func:`make_lm_train_step` with the model axis manual, so the per-layer
    all-gather/reduce-scatter become ppermute rings overlapped with the
    partial matmuls. Same params, same optimizer state, same ZeRO
    composition; only vocab/class-parallel params (lm_head, tok_embed)
    stay replicated over ``model`` (their softmax-CE psum is not part of
    the overlapped layer schedule).

    Returns ``step(state, batch, rng) -> (state, metrics)`` plus a
    ``.state_shardings(state)`` attribute for placing a host-built state.
    """
    from distributed_training_tpu.parallel.tensor_parallel import (
        tp_state_shardings,
    )

    if model.seq_axis is not None:
        raise ValueError(
            "TP step runs under plain jit; build the model with "
            "seq_axis=None (ring attention needs the shard_map step)")
    if tp_overlap:
        return make_lm_train_step(
            mesh, model=model, donate=donate, ce_chunk=ce_chunk,
            grad_accum_steps=grad_accum_steps, zero_stage=zero_stage,
            accuracy_metric=accuracy_metric, cpu_offload=cpu_offload,
            ce_save_probs=ce_save_probs, tp_overlap=True,
            grad_norm_metric=grad_norm_metric)
    return _make_gspmd_lm_step(
        mesh,
        lambda state: tp_state_shardings(state, mesh, zero_stage=zero_stage,
                                         cpu_offload=cpu_offload),
        max_len=model.max_len, donate=donate,
        grad_accum_steps=grad_accum_steps, ce_chunk=ce_chunk,
        accuracy_metric=accuracy_metric,
        logits_dtype=model_logits_dtype(model),
        cpu_offload=cpu_offload, ce_save_probs=ce_save_probs,
        grad_norm_metric=grad_norm_metric)


def make_pp_lm_train_step(
    mesh: Mesh, *, model, num_microbatches: int, donate: bool = True,
    ce_chunk: int | None = None, accuracy_metric: bool = True,
    zero_stage: int = 0, virtual_stages: int = 1,
    cpu_offload: bool = False, ce_save_probs: bool = False,
    grad_norm_metric: bool = False,
) -> Callable:
    """Pipeline-parallel LM train step (GPipe or circular schedule over
    ``pipe``).

    Decoder blocks are stacked and sharded over the ``pipe`` mesh axis; the
    forward runs the ``lax.scan`` + ``lax.ppermute`` schedule from
    ``parallel/pipeline.py`` and the backward pipeline falls out of
    autodiff (ppermute's transpose is the reverse hop). Embeddings and the
    LM head are plain GSPMD ops sharded over ``data``, so DP composes. A
    ``seq_axis`` model selects SP×PP (round 5): the batch shards over
    ``data × sequence`` and ring attention runs inside each stage.
    ``virtual_stages > 1`` selects the interleaved/circular schedule
    (bubble ``(S-1)/(v·M+S-1)`` instead of GPipe's ``(S-1)/(M+S-1)``).

    ``zero_stage`` 1/2 composes DeepSpeed-style: the optimizer state of
    every leaf — pipe-stacked blocks and the replicated embeddings/head —
    additionally shards over the data axis on a dim the pipe/TP specs left
    free, and ``commit_gradients`` runs under plain GSPMD where the
    placement propagates (reduce-scatter + sharded update + all-gather).
    Stage 3 is refused: sharding the *parameters* over data would make the
    pipeline shard_map all-gather every stage's weights each tick —
    DeepSpeed likewise does not compose ZeRO-3 with its pipeline engine.

    Returns ``step(state, batch, rng) -> (state, metrics)`` with a
    ``.pipelined`` attribute (the :class:`PipelinedLM`) and
    ``.batch_shardings`` / ``.state_shardings(state)`` like the TP step.
    """
    from distributed_training_tpu.parallel.pipeline import (
        PipelinedLM,
        pp_tree_shardings,
    )
    from distributed_training_tpu.parallel.sharding import (
        check_cpu_offload,
        zero_stage_axes,
    )

    if zero_stage >= 3:
        raise NotImplementedError(
            "zero stage 3 does not compose with the pipeline strategy "
            "(data-sharded params would be all-gathered every pipeline "
            "tick; DeepSpeed's pipeline engine refuses ZeRO-3 for the same "
            "reason) — use stage 1/2, or the tensor/dp or sequence "
            "strategies for stage 3")
    check_cpu_offload(cpu_offload, zero_stage)
    plm = PipelinedLM(model, mesh, num_microbatches=num_microbatches,
                      virtual_stages=virtual_stages)
    tp = plm.tp_size > 1 or plm.moe  # rule-table specs (model AND expert)
    _, opt_axes = zero_stage_axes(mesh, zero_stage)
    opt_mem = "pinned_host" if cpu_offload else None

    def state_shardings(state: TrainState):
        repl = NamedSharding(mesh, P())
        return state.replace(
            step=repl,
            params=pp_tree_shardings(state.params, mesh, tp=tp),
            batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
            opt_state=pp_tree_shardings(
                state.opt_state, mesh, tp=tp, extra_axes=opt_axes,
                memory_kind=opt_mem),
            loss_scale=jax.tree.map(lambda _: repl, state.loss_scale),
        )

    # max_len is enforced inside PipelinedLM.apply_fn (statically), so the
    # shared builder doesn't need to re-check it.
    step = _make_gspmd_lm_step(
        mesh, state_shardings, donate=donate, ce_chunk=ce_chunk,
        accuracy_metric=accuracy_metric,
        logits_dtype=model_logits_dtype(model),
        cpu_offload=cpu_offload, ce_save_probs=ce_save_probs,
        batch_spec=(P(AXIS_DATA, model.seq_axis)
                    if model.seq_axis else None),
        grad_norm_metric=grad_norm_metric)
    step.pipelined = plm
    return step


def lm_batch_shardings(mesh: Mesh) -> dict:
    """NamedShardings for placing host token arrays on the mesh."""
    spec = P(AXIS_DATA, AXIS_SEQUENCE)
    return {"tokens": NamedSharding(mesh, spec),
            "targets": NamedSharding(mesh, spec)}


def make_lm_batch(tokens) -> dict:
    """Host-side next-token split: inputs = tokens[:, :-1], targets = tokens[:, 1:].

    Done before device sharding so the one-position shift crosses sequence
    shard boundaries for free.
    """
    return {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}
