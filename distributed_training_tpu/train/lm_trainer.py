"""LMTrainer: end-to-end transformer-LM training over any mesh strategy.

The LM counterpart of :class:`~distributed_training_tpu.train.trainer.Trainer`
(the reference has no token workload at all — SURVEY.md §5 "Long-context";
this engine drives the framework's long-context extension as a first-class
product surface, not just library steps).

The parallel strategy follows from the mesh, not from a flag:

- ``pipe > 1``      → GPipe pipeline parallelism
  (``make_pp_lm_train_step``: stacked blocks sharded over ``pipe``; with
  ``sequence > 1`` too, ring attention runs INSIDE each tick — SP×PP,
  round 5);
- ``sequence > 1``  → ring-attention sequence parallelism
  (``make_lm_train_step``: shard_map, K/V blocks hop the ICI ring);
- otherwise         → the GSPMD step (``make_tp_lm_train_step``), which is
  megatron TP when ``model > 1`` and plain DP when ``model == 1``, with
  ZeRO stages composing on the free dims.

``model > 1`` composes with EITHER explicit strategy (TP×SP, PP×TP), and
``expert > 1`` with tensor/dp, sequence, and (homogeneous MoE) pipeline:
the explicit shard_maps are partial-manual — their own axes are manual
while ``model``/``expert`` stay automatic, so megatron/expert shardings
propagate inside the shards and GSPMD inserts the collectives there.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_tpu import checkpoint as ckpt_lib
from distributed_training_tpu.config import TrainConfig, effective_batch_sizes
from distributed_training_tpu.data.lm_text import (
    TokenLoader,
    byte_corpus,
    synthetic_tokens,
)
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state
from distributed_training_tpu.runtime.coordinator import Coordinator
from distributed_training_tpu.runtime.mesh import (
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQUENCE,
    MeshConfig,
    create_mesh,
    data_axis_size,
)
from distributed_training_tpu.train.lm_step import (
    make_lm_batch,
    model_logits_dtype,
    parse_logits_dtype,
    make_lm_train_step,
    make_pp_lm_train_step,
    make_tp_lm_train_step,
)
from distributed_training_tpu.train.optim import make_optimizer
from distributed_training_tpu.train.precision import LossScaleState, Policy
from distributed_training_tpu.train.train_state import (
    TrainState,
    init_train_state,
    param_count,
)
from distributed_training_tpu.observability import (
    AnomalyError,
    TrainObservability,
    forward_flops,
    train_step_flops,
)
from distributed_training_tpu.observability import trace as trace_lib
from distributed_training_tpu.resilience import retry as retry_lib
from distributed_training_tpu.resilience import chaos as chaos_lib
from distributed_training_tpu.resilience.async_ckpt import (
    AsyncCheckpointWriter,
)
from distributed_training_tpu.resilience.chaos import ChaosMonkey
from distributed_training_tpu.runtime.preemption import PreemptionGuard
from distributed_training_tpu.utils.logging import EpochBar, MetricMeter
from distributed_training_tpu.utils.metrics_io import MetricsWriter
from distributed_training_tpu.utils.profiling import WallClock, trace


def restore_lm_checkpoint(directory: str, epoch: int, state, layout=None):
    """``checkpoint.restore_checkpoint`` with actionable LM diagnostics.

    The most common pytree-structure mismatch after round 5 is the
    head-bias default flip: pre-round-5 checkpoints carry an ``lm_head``
    bias the new bias-less template lacks, and orbax surfaces that as a raw
    tree-structure error. Name the flag (mirroring
    ``gpt/jax_tpu/generate.py``'s handler) instead of leaving the user to
    decode the pytree diff.
    """
    try:
        return ckpt_lib.restore_checkpoint(
            directory, epoch, state, layout=layout)
    except FileNotFoundError:
        raise  # missing checkpoint: not a model-tree problem
    except ckpt_lib.CheckpointCorruptError:
        raise  # typed corruption verdict already names dir + remedy
    except Exception as e:
        if isinstance(e, ValueError) and "PERMUTED" in str(e):
            raise  # the layout guard's own refusal is already actionable
        raise ValueError(
            f"checkpoint restore failed — if the original error below is a "
            f"tree-structure mismatch, the configured model must mirror "
            f"the training run's. Most likely: this build defaults to NO "
            f"lm_head bias (round 5); set lm.head_bias=True (--head-bias "
            f"on the CLI) to resume checkpoints trained before that, or "
            f"check num_layers/hidden_dim/vocab/MoE flags. (An I/O or "
            f"deserialization error instead means the checkpoint itself is "
            f"damaged.) Original error: {e}") from e


class LMTrainer:
    """Epoch-loop engine for :class:`TransformerLM` on a device mesh."""

    def __init__(self, cfg: TrainConfig, mesh=None):
        self.cfg = cfg
        self.coord = Coordinator()
        self.mesh = mesh if mesh is not None else create_mesh(
            MeshConfig(**dataclasses.asdict(cfg.mesh)))
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        seq = shape.get(AXIS_SEQUENCE, 1)
        pipe = shape.get(AXIS_PIPE, 1)
        model_par = shape.get(AXIS_MODEL, 1)
        # pipe>1 selects the pipeline engine; a sequence axis composes
        # WITH it since round 5 (each pipeline tick runs ring attention
        # over the manual sequence axis inside the stage), so seq>1 alone
        # selects the plain ring strategy and seq×pipe goes through the
        # pipeline with a seq_axis model.
        self.strategy = ("pipeline" if pipe > 1 else
                         "sequence" if seq > 1 else
                         "tensor/dp")
        # model_par composes with EITHER explicit strategy: the sequence and
        # pipeline shard_maps are partial-manual (their own axes manual,
        # ``model`` automatic), so megatron TP shardings propagate inside
        # the shards and GSPMD inserts the row-parallel psums there.
        self.tp_size = model_par
        if cfg.tp_overlap and self.strategy == "pipeline":
            raise NotImplementedError(
                "tp_overlap does not compose with the pipeline strategy "
                "(the stacked-stage scan keeps `model` automatic for "
                "GSPMD); use the tensor/dp or sequence strategy")
        if cfg.tp_overlap and cfg.moe.enabled:
            raise NotImplementedError(
                "tp_overlap does not compose with MoE (expert dispatch "
                "relies on GSPMD's expert axis, which the full-manual "
                "overlap region unbinds)")
        if self.strategy == "pipeline" and cfg.zero.stage >= 3:
            # Stages 1/2 compose since round 4 (make_pp_lm_train_step
            # shards the optimizer state over data on dims the pipe/TP
            # specs leave free); stage 3 would all-gather every stage's
            # params each pipeline tick — DeepSpeed's pipeline engine
            # refuses ZeRO-3 for the same reason.
            raise NotImplementedError(
                f"zero stage {cfg.zero.stage} does not compose with the "
                "pipeline strategy (params sharded over data would be "
                "all-gathered every tick); use stage 1/2 or another "
                "strategy")
        from distributed_training_tpu.parallel.sharding import (
            check_cpu_offload,
        )

        # Validate the ds_config offload knob once, strategy-independent
        # (the step builders re-check where they place opt state).
        check_cpu_offload(cfg.zero.cpu_offload, cfg.zero.stage)
        expert = shape.get("expert", 1)
        if cfg.moe.enabled and expert > 1 and cfg.zero.stage >= 1 \
                and not cfg.moe.moe_param_group:
            # DeepSpeed's --moe-param-group splits expert params into their
            # own groups so ZeRO partitions their optimizer state per
            # expert-parallel group instead of over the whole DP world
            # (resnet/deepspeed/deepspeed_train.py:103-106) — without it,
            # ZeRO×EP is wrong there. This framework's rule table always
            # keeps expert moments expert-sharded (tensor_parallel.py
            # LM_TP_RULES), i.e. the flag's semantics are the only
            # implemented behavior; requiring it under ZeRO×EP keeps the
            # CLI contract explicit rather than silently implying it.
            raise ValueError(
                "zero stage >= 1 with expert parallelism requires "
                "--moe-param-group (expert optimizer state is partitioned "
                "per expert group, DeepSpeed's split_params_into_"
                "different_moe_groups_for_optimizer semantics)")
        # Gated on moe.enabled (not the expert axis): an expert axis with
        # MoE off has its own accurate diagnosis below ("enable --moe or
        # drop the expert axis") — steering that user to --moe-every 1
        # would not fix anything.
        if cfg.moe.enabled and self.strategy == "pipeline":
            homogeneous = (cfg.moe.every == 1
                           and len(set(cfg.moe.num_experts)) == 1)
            if not homogeneous:
                raise NotImplementedError(
                    "the pipeline engine carries MoE only in the "
                    "HOMOGENEOUS layout (--moe-every 1, one expert count: "
                    "the stacked-stage scan requires congruent per-layer "
                    "param trees, which the alternating/per-layer layouts "
                    "break). That already exceeds the parity bar — "
                    "DeepSpeed's PipelineModule cannot carry MoE layers at "
                    "all (deepspeed.moe routes through the non-pipeline "
                    "engine only; the reference's MoE surface, "
                    "resnet/deepspeed/deepspeed_train.py:61-106, drives "
                    "plain DP training). Use tensor/dp or sequence for "
                    "alternating/per-layer MoE")
        if expert > 1 and not cfg.moe.enabled:
            raise ValueError(
                f"expert mesh axis sized {expert} with MoE disabled would "
                "replicate the dense model over it (idle chips); enable "
                "--moe or drop the expert axis")
        lm = cfg.lm
        if seq > 1 and lm.seq_len % seq:
            raise ValueError(
                f"sequence-parallel size {seq} must divide seq_len "
                f"(= {lm.seq_len})")
        if lm.ce_chunk_size is not None:
            if lm.ce_chunk_size < 1:
                raise ValueError(
                    f"ce_chunk_size must be >= 1, got {lm.ce_chunk_size}")
            # Token datasets yield seq_len+1 tokens so the shifted loss
            # length is exactly seq_len — seq_len/sp per shard for the
            # ring strategy's shard-local chunked CE, but the FULL seq_len
            # for the pipeline path (its chunked CE runs under GSPMD over
            # the global time axis, even with a sequence mesh axis).
            # tp_overlap additionally time-shards the loss over the model
            # axis (both the ring and tensor/dp strategies route through
            # the full-manual overlap body).
            t_loss = (lm.seq_len // seq
                      if self.strategy == "sequence" else lm.seq_len)
            if cfg.tp_overlap and self.strategy != "pipeline":
                t_loss //= model_par
            if t_loss % lm.ce_chunk_size:
                raise ValueError(
                    f"ce_chunk_size {lm.ce_chunk_size} must divide the "
                    f"per-shard loss sequence length (= {t_loss})")
        if pipe > 1:
            if lm.num_layers % pipe:
                raise ValueError(
                    f"pipeline size {pipe} must divide num_layers "
                    f"(= {lm.num_layers})")
            if cfg.data.batch_size % lm.num_microbatches:
                raise ValueError(
                    f"num_microbatches {lm.num_microbatches} must divide "
                    f"the per-shard batch_size (= {cfg.data.batch_size})")
        if cfg.moe.enabled and expert > 1:
            # Per-layer lists (DeepSpeed --num-experts nargs surface) are
            # honored since round 4; EVERY layer's expert set shards over
            # the expert axis, so each count must divide it.
            for ne in cfg.moe.num_experts:
                if int(ne) % expert:
                    raise ValueError(
                        f"expert-parallel size {expert} must divide every "
                        f"per-layer num_experts "
                        f"(= {tuple(cfg.moe.num_experts)})")
        if model_par > 1:
            # The megatron rule table shards heads / mlp columns / vocab over
            # the model axis; device_put fails opaquely on non-divisible
            # dims, so check here where the message can name the knob.
            # tp_overlap keeps vocab params replicated (no vocab constraint)
            # but time-shards activations over `model` instead.
            checks = [("num_heads", lm.num_heads),
                      ("mlp dim", lm.hidden_dim * lm.mlp_ratio)]
            if not cfg.tp_overlap:
                checks.append(("vocab_size", lm.vocab_size))
            for what, n in checks:
                if n % model_par:
                    raise ValueError(
                        f"tensor parallelism size {model_par} must divide "
                        f"{what} (= {n})")
            if cfg.tp_overlap and (lm.seq_len // seq) % model_par:
                raise ValueError(
                    f"tp_overlap time-shards activations over the model "
                    f"axis: the per-sequence-shard length "
                    f"(= {lm.seq_len // seq}) must divide by the "
                    f"tensor-parallel size {model_par}")
        policy = Policy.from_config(cfg.precision)
        moe_kwargs = {}
        if cfg.moe.enabled:
            moe_kwargs = dict(
                moe_num_experts=tuple(int(n) for n in cfg.moe.num_experts),
                moe_every=cfg.moe.every,
                moe_top_k=cfg.moe.top_k,
                moe_capacity_factor=cfg.moe.capacity_factor,
                moe_min_capacity=cfg.moe.min_capacity,
                moe_noisy_gate_policy=cfg.moe.noisy_gate_policy,
                moe_mlp_type=cfg.moe.mlp_type,
                moe_expert_axis="expert" if expert > 1 else None,
            )
        self.model = get_model(
            "transformer_lm",
            num_classes=lm.vocab_size,
            dtype=policy.compute_dtype,
            remat=cfg.remat,
            seq_axis=AXIS_SEQUENCE if seq > 1 else None,
            num_layers=lm.num_layers,
            num_heads=lm.num_heads,
            hidden_dim=lm.hidden_dim,
            mlp_ratio=lm.mlp_ratio,
            max_len=lm.max_len,
            attn_impl=lm.attn_impl,
            logits_dtype=parse_logits_dtype(lm.logits_dtype),
            head_bias=lm.head_bias,
            **moe_kwargs,
        )
        self.world_size = data_axis_size(self.mesh)
        self.train_gbs, self.eval_gbs, self.grad_accum = effective_batch_sizes(
            cfg, self.world_size)
        # DeepSpeed's pipeline engine EQUATES gradient accumulation with
        # microbatching (`gradient_accumulation_steps` is its microbatch
        # count; the ds_config surface at
        # resnet/deepspeed/deepspeed_train.py:172-173 feeds both knobs from
        # the same batch triple): accum multiplies the microbatch count,
        # each microbatch keeps its shape (batch_size/num_microbatches),
        # and the schedule drains accum× more ticks before the single
        # optimizer update — same effective batch, better bubble fraction.
        self._pp_microbatches = cfg.lm.num_microbatches * (
            self.grad_accum if self.strategy == "pipeline" else 1)
        if (self.strategy == "pipeline"
                and cfg.data.batch_size % self._pp_microbatches):
            # The shared PipelinedLM apply_fn serves BOTH the train step
            # (which sees batch_size × accum rows and drains num_micro ×
            # accum microbatches) and eval (micro-sized batches through the
            # same schedule): batch_size itself must divide by the scaled
            # count, or eval's spmd_pipeline would crash after a full
            # training epoch.
            raise ValueError(
                f"with the pipeline strategy, gradient_accumulation_steps "
                f"multiplies the microbatch count (DeepSpeed pipeline "
                f"semantics): num_microbatches × accum = "
                f"{self._pp_microbatches} must divide the per-shard "
                f"batch_size (= {cfg.data.batch_size})")
        self.tx = make_optimizer(cfg.optimizer, cfg.scheduler, self.world_size)
        loss_scale = LossScaleState.create(cfg.precision)

        self.rng, init_rng = jax.random.split(jax.random.PRNGKey(cfg.seed))
        if self.strategy == "pipeline":
            self.train_step = make_pp_lm_train_step(
                self.mesh, model=self.model,
                num_microbatches=self._pp_microbatches,
                ce_chunk=lm.ce_chunk_size,
                accuracy_metric=lm.metrics_accuracy,
                zero_stage=cfg.zero.stage,
                virtual_stages=lm.virtual_stages,
                cpu_offload=cfg.zero.cpu_offload,
                ce_save_probs=lm.ce_save_probs,
                grad_norm_metric=cfg.observability.grad_norm)
            plm = self.train_step.pipelined
            state = TrainState.create(
                apply_fn=plm.apply_fn, params=plm.init_params(init_rng),
                tx=self.tx, loss_scale=loss_scale)
            self.shardings = self.train_step.state_shardings(state)
        elif self.strategy == "sequence":
            self.train_step = make_lm_train_step(
                self.mesh, model=self.model, ce_chunk=lm.ce_chunk_size,
                grad_accum_steps=self.grad_accum, zero_stage=cfg.zero.stage,
                accuracy_metric=lm.metrics_accuracy,
                cpu_offload=cfg.zero.cpu_offload,
                ce_save_probs=lm.ce_save_probs,
                tp_overlap=cfg.tp_overlap and model_par > 1,
                grad_norm_metric=cfg.observability.grad_norm)
            state = init_train_state(
                self.model, init_rng, (1, 8), self.tx,
                loss_scale=loss_scale, input_dtype=jnp.int32)
            # TP rule table (+ ZeRO recruitment over data × sequence): over
            # a model axis of size 1 every TP spec is a no-op shard; with
            # model > 1 the weights shard megatron-style and the sequence
            # step's partial-manual shard_map leaves them automatic.
            self.shardings = self.train_step.state_shardings(state)
        else:
            self.train_step = make_tp_lm_train_step(
                self.mesh, model=self.model, zero_stage=cfg.zero.stage,
                grad_accum_steps=self.grad_accum,
                ce_chunk=lm.ce_chunk_size,
                accuracy_metric=lm.metrics_accuracy,
                cpu_offload=cfg.zero.cpu_offload,
                ce_save_probs=lm.ce_save_probs,
                tp_overlap=cfg.tp_overlap and model_par > 1,
                grad_norm_metric=cfg.observability.grad_norm)
            state = init_train_state(
                self.model, init_rng, (1, 8), self.tx,
                loss_scale=loss_scale, input_dtype=jnp.int32)
            self.shardings = self.train_step.state_shardings(state)
        self.state = place_state(state, self.shardings)

        self.batch_shardings = self.train_step.batch_shardings

        # Eval forward. The sequence strategy evaluates through the SHARDED
        # ring forward (make_lm_eval_fn): the ring model only applies
        # inside shard_map, and a context that only *fits* sharded (the
        # T16384 flagship) must be evaluable at its trained length —
        # tests/test_lm_sequence_parallel.py pins sharded eval == the
        # unsharded oracle.
        if self.strategy == "sequence":
            from distributed_training_tpu.train.lm_step import make_lm_eval_fn

            self._eval_fn = make_lm_eval_fn(
                self.mesh, model=self.model, ce_chunk=lm.ce_chunk_size,
                tp_overlap=cfg.tp_overlap and self.tp_size > 1)
        else:
            eval_apply = self.state.apply_fn

            if lm.ce_chunk_size:
                from distributed_training_tpu.train.lm_step import (
                    chunked_ce_and_accuracy,
                )

                def eval_loss(params, batch):
                    hidden = eval_apply({"params": params}, batch["tokens"],
                                        train=False, return_hidden=True)
                    ce, _ = chunked_ce_and_accuracy(
                        hidden, params["lm_head"], batch["targets"],
                        lm.ce_chunk_size,
                        logits_dtype=model_logits_dtype(self.model))
                    return ce
            else:
                from distributed_training_tpu.train.lm_step import (
                    _fused_softmax_ce,
                )

                def eval_loss(params, batch):
                    # Same fusion-friendly CE as training: fp32 reduction
                    # over stored-dtype logits with no materialized
                    # log-prob tensor (see lm_step._fused_ce_rows).
                    logits = eval_apply({"params": params}, batch["tokens"],
                                        train=False)
                    return _fused_softmax_ce(logits, batch["targets"])

            self._eval_fn = jax.jit(eval_loss)

        self.meter = MetricMeter(cfg.log_interval)
        # Forensics default next to the run's durable artifacts.
        obs_dump_dir = cfg.observability.dump_dir or os.path.join(
            cfg.checkpoint.directory, "flight")
        # Span tracing (off by default → trace is None and every
        # integration point below stays span-free; observability/trace.py).
        self.trace, trace_path = trace_lib.session_for_run(
            cfg.observability.trace, default_dir=obs_dump_dir)
        # Always-on when the flight recorder (or the span trace) is
        # (goodput attribution); the per-epoch report print stays gated
        # on wall_clock_breakdown.
        self.clock = WallClock(
            cfg.wall_clock_breakdown or cfg.observability.flight_recorder
            or self.trace is not None, trace=self.trace)
        self.metrics_writer = MetricsWriter(
            cfg.tensorboard_dir, cfg.metrics_jsonl,
            enabled=self.coord.is_master())
        # Flight instruments. Step FLOPs cover the EFFECTIVE batch's
        # tokens (micro × accum × world × seq_len) — one optimizer step's
        # model FLOPs, accumulation-aware by construction; MoE models
        # report no MFU (routed FLOPs are runtime-dependent).
        self.obs = TrainObservability(
            cfg.observability,
            step_flops=train_step_flops(forward_flops(
                self.model, seq_len=lm.seq_len, batch=self.train_gbs)),
            n_devices=int(self.mesh.devices.size),
            clock=self.clock, is_master=self.coord.is_master(),
            printer=self.coord.print,
            dump_dir=obs_dump_dir,
            extra_provider=self._resilience_snapshot,
            trace=self.trace, trace_path=trace_path,
            num_processes=jax.process_count())
        # Resilience: fault injection + background checkpoint writer
        # (single-process only; multihost saves stay synchronous — see
        # trainer.py for the rationale).
        self.chaos = (ChaosMonkey(cfg.chaos,
                                  process_index=jax.process_index(),
                                  trace=self.trace)
                      if cfg.chaos.active else None)
        self._ckpt_writer = None
        if cfg.checkpoint.async_save and jax.process_count() == 1:
            self._ckpt_writer = AsyncCheckpointWriter(
                post_save=(self.chaos.after_checkpoint_save
                           if self.chaos else None),
                printer=self.coord.print, trace=self.trace)
        self._sync_saves = 0
        self._guard: PreemptionGuard | None = None
        self._global_step = 0
        self._epoch_step = 0
        strategy_label = self.strategy + (
            "×tp" if self.tp_size > 1 and self.strategy != "tensor/dp" else ""
        ) + ("(tp-overlap)" if cfg.tp_overlap and self.tp_size > 1 else "")
        self.coord.print(
            f"[lm_trainer] params={param_count(state.params):,} "
            f"mesh={shape} strategy={strategy_label} "
            f"zero_stage={cfg.zero.stage} dtype={cfg.precision.dtype} "
            f"seq_len={lm.seq_len}"
            + (f" grad_accum={self.grad_accum}" if self.grad_accum > 1 else ""))

    # -- resilience ---------------------------------------------------------
    def _save_ckpt(self, epoch: int, *, sync: bool = False, **kw) -> None:
        """One save through the configured path (async writer or sync
        orbax); ``sync=True`` = the preemption durability contract."""
        d = self.cfg.checkpoint.directory
        kw.setdefault("layout", self._ckpt_layout())
        if self._ckpt_writer is not None:
            self._ckpt_writer.save(d, epoch, self.state, sync=sync, **kw)
            return
        path = ckpt_lib.save_checkpoint(d, epoch, self.state, **kw)
        self._sync_saves += 1
        if self.chaos is not None:
            self.chaos.after_checkpoint_save(path, epoch)

    def _prune_ckpts(self) -> None:
        d, keep = self.cfg.checkpoint.directory, self.cfg.checkpoint.keep
        if self._ckpt_writer is not None:
            self._ckpt_writer.prune(d, keep)
        else:
            ckpt_lib.prune_checkpoints(d, keep)

    def _resilience_snapshot(self) -> dict:
        """Flight-dump resilience section (tools/flight_report.py)."""
        c = {"io_retries": retry_lib.total_retries(),
             "saves_committed": self._sync_saves, "saves_failed": 0}
        if self._ckpt_writer is not None:
            c["saves_committed"] += \
                self._ckpt_writer.counters["saves_committed"]
            c["saves_failed"] = self._ckpt_writer.counters["saves_failed"]
        if self.chaos is not None:
            c["chaos_faults"] = dict(self.chaos.counters)
        return {"resilience": c}

    # -- data ---------------------------------------------------------------
    def make_loaders(self) -> tuple[TokenLoader, TokenLoader]:
        lm = self.cfg.lm
        if lm.corpus_path:
            # Disjoint byte spans: eval windows never overlap training text.
            train = byte_corpus(
                lm.corpus_path, lm.train_sequences, lm.seq_len,
                seed=self.cfg.seed, span=(0.0, 0.9))
            evals = byte_corpus(
                lm.corpus_path, lm.eval_sequences, lm.seq_len,
                seed=self.cfg.seed + 1, span=(0.9, 1.0))
        else:
            train = synthetic_tokens(
                lm.train_sequences, lm.seq_len, lm.vocab_size,
                seed=self.cfg.seed)
            evals = synthetic_tokens(
                lm.eval_sequences, lm.seq_len, lm.vocab_size,
                seed=self.cfg.seed + 1)
        def mk(toks, train_mode):
            # Train consumes effective batches; eval stays micro-sized.
            return TokenLoader(
                toks,
                global_batch_size=(self.train_gbs if train_mode
                                   else self.eval_gbs),
                shuffle=train_mode,
                seed=self.cfg.seed,
                max_steps=(self.cfg.data.max_steps_per_epoch
                           if train_mode else None))
        return mk(train, True), mk(evals, False)

    def _place(self, host_batch: dict) -> dict:
        # Shift on the host numpy array, then one device_put straight onto
        # the mesh placement — no staging copy through the default device.
        batch = make_lm_batch(host_batch["tokens"])
        return jax.device_put(batch, self.batch_shardings)

    def _batches(self, loader: TokenLoader):
        """Device-resident batches, prefetched ``cfg.data.prefetch`` ahead;
        the synchronous path keeps per-batch 'data' wall-clock attribution."""
        from distributed_training_tpu.data.prefetch import DevicePrefetcher

        if self.cfg.data.prefetch < 1:
            def sync_gen():
                for b in loader:
                    with self.clock.phase("data"):
                        gb = self._place(b)
                    yield gb
            return sync_gen()
        return DevicePrefetcher(loader, self._place,
                                depth=self.cfg.data.prefetch)

    # -- train --------------------------------------------------------------
    def train_epoch(self, epoch: int, loader: TokenLoader,
                    skip_steps: int = 0) -> dict:
        loader.set_epoch(epoch)
        if skip_steps:
            # Step-accurate preemption resume: skip the already-trained
            # prefix of the epoch's deterministic shuffle (see trainer.py).
            from distributed_training_tpu.data.pipeline import SkipBatches

            self.coord.print(
                f"[lm_trainer] resuming epoch {epoch} at step {skip_steps}")
            loader = SkipBatches(loader, skip_steps)
        self._epoch_step = skip_steps
        self.obs.on_epoch()  # boundary pause ≠ a straggler step
        bar = EpochBar(len(loader), epoch, self.cfg.num_epochs,
                       self.coord.is_master())
        gbatch = None
        for gbatch in self._batches(loader):
            with self.clock.phase("step"):
                self.rng, step_rng = jax.random.split(self.rng)
                self.state, metrics = self.train_step(
                    self.state, gbatch, step_rng)
            with self.clock.phase("log"):
                self._global_step += 1
                self._epoch_step += 1
                fetched = self.meter.push(self._global_step, metrics)
                # Chaos BEFORE the recorder's timestamp: an injected
                # slow-step stall then lands in THIS step's wall delta
                # (like a real straggler's would), so the cross-host
                # aggregation attributes the injected step itself.
                if self.chaos is not None:
                    self.chaos.on_step(self._global_step)
                self.obs.on_step(self._global_step)
                bar.update()
                if fetched:
                    extras = self.obs.on_flush(
                        self.meter.last, batch=gbatch, state=self.state,
                        step_fn=self.train_step, rng=self.rng)
                    bar.set_postfix(self.meter.last)
                    self.metrics_writer.write(
                        self.meter.last["step"],
                        {**self.meter.last, **extras})
            if self._guard is not None and self._guard.should_stop(
                    at_sync_point=fetched):
                break
        # Flush the epoch tail only if steps are actually pending — an
        # unconditional write would duplicate the last interval's point.
        if self.meter.pending:
            flushed = self.meter.flush()
            extras = self.obs.on_flush(
                flushed, batch=gbatch, state=self.state,
                step_fn=self.train_step, rng=self.rng)
            self.metrics_writer.write(flushed["step"], {**flushed, **extras})
        bar.set_postfix(self.meter.last)
        bar.close()
        if self.cfg.wall_clock_breakdown:
            self.coord.print(f"[wall_clock] {self.clock.report()}")
        return self.meter.last

    # -- eval ---------------------------------------------------------------
    def _eval_params(self):
        """Params evaluation sees: the EMA tree when configured."""
        if (self.cfg.optimizer.ema_decay is not None
                and self.cfg.eval_with_ema):
            from distributed_training_tpu.train.optim import ema_params

            return ema_params(self.state.opt_state)
        return self.state.params

    def evaluate(self, loader: TokenLoader) -> float:
        """Mean held-out perplexity (exp of the mean token CE)."""
        params = self._eval_params()
        losses = []
        for gbatch in self._batches(loader):
            losses.append(float(self._eval_fn(params, gbatch)))
        if not losses:
            raise ValueError(
                "eval loader yielded no batches (eval_sequences "
                f"{self.cfg.lm.eval_sequences} < global batch "
                f"{loader.global_batch_size}? drop_last discards partials)")
        ppl = float(np.exp(np.mean(losses)))
        self.metrics_writer.write(
            self._global_step, {"perplexity": ppl}, prefix="eval")
        return ppl

    # -- full run -----------------------------------------------------------
    def fit(self) -> dict:
        if self.chaos is not None:
            chaos_lib.install(self.chaos)  # data loaders poll it
        try:
            result = self._fit()
            # Surfaces a deferred anomaly raise whose trace window the
            # run's end cut short (forensics were dumped at trigger time).
            self.obs.close()
            return result
        except AnomalyError:
            raise
        except BaseException:
            self.obs.on_crash()  # flight record before the exception flies
            raise
        finally:
            if self.chaos is not None:
                chaos_lib.uninstall()
            if self._ckpt_writer is not None:
                self._ckpt_writer.close(raise_on_error=False)
            self.obs.close(raise_pending=False)  # idempotent trace teardown
            self.metrics_writer.close()

    def _ckpt_layout(self) -> dict:
        """Storage-layout metadata for save/restore validation: the
        pipeline strategy stacks blocks in a (pipe_size × virtual_stages)-
        dependent permutation (parallel/pipeline.circular_layer_order);
        shape-identical checkpoints across different layouts would load
        silently permuted (see checkpoint.restore_checkpoint)."""
        if self.strategy != "pipeline":
            return {}
        plm = self.train_step.pipelined
        if plm.virtual_stages == 1:
            # GPipe stacking is the identity for ANY pipe size — only the
            # circular permutation makes the layout pipe-size-dependent.
            return {"virtual_stages": 1}
        return {"pipe_size": plm.pipe_size,
                "virtual_stages": plm.virtual_stages}

    def _fit(self) -> dict:
        cfg = self.cfg
        train_loader, eval_loader = self.make_loaders()

        start_epoch = 0
        start_step = 0
        resume = ckpt_lib.resolve_resume(cfg.checkpoint)
        if resume >= 0:
            self.state, start_epoch, start_step = restore_lm_checkpoint(
                cfg.checkpoint.directory, resume, self.state,
                layout=self._ckpt_layout())
            self.state = place_state(self.state, self.shardings)
            # Metric sinks continue the restored step axis (see trainer.py).
            self._global_step = int(jax.device_get(self.state.step))
            self.coord.print(f"[lm_trainer] resumed at epoch {start_epoch}")

        ppl = None
        preempted = False
        with trace(cfg.profile_dir), PreemptionGuard() as guard:
            self._guard = guard
            for epoch in range(start_epoch, cfg.num_epochs):
                self.train_epoch(
                    epoch, train_loader,
                    skip_steps=start_step if epoch == start_epoch else 0)
                if guard.should_stop():
                    preempted = True
                    if cfg.checkpoint.save_on_preemption:
                        # Completed-epoch preemption rolls over (trainer.py).
                        done = self._epoch_step >= len(train_loader)
                        next_ep = epoch + 1 if done else epoch
                        estep = 0 if done else self._epoch_step
                        with self.clock.phase("ckpt"):
                            # sync: durable before the grace window ends.
                            self._save_ckpt(epoch, sync=True,
                                            next_epoch=next_ep,
                                            epoch_step=estep)
                        self.coord.print(
                            f"[lm_trainer] SIGTERM: saved preemption "
                            f"checkpoint (resumes at epoch {next_ep} "
                            f"step {estep})")
                    break
                if cfg.eval_every and (epoch + 1) % cfg.eval_every == 0:
                    with self.clock.phase("eval"):
                        ppl = self.evaluate(eval_loader)
                    self.coord.print(
                        f"[eval] epoch {epoch + 1}: perplexity {ppl:.4f}")
                if cfg.checkpoint.interval and (
                        epoch + 1) % cfg.checkpoint.interval == 0:
                    with self.clock.phase("ckpt"):
                        self._save_ckpt(epoch)
                        self._prune_ckpts()
        self._guard = None
        if self._ckpt_writer is not None:
            # Durable before fit() reports done (failures counted, not
            # thrown over a successful run — see trainer.py).
            self._ckpt_writer.wait(raise_on_error=False)
        return {"final_perplexity": ppl, "preempted": preempted,
                "last_metrics": self.meter.last,
                "steps": int(jax.device_get(self.state.step))}
