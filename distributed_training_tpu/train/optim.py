"""Optimizer + LR schedule factory (optax).

Parity targets:
- Adam(lr = 1e-3 * world_size) — ``resnet/pytorch_ddp/ddp_train.py:97,110``
- DeepSpeed Adam betas [0.8, 0.999], eps 1e-8, wd 3e-7 —
  ``resnet/deepspeed/deepspeed_train.py:175-186``
- WarmupLR 0 → 1e-3 over 1000 steps — ``deepspeed_train.py:187-194``
- gradient_clipping 1.0 — ``deepspeed_train.py:195``
- ColossalAI HybridAdam(lr·world) — ``resnet/colossal/colossal_train.py:153``
  (HybridAdam is CUDA-fused Adam; the XLA-fused optax update is the TPU
  analogue — XLA fuses the whole update into the step program. A Pallas
  fused-Adam kernel lives in ``ops/fused_adam.py`` as the explicit-kernel
  variant.)
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from distributed_training_tpu.config import OptimizerConfig, SchedulerConfig


class EmaState(NamedTuple):
    """State of :func:`with_ema`: the wrapped optimizer's state plus the
    exponential moving average of the *post-update* parameters — and, for
    BatchNorm models, of the running statistics (maintained by
    ``precision.commit_gradients``, the one place both trees exist;
    evaluating EMA weights against live-weight BN statistics would skew
    the metric). ``ema_batch_stats`` is ``{}`` for stat-less models."""

    inner: Any
    ema_params: Any
    ema_batch_stats: Any
    decay: jnp.ndarray


def with_ema(tx: optax.GradientTransformation,
             decay: float) -> optax.GradientTransformation:
    """Wrap ``tx`` so its state carries an EMA of the updated params.

    Living inside ``opt_state`` (rather than a parallel TrainState field)
    buys checkpointing and ZeRO sharding for free: the EMA tree is just
    more optimizer state, so orbax saves it and the stage-1/2 placement
    rules shard it over ``data`` like Adam moments. The fp16 path's
    skip-on-overflow also covers it — a rejected step discards the whole
    tentative opt_state, EMA included.

    The average is initialized to the initial params (the standard,
    already-unbiased choice). ``decay`` (e.g. 0.9999) is kept in the
    state so ``commit_gradients`` can apply the same constant to the
    BatchNorm-statistics average (``TrainState.create`` seeds
    ``ema_batch_stats`` for models that carry stats).
    """
    def init(params):
        return EmaState(
            inner=tx.init(params),
            # Real copies: jnp.asarray would alias the param buffers, and
            # an opt_state leaf aliasing a param breaks buffer donation
            # ("attempt to donate the same buffer twice").
            ema_params=jax.tree.map(
                lambda p: jnp.array(p, copy=True), params),
            ema_batch_stats={},
            decay=jnp.float32(decay),
        )

    def update(updates, state, params=None, **extra):
        if params is None:
            raise ValueError("with_ema requires params in update()")
        new_updates, inner = tx.update(updates, state.inner, params, **extra)
        new_params = optax.apply_updates(params, new_updates)
        # state.decay (not the closure constant): the checkpointed value is
        # the single source of truth, so params and BN-stats EMAs cannot
        # advance at different rates after a resume with a changed config.
        d = state.decay
        ema = jax.tree.map(
            lambda e, p: d * e + (1.0 - d) * p,
            state.ema_params, new_params)
        return new_updates, state._replace(inner=inner, ema_params=ema)

    return optax.GradientTransformation(init, update)


def ema_params(opt_state: Any) -> Any:
    """Extract the EMA parameter tree from an optimizer state built with
    ``OptimizerConfig(ema_decay=...)``; raises if EMA was not enabled."""
    if isinstance(opt_state, EmaState):
        return opt_state.ema_params
    raise ValueError(
        "optimizer state carries no EMA; set OptimizerConfig.ema_decay")


def ema_batch_stats(opt_state: Any) -> Any:
    """The EMA of BatchNorm running stats ({} for stat-less models)."""
    if isinstance(opt_state, EmaState):
        return opt_state.ema_batch_stats
    raise ValueError(
        "optimizer state carries no EMA; set OptimizerConfig.ema_decay")


def make_schedule(opt: OptimizerConfig, sched: SchedulerConfig, world_size: int = 1):
    """Build the LR schedule; returns an optax schedule fn."""
    base_lr = opt.lr * (world_size if opt.scale_lr_by_world else 1)
    if sched.name == "constant":
        return optax.constant_schedule(base_lr)
    if sched.name == "warmup_lr":
        # DeepSpeed WarmupLR: linear warmup_min_lr → warmup_max_lr over
        # warmup_num_steps, then constant at warmup_max_lr.
        return optax.join_schedules(
            [
                optax.linear_schedule(
                    sched.warmup_min_lr, sched.warmup_max_lr,
                    sched.warmup_num_steps),
                optax.constant_schedule(sched.warmup_max_lr),
            ],
            boundaries=[sched.warmup_num_steps],
        )
    if sched.name == "cosine":
        if sched.total_steps is None:
            raise ValueError("cosine schedule needs total_steps")
        return optax.warmup_cosine_decay_schedule(
            init_value=sched.warmup_min_lr,
            peak_value=base_lr,
            warmup_steps=sched.warmup_num_steps,
            decay_steps=sched.total_steps,
        )
    raise ValueError(f"unknown scheduler {sched.name!r}")


def decay_mask(opt: OptimizerConfig):
    """optax weight-decay mask per ``weight_decay_mask``.

    ``no_1d`` implements the standard ImageNet-recipe exclusion: biases
    and normalization scales/offsets are not decayed. The test is rank>=2
    AND leaf name not in {bias, scale} — the name check matters because
    stacked executors (the pipeline strategy stacks per-layer params with
    a leading layer dim) turn [D] norm params into rank-2 [L, D]; a pure
    rank heuristic would decay them under one mesh and not another.
    ``all`` (torch default semantics) returns None — decay everything.
    """
    if opt.weight_decay_mask == "all":
        return None
    if opt.weight_decay_mask == "no_1d":
        def mask(params):
            def leaf(path, p):
                last = path[-1]
                name = getattr(last, "key", None) or str(last)
                return p.ndim >= 2 and name not in ("bias", "scale")
            return jax.tree_util.tree_map_with_path(leaf, params)

        return mask
    raise ValueError(
        f"unknown weight_decay_mask {opt.weight_decay_mask!r}")


def _decay(opt: OptimizerConfig):
    return optax.add_decayed_weights(opt.weight_decay, mask=decay_mask(opt))


def make_optimizer(
    opt: OptimizerConfig,
    sched: SchedulerConfig | None = None,
    world_size: int = 1,
) -> optax.GradientTransformation:
    """Build the full gradient transformation chain.

    Chain order mirrors the engines' semantics: clip the (already unscaled,
    already all-reduced) global grad norm, then the update. 'adam' uses
    additive L2 before the moments (torch Adam ``weight_decay`` semantics,
    which is what DeepSpeed's config maps to); 'adamw' decouples it;
    'sgd' adds L2 to the gradient before momentum (torch SGD semantics);
    'lamb' is AdamW + per-layer trust ratios (large-batch training).
    """
    sched = sched or SchedulerConfig()
    lr = make_schedule(opt, sched, world_size)
    parts = []
    if opt.grad_clip_norm is not None:
        parts.append(optax.clip_by_global_norm(opt.grad_clip_norm))
    if opt.name == "hybrid_adam":
        # Pallas fused Adam (ColossalAI HybridAdam analogue): one HBM pass
        # per tensor; lr/schedule handled inside the transformation.
        from distributed_training_tpu.ops.fused_adam import fused_adam

        if opt.weight_decay:
            parts.append(_decay(opt))
        parts.append(fused_adam(
            lr, b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
        tx = optax.chain(*parts)
        return tx if opt.ema_decay is None else with_ema(tx, opt.ema_decay)
    if opt.name == "adam":
        if opt.weight_decay:
            parts.append(_decay(opt))
        parts.append(
            optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
    elif opt.name == "adamw":
        parts.append(
            optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
        if opt.weight_decay:
            parts.append(_decay(opt))
    elif opt.name == "sgd":
        if opt.weight_decay:
            parts.append(_decay(opt))
        if opt.momentum:
            parts.append(optax.trace(decay=opt.momentum,
                                     nesterov=opt.nesterov))
    elif opt.name == "lamb":
        parts.append(
            optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
        if opt.weight_decay:
            parts.append(_decay(opt))
        parts.append(optax.scale_by_trust_ratio())
    else:
        raise ValueError(f"unknown optimizer {opt.name!r}")
    parts.append(optax.scale_by_learning_rate(lr))
    tx = optax.chain(*parts)
    if opt.ema_decay is not None:
        tx = with_ema(tx, opt.ema_decay)
    return tx
