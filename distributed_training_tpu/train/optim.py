"""Optimizer + LR schedule factory (optax).

Parity targets:
- Adam(lr = 1e-3 * world_size) — ``resnet/pytorch_ddp/ddp_train.py:97,110``
- DeepSpeed Adam betas [0.8, 0.999], eps 1e-8, wd 3e-7 —
  ``resnet/deepspeed/deepspeed_train.py:175-186``
- WarmupLR 0 → 1e-3 over 1000 steps — ``deepspeed_train.py:187-194``
- gradient_clipping 1.0 — ``deepspeed_train.py:195``
- ColossalAI HybridAdam(lr·world) — ``resnet/colossal/colossal_train.py:153``
  (HybridAdam is CUDA-fused Adam; the XLA-fused optax update is the TPU
  analogue — XLA fuses the whole update into the step program. A Pallas
  fused-Adam kernel lives in ``ops/fused_adam.py`` as the explicit-kernel
  variant.)
"""

from __future__ import annotations

import optax

from distributed_training_tpu.config import OptimizerConfig, SchedulerConfig


def make_schedule(opt: OptimizerConfig, sched: SchedulerConfig, world_size: int = 1):
    """Build the LR schedule; returns an optax schedule fn."""
    base_lr = opt.lr * (world_size if opt.scale_lr_by_world else 1)
    if sched.name == "constant":
        return optax.constant_schedule(base_lr)
    if sched.name == "warmup_lr":
        # DeepSpeed WarmupLR: linear warmup_min_lr → warmup_max_lr over
        # warmup_num_steps, then constant at warmup_max_lr.
        return optax.join_schedules(
            [
                optax.linear_schedule(
                    sched.warmup_min_lr, sched.warmup_max_lr,
                    sched.warmup_num_steps),
                optax.constant_schedule(sched.warmup_max_lr),
            ],
            boundaries=[sched.warmup_num_steps],
        )
    if sched.name == "cosine":
        if sched.total_steps is None:
            raise ValueError("cosine schedule needs total_steps")
        return optax.warmup_cosine_decay_schedule(
            init_value=sched.warmup_min_lr,
            peak_value=base_lr,
            warmup_steps=sched.warmup_num_steps,
            decay_steps=sched.total_steps,
        )
    raise ValueError(f"unknown scheduler {sched.name!r}")


def make_optimizer(
    opt: OptimizerConfig,
    sched: SchedulerConfig | None = None,
    world_size: int = 1,
) -> optax.GradientTransformation:
    """Build the full gradient transformation chain.

    Chain order mirrors the engines' semantics: clip the (already unscaled,
    already all-reduced) global grad norm, then the Adam update. Weight decay
    uses additive L2 (torch Adam ``weight_decay`` semantics, which is what
    DeepSpeed's config maps to) rather than decoupled AdamW.
    """
    sched = sched or SchedulerConfig()
    lr = make_schedule(opt, sched, world_size)
    parts = []
    if opt.grad_clip_norm is not None:
        parts.append(optax.clip_by_global_norm(opt.grad_clip_norm))
    if opt.name == "hybrid_adam":
        # Pallas fused Adam (ColossalAI HybridAdam analogue): one HBM pass
        # per tensor; lr/schedule handled inside the transformation.
        from distributed_training_tpu.ops.fused_adam import fused_adam

        if opt.weight_decay:
            parts.append(optax.add_decayed_weights(opt.weight_decay))
        parts.append(fused_adam(
            lr, b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
        return optax.chain(*parts)
    if opt.name == "adam":
        if opt.weight_decay:
            parts.append(optax.add_decayed_weights(opt.weight_decay))
        parts.append(
            optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
    elif opt.name == "adamw":
        parts.append(
            optax.scale_by_adam(b1=opt.betas[0], b2=opt.betas[1], eps=opt.eps))
        if opt.weight_decay:
            parts.append(optax.add_decayed_weights(opt.weight_decay))
    elif opt.name == "sgd":
        parts.append(optax.trace(decay=0.9, nesterov=False))
    else:
        raise ValueError(f"unknown optimizer {opt.name!r}")
    parts.append(optax.scale_by_learning_rate(lr))
    return optax.chain(*parts)
