"""Mixed-precision policy and dynamic loss scaling.

Reproduces the DeepSpeed fp16 engine semantics
(``resnet/deepspeed/deepspeed_train.py:197-208``) as *traced* state — the
reference updates its scaler in eager Python per step; here the scaler state
lives in the train state and every transition is a ``jnp.where`` select, so
the whole train step stays one XLA program with no recompilation
(SURVEY.md §7 hard parts: "fp16 dynamic loss scaling as traced control flow").

Semantics implemented (DeepSpeed DynamicLossScaler):
- dynamic scale starting at ``2**initial_scale_power`` (default 2^15);
- on overflow (non-finite grads): skip the update; if the hysteresis budget
  is exhausted, halve the scale (floored at ``min_loss_scale``), else just
  consume one hysteresis credit;
- after ``loss_scale_window`` consecutive good steps: double the scale and
  refill the hysteresis budget.

bf16 needs no scaling on TPU (same exponent range as fp32) — policy 'bf16'
uses scale ≡ 1 and the scaler becomes inert.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from distributed_training_tpu.config import PrecisionConfig


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype policy: params master copy, compute, and output dtypes."""

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32

    @staticmethod
    def from_config(cfg: PrecisionConfig) -> "Policy":
        compute = {
            "fp32": jnp.float32,
            "bf16": jnp.bfloat16,
            "fp16": jnp.float16,
        }[cfg.dtype]
        return Policy(param_dtype=jnp.float32, compute_dtype=compute)

    def cast_to_compute(self, tree):
        return jax.tree.map(lambda x: x.astype(self.compute_dtype), tree)


class LossScaleState(struct.PyTreeNode):
    """Traced dynamic loss-scaler state (a pytree carried in TrainState)."""

    scale: jnp.ndarray            # f32 scalar
    good_steps: jnp.ndarray       # i32 scalar — consecutive overflow-free steps
    hysteresis_left: jnp.ndarray  # i32 scalar — overflows tolerated before halving
    # Static config (not traced):
    window: int = struct.field(pytree_node=False, default=500)
    hysteresis: int = struct.field(pytree_node=False, default=2)
    min_scale: float = struct.field(pytree_node=False, default=1.0)
    max_scale: float = struct.field(pytree_node=False, default=float(2 ** 24))
    dynamic: bool = struct.field(pytree_node=False, default=True)

    @staticmethod
    def create(cfg: PrecisionConfig) -> "LossScaleState":
        if cfg.dtype != "fp16":
            # Inert scaler: scale 1, never updated.
            return LossScaleState(
                scale=jnp.float32(1.0),
                good_steps=jnp.int32(0),
                hysteresis_left=jnp.int32(1),
                dynamic=False,
            )
        if cfg.static_loss_scale is not None:
            return LossScaleState(
                scale=jnp.float32(cfg.static_loss_scale),
                good_steps=jnp.int32(0),
                hysteresis_left=jnp.int32(cfg.hysteresis),
                window=cfg.loss_scale_window,
                hysteresis=cfg.hysteresis,
                min_scale=cfg.min_loss_scale,
                dynamic=False,
            )
        return LossScaleState(
            scale=jnp.float32(cfg.initial_scale),
            good_steps=jnp.int32(0),
            hysteresis_left=jnp.int32(cfg.hysteresis),
            window=cfg.loss_scale_window,
            hysteresis=cfg.hysteresis,
            min_scale=cfg.min_loss_scale,
            dynamic=True,
        )

    def scale_loss(self, loss: jnp.ndarray) -> jnp.ndarray:
        return loss * self.scale.astype(loss.dtype)

    def unscale_grads(self, grads):
        inv = (1.0 / self.scale).astype(jnp.float32)
        return jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)

    def update(self, grads_finite: jnp.ndarray) -> "LossScaleState":
        """One traced scaler transition. ``grads_finite``: bool scalar."""
        if not self.dynamic:
            return self

        # Good path: count up; double at window boundary, refill hysteresis.
        good = self.good_steps + 1
        grow = good >= self.window
        good_scale = jnp.where(
            grow, jnp.minimum(self.scale * 2.0, self.max_scale), self.scale)
        good_steps_next = jnp.where(grow, 0, good)
        good_hyst = jnp.where(grow, jnp.int32(self.hysteresis), self.hysteresis_left)

        # Overflow path: consume hysteresis; halve only when exhausted.
        halve = self.hysteresis_left <= 1
        bad_scale = jnp.where(
            halve, jnp.maximum(self.scale / 2.0, self.min_scale), self.scale)
        bad_hyst = jnp.where(
            halve, jnp.int32(self.hysteresis), self.hysteresis_left - 1)

        return self.replace(
            scale=jnp.where(grads_finite, good_scale, bad_scale),
            good_steps=jnp.where(grads_finite, good_steps_next, 0),
            hysteresis_left=jnp.where(grads_finite, good_hyst, bad_hyst),
        )


def all_finite(tree) -> jnp.ndarray:
    """True iff every leaf of the tree is finite (overflow detector)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.bool_(True)
    checks = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(checks).all()


def select_tree(pred: jnp.ndarray, on_true, on_false):
    """Elementwise ``where`` over matching pytrees (skip-step on overflow)."""
    return jax.tree.map(
        lambda t, f: jnp.where(pred, t, f), on_true, on_false)


def commit_gradients(state, grads, new_batch_stats=None):
    """Apply unscaled grads to a TrainState with overflow skip/commit.

    The one copy of the dynamic-loss-scale transaction shared by the image
    step (``train/step.py``) and the LM step (``train/lm_step.py``):

    - dynamic scaler: detect overflow, apply-or-skip the whole update
      (``select_tree`` wheres every leaf, so the step counter must be
      recomputed explicitly — a skipped step must not tick the scheduler),
      and commit ``new_batch_stats`` only on good steps (an overflowed
      forward's running mean/var are non-finite and would poison BN
      permanently);
    - static/inert scaler: plain apply.

    Returns ``(new_state, grads_finite)``.
    """
    if state.loss_scale.dynamic:
        candidate = _with_ema_batch_stats(
            state.apply_gradients(grads), new_batch_stats)
        # Guard the UPDATE, not just the gradients: a finite-but-huge
        # unscaled grad (|g| > ~1.8e19, possible once the scale sits at its
        # floor under real divergence) passes an all_finite(grads) check
        # and then overflows inside the optimizer (e.g. Adam's g² > fp32
        # max → v = inf), committing a non-finite value PERMANENTLY —
        # a NaN param kills the model; an inf moment silently freezes its
        # weight (β·inf stays inf, updates become 0 forever). Checking the
        # candidate params AND optimizer state catches any update-path
        # overflow; the skip machinery then handles it like an overflowed
        # gradient (observed in the wild: round-2 fp16 convergence run,
        # one NaN in conv_init/kernel with loss_scale at 1.0).
        finite = (all_finite(grads) & all_finite(candidate.params)
                  & all_finite(candidate.opt_state))
        new_state = select_tree(
            finite,
            candidate.replace(loss_scale=state.loss_scale.update(finite)),
            state.replace(loss_scale=state.loss_scale.update(finite)),
        )
        new_state = new_state.replace(
            step=state.step + finite.astype(jnp.int32))
        if new_batch_stats is not None:
            new_state = new_state.replace(
                batch_stats=select_tree(
                    finite, new_batch_stats, state.batch_stats))
    else:
        finite = jnp.bool_(True)
        new_state = _with_ema_batch_stats(
            state.apply_gradients(grads), new_batch_stats)
        if new_batch_stats is not None:
            new_state = new_state.replace(batch_stats=new_batch_stats)
    return new_state, finite


def _with_ema_batch_stats(state, new_batch_stats):
    """Advance the EMA of BatchNorm running stats alongside the parameter
    EMA (``optim.with_ema`` sees only params; this is the one place both
    trees exist). No-op unless EMA is enabled AND the model carries stats.
    """
    from distributed_training_tpu.train.optim import EmaState

    es = state.opt_state
    if (not isinstance(es, EmaState) or new_batch_stats is None
            or not jax.tree.leaves(es.ema_batch_stats)):
        return state
    new_ema = jax.tree.map(
        lambda e, b: es.decay * e + (1.0 - es.decay) * b,
        es.ema_batch_stats, new_batch_stats)
    return state.replace(opt_state=es._replace(ema_batch_stats=new_ema))
