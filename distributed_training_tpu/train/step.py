"""The jitted train/eval step.

This is the TPU-native rewrite of the reference's shared hot loop
(SURVEY.md §3): per step the reference does
``host→device copy → forward → loss → backward (+NCCL all-reduce) →
optimizer.step() → loss.item() host sync``
(``resnet/pytorch_ddp/ddp_train.py:61-75``,
``resnet/deepspeed/deepspeed_train.py:143-158``,
``resnet/colossal/colossal_train.py:89-105``).

Here the whole transition — forward, loss, backward, gradient all-reduce,
loss-scale handling, clipping, Adam update, scheduler tick — is ONE XLA
program: ``(state, batch, rng) -> (state, metrics)`` under ``jax.jit`` over a
device mesh. Collectives are not written by hand: the batch is sharded over
the ``data`` axis while params are replicated (or ZeRO-sharded), so GSPMD
materializes the gradient all-reduce (or reduce-scatter) itself and XLA's
latency-hiding scheduler overlaps it with the backward pass — the knobs
DeepSpeed exposes for this (bucket sizes, ``overlap_comm``,
``deepspeed_train.py:214-216``) have no TPU equivalent because the compiler
owns the schedule.

Metrics stay on device; the host fetches them every ``log_interval`` steps
(no per-step ``loss.item()`` sync — SURVEY.md §7 "steady-state step without
host syncs").

An explicit-collective variant built on ``shard_map`` + ``lax.pmean`` is
provided for parity demonstration and for tests that pin down the collective
math (the DDP-equivalence property, SURVEY.md §4).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_tpu.parallel.sharding import (
    batch_sharding,
    replicated,
    state_shardings,
)
from distributed_training_tpu.runtime.mesh import AXIS_DATA
from distributed_training_tpu.train.precision import commit_gradients
from distributed_training_tpu.train.train_state import TrainState
from distributed_training_tpu.utils.compat import shard_map


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean softmax CE over the (local) batch — ``nn.CrossEntropyLoss``
    parity; ``label_smoothing`` blends the one-hot target with uniform mass
    (the standard ImageNet-recipe regularizer)."""
    if label_smoothing:
        n = logits.shape[-1]
        targets = optax.smooth_labels(
            jax.nn.one_hot(labels, n), label_smoothing)
        return optax.softmax_cross_entropy(logits, targets).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def _input_images(batch, input_affine=None):
    """Device-side input decode: uint8 batches (the decoded-cache loader
    ships raw u8 — 4× less host/PCIe traffic, and the cast fuses into the
    first conv on TPU) are mapped to float with a static affine.
    ``input_affine`` defaults to ToTensor's ``x/255``; the normalize_only
    augment mode passes ``(2/255, -1)`` (= Normalize(0.5, 0.5) after
    ToTensor). Float inputs pass through untouched (host already did it).
    """
    x = batch["image"]
    if x.dtype == jnp.uint8:
        scale, bias = input_affine or (1.0 / 255.0, 0.0)
        x = x.astype(jnp.float32) * scale + bias
    return x


def _forward_and_loss(state: TrainState, params, batch, rng, train: bool,
                      label_smoothing: float = 0.0, input_affine=None):
    variables = {"params": params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    images = _input_images(batch, input_affine)
    if train:
        rngs = dict(zip(("dropout", "gate"), jax.random.split(rng)))
        logits, mutated = state.apply_fn(
            variables, images, train=True,
            mutable=["batch_stats", "aux_loss"],
            rngs=rngs,
        )
        mutated = dict(mutated)
        new_batch_stats = mutated.get("batch_stats", state.batch_stats)
        aux = sum(jax.tree.leaves(mutated.get("aux_loss", {})), jnp.float32(0))
    else:
        logits = state.apply_fn(variables, images, train=False)
        new_batch_stats = state.batch_stats
        aux = jnp.float32(0)
    loss = cross_entropy_loss(logits, batch["label"], label_smoothing) + aux
    return loss, logits, new_batch_stats


def microbatches(batch, accum_steps: int, mesh: Mesh | None = None):
    """Reshape batch leaves [G, ...] -> [accum, G/accum, ...].

    Under GSPMD (``mesh`` given) the microbatch dim is constrained unsharded
    with ``data`` moved to dim 1, so every microbatch stays sharded the way
    a full batch would be (one redistribution of the input batch per step —
    cheap next to accum× the compute).
    """
    def resh(x):
        if x.shape[0] % accum_steps:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by "
                f"gradient_accumulation_steps={accum_steps}")
        x = x.reshape((accum_steps, x.shape[0] // accum_steps) + x.shape[1:])
        if mesh is not None:
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(
                    mesh, P(None, AXIS_DATA, *([None] * (x.ndim - 2)))))
        return x
    return jax.tree.map(resh, batch)


def accumulate_grads(params, batch, rng, accum_steps: int, mesh: Mesh | None,
                     micro_fn, init_carry):
    """Shared gradient-accumulation scan (used by the image and LM steps).

    ``micro_fn(params, mbatch, r, carry) -> (grads, new_carry, aux_tuple)``
    runs one microbatch's fwd/bwd; grads are summed across the scan and
    averaged, ``carry`` threads sequentially (e.g. BatchNorm EMA state),
    and each ``aux_tuple`` element comes back stacked along the scan dim.
    Returns ``(avg_grads, final_carry, stacked_aux)``.
    """
    mb = microbatches(batch, accum_steps, mesh)
    rngs = jax.random.split(rng, accum_steps)

    def body(c, xs):
        gsum, carry = c
        mbatch, r = xs
        grads, carry, aux = micro_fn(params, mbatch, r, carry)
        return (jax.tree.map(jnp.add, gsum, grads), carry), aux

    zeros = jax.tree.map(jnp.zeros_like, params)
    (gsum, carry), aux = jax.lax.scan(body, (zeros, init_carry), (mb, rngs))
    return jax.tree.map(lambda g: g / accum_steps, gsum), carry, aux


def _accum_grads_and_stats(state: TrainState, batch, rng, accum_steps: int,
                           mesh: Mesh | None, label_smoothing: float = 0.0,
                           input_affine=None):
    """Image-step accumulation: BatchNorm running stats thread sequentially
    through the scan (torch grad-accum semantics: every microbatch forward
    ticks the EMA). Returns (avg grads, mean loss, mean accuracy, stats)."""

    def micro_fn(params, mbatch, r, bs):
        def loss_fn(p):
            loss, logits, new_bs = _forward_and_loss(
                state.replace(batch_stats=bs), p, mbatch, r, train=True,
                label_smoothing=label_smoothing, input_affine=input_affine)
            return state.loss_scale.scale_loss(loss), (loss, logits, new_bs)

        grads, (loss, logits, new_bs) = jax.grad(
            loss_fn, has_aux=True)(params)
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == mbatch["label"]).astype(jnp.float32))
        return grads, new_bs, (loss, acc)

    grads, new_bs, (losses, accs) = accumulate_grads(
        state.params, batch, rng, accum_steps, mesh, micro_fn,
        state.batch_stats)
    return grads, losses.mean(), accs.mean(), new_bs


def fetch_offloaded_opt_state(state: TrainState) -> TrainState:
    """Move a pinned-host optimizer state to device memory (inside jit).

    The entry half of ZeRO-Offload: with ``cpu_offload`` the jitted step's
    in/out shardings keep the optimizer state in ``pinned_host`` memory;
    this transfer brings the shard on-device for the update, and jit's
    out_shardings stream the updated shard back — XLA schedules both
    around the compute. (Offload placement: ``parallel/sharding.py``.)
    """
    return state.replace(opt_state=jax.device_put(
        state.opt_state, jax.memory.Space.Device))


def global_grad_norm(grads) -> jnp.ndarray:
    """Global L2 norm of a gradient pytree, as an fp32 scalar.

    The on-device grad-norm metric (``observability.grad_norm`` knob) and
    the anomaly detector's spike signal. One fused reduction over grads
    that are already materialized for the update — it rides the metrics
    dict to the host at meter flushes only, costing no extra syncs.
    """
    return optax.global_norm(grads).astype(jnp.float32)


def _step_body(state: TrainState, batch, rng, *, axis_name: str | None = None,
               accum_steps: int = 1, mesh: Mesh | None = None,
               label_smoothing: float = 0.0, input_affine=None,
               cpu_offload: bool = False, grad_norm_metric: bool = False):
    """Shared step body for the GSPMD and shard_map paths.

    When ``axis_name`` is set (shard_map path), gradients/metrics are
    explicitly ``lax.pmean``-ed over that axis — the hand-written analogue of
    DDP's bucketed NCCL all-reduce. When None (GSPMD path), the same
    collective is inserted by the partitioner. ``accum_steps > 1`` scans
    microbatches through fwd/bwd before the single update — under
    shard_map the scan runs shard-locally and the one pmean follows
    (equal microbatches ⇒ mean of micro-means is the full mean).
    """
    if cpu_offload:
        state = fetch_offloaded_opt_state(state)
    if accum_steps > 1:
        grads, loss, accuracy, new_batch_stats = _accum_grads_and_stats(
            state, batch, rng, accum_steps, mesh, label_smoothing,
            input_affine)
    else:
        def loss_fn(params):
            loss, logits, new_bs = _forward_and_loss(
                state, params, batch, rng, train=True,
                label_smoothing=label_smoothing, input_affine=input_affine)
            return state.loss_scale.scale_loss(loss), (loss, logits, new_bs)

        grads, (loss, logits, new_batch_stats) = jax.grad(
            loss_fn, has_aux=True)(state.params)
        accuracy = jnp.mean(
            (jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))

    if axis_name is not None:
        grads = jax.lax.pmean(grads, axis_name)

    grads = state.loss_scale.unscale_grads(grads)

    new_state, finite = commit_gradients(state, grads, new_batch_stats)

    if axis_name is not None and new_batch_stats:
        # shard_map path: with SyncBN (model axis_name set) stats are already
        # identical across shards and this pmean is a no-op; with local BN
        # they diverge per shard, and the step's contract is replicated
        # output state — average them (torch DDP instead silently keeps
        # per-rank stats and checkpoints rank 0's; averaging is deterministic
        # and at least as principled).
        new_state = new_state.replace(
            batch_stats=jax.lax.pmean(new_state.batch_stats, axis_name))
        # Same for the EMA of the stats (commit_gradients averaged in the
        # per-shard values; EMA and pmean are both linear, so pmean-ing
        # after commutes with averaging the pmean-ed stats).
        from distributed_training_tpu.train.optim import EmaState

        es = new_state.opt_state
        if isinstance(es, EmaState) and jax.tree.leaves(es.ema_batch_stats):
            new_state = new_state.replace(opt_state=es._replace(
                ema_batch_stats=jax.lax.pmean(
                    es.ema_batch_stats, axis_name)))

    if axis_name is not None:
        loss = jax.lax.pmean(loss, axis_name)
        accuracy = jax.lax.pmean(accuracy, axis_name)
    metrics = {
        "loss": loss.astype(jnp.float32),
        "accuracy": accuracy,
        "loss_scale": new_state.loss_scale.scale,
        "grads_finite": finite.astype(jnp.float32),
    }
    if grad_norm_metric:
        # Post-pmean, post-unscale: the same (replicated) gradient the
        # optimizer consumes, so every host flushes the identical value.
        metrics["grad_norm"] = global_grad_norm(grads)
    return new_state, metrics


def make_train_step(
    mesh: Mesh,
    *,
    zero_stage: int = 0,
    donate: bool = True,
    grad_accum_steps: int = 1,
    label_smoothing: float = 0.0,
    input_affine: tuple | None = None,
    cpu_offload: bool = False,
    tensor_parallel: bool = False,
    tp_overlap: bool = False,
    grad_norm_metric: bool = False,
) -> Callable:
    """Build the GSPMD jitted train step for a mesh + ZeRO stage.

    Returns ``step(state, batch, rng) -> (state, metrics)``. Shardings are
    resolved lazily from the first state's structure (abstract eval — no
    device transfer) and cached on the returned closure.

    ``grad_accum_steps > 1``: the batch is the *effective* batch
    (micro × accum × world); the step scans accum microbatches through
    fwd/bwd and applies ONE optimizer update on the averaged gradient —
    DeepSpeed's ``gradient_accumulation_steps`` semantics, but as a single
    XLA program instead of engine-level micro-steps.

    ``tp_overlap=True`` (requires ``tensor_parallel``) swaps the
    declarative megatron schedule for the ring-overlapped collective
    matmul: the step becomes a full-manual shard_map whose row-parallel
    reductions are ppermute rings fused with the chunk matmuls
    (``parallel/collective_matmul.py``, replicated-activation layout — the
    one layout whose token count needn't divide by the TP size, which ViT's
    patches+cls rarely does).
    """
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if tp_overlap:
        if not tensor_parallel:
            raise ValueError("tp_overlap requires tensor_parallel=True "
                             "(it reschedules the megatron collectives)")
        return _make_overlap_tp_train_step(
            mesh, zero_stage=zero_stage, donate=donate,
            grad_accum_steps=grad_accum_steps,
            label_smoothing=label_smoothing, input_affine=input_affine,
            cpu_offload=cpu_offload, grad_norm_metric=grad_norm_metric)
    cache: dict[Any, Callable] = {}

    def ensure_jitted(state: TrainState, batch):
        treedef = jax.tree.structure((state, batch))
        fn = cache.get(treedef)
        if fn is None:
            if tensor_parallel:
                # Megatron placement by the shared rule table (ViT blocks:
                # q/k/v column-parallel over heads, out/fc2 row-parallel,
                # head class-parallel) + the same ZeRO/offload recruitment.
                from distributed_training_tpu.parallel.tensor_parallel import (
                    tp_state_shardings,
                )

                sshard = tp_state_shardings(state, mesh, zero_stage,
                                            cpu_offload=cpu_offload)
            else:
                sshard = state_shardings(state, mesh, zero_stage,
                                         cpu_offload=cpu_offload)
            bshard = {
                "image": batch_sharding(mesh, batch["image"].ndim),
                "label": batch_sharding(mesh, batch["label"].ndim),
            }
            fn = jax.jit(
                functools.partial(
                    _step_body, axis_name=None,
                    accum_steps=grad_accum_steps,
                    mesh=mesh if grad_accum_steps > 1 else None,
                    label_smoothing=label_smoothing,
                    input_affine=input_affine,
                    cpu_offload=cpu_offload,
                    grad_norm_metric=grad_norm_metric),
                in_shardings=(sshard, bshard, replicated(mesh)),
                out_shardings=(sshard, replicated(mesh)),
                donate_argnums=(0,) if donate else (),
            )
            cache[treedef] = fn
        return fn

    def step(state: TrainState, batch, rng):
        return ensure_jitted(state, batch)(state, batch, rng)

    # AOT hook for collective accounting (utils/hlo.py).
    step.lower = lambda state, batch, rng: ensure_jitted(state, batch).lower(
        state, batch, rng)
    return step


def _overlap_tp_grads_body(gstate: TrainState, batch, rng, *,
                           accum_steps: int, label_smoothing: float,
                           input_affine):
    """Full-manual grads body for the ring-overlapped image TP step.

    Runs the model under :func:`~distributed_training_tpu.parallel.
    collective_matmul.replicated_overlap_interceptor`: activations stay
    replicated over ``model`` (ViT's patches+cls token needn't divide by
    the TP size) and each row-parallel psum becomes a cols-mode
    matmul-reduce-scatter ring + ppermute all-gather. The rng folds per
    data/fsdp rank (decorrelated dropout across replicas, as the LM body
    does) but stays IDENTICAL across model ranks on purpose: the rings'
    partial-sum algebra assumes the replicated activations match, which
    diverged per-rank masks would desync.
    """
    import flax.linen as nn

    from distributed_training_tpu.parallel.collective_matmul import (
        overlap_finalize_grads,
        replicated_overlap_interceptor,
    )
    from distributed_training_tpu.runtime.mesh import AXIS_FSDP, AXIS_MODEL
    from distributed_training_tpu.utils.compat import axis_size

    rng = jax.random.fold_in(
        rng, jax.lax.axis_index(AXIS_DATA) * axis_size(AXIS_FSDP)
        + jax.lax.axis_index(AXIS_FSDP))
    with nn.intercept_methods(replicated_overlap_interceptor(AXIS_MODEL)):
        if accum_steps > 1:
            grads, loss, accuracy, _ = _accum_grads_and_stats(
                gstate, batch, rng, accum_steps, None, label_smoothing,
                input_affine)
        else:
            def loss_fn(params):
                loss, logits, new_bs = _forward_and_loss(
                    gstate, params, batch, rng, train=True,
                    label_smoothing=label_smoothing,
                    input_affine=input_affine)
                return gstate.loss_scale.scale_loss(loss), (loss, logits)

            grads, (loss, logits) = jax.grad(
                loss_fn, has_aux=True)(gstate.params)
            accuracy = jnp.mean(
                (jnp.argmax(logits, -1) == batch["label"]).astype(
                    jnp.float32))

    # Per-leaf completion: the one shared copy of the /tp-vs-pmean
    # gradient algebra (see collective_matmul.overlap_finalize_grads).
    grads = overlap_finalize_grads(grads)
    data_axes = (AXIS_DATA, AXIS_FSDP)
    grads = jax.lax.pmean(grads, data_axes)
    grads = gstate.loss_scale.unscale_grads(grads)
    loss = jax.lax.pmean(loss, data_axes + (AXIS_MODEL,))
    accuracy = jax.lax.pmean(accuracy, data_axes + (AXIS_MODEL,))
    return grads, (loss, accuracy)


def _make_overlap_tp_train_step(
    mesh: Mesh, *, zero_stage: int, donate: bool, grad_accum_steps: int,
    label_smoothing: float, input_affine: tuple | None, cpu_offload: bool,
    grad_norm_metric: bool = False,
) -> Callable:
    """Ring-overlapped TP image step (see :func:`make_train_step`).

    Mirrors the LM overlap scaffold: the full-manual shard_map computes
    grads + metrics only (params enter as rule-table shards; the optimizer
    state never enters the manual region), and ``commit_gradients`` runs
    under plain GSPMD where the ZeRO placements propagate.
    """
    from distributed_training_tpu.parallel.collective_matmul import (
        overlap_param_specs as param_specs,
    )
    from distributed_training_tpu.parallel.tensor_parallel import (
        tp_state_shardings,
    )

    cache: dict[Any, Callable] = {}

    def ensure_jitted(state: TrainState, batch):
        treedef = jax.tree.structure((state, batch))
        fn = cache.get(treedef)
        if fn is not None:
            return fn
        if jax.tree.leaves(state.batch_stats):
            raise NotImplementedError(
                "tp_overlap image step supports BatchNorm-free models only "
                "(ViT); BN statistics under a manual model axis are not "
                "wired — use the declarative TP schedule")
        sshard = tp_state_shardings(state, mesh, zero_stage,
                                    cpu_offload=cpu_offload, overlap=True)
        bshard = {
            "image": batch_sharding(mesh, batch["image"].ndim),
            "label": batch_sharding(mesh, batch["label"].ndim),
        }
        bspec = {k: v.spec for k, v in bshard.items()}

        def stepfn(state: TrainState, batch, rng):
            if cpu_offload:
                state = fetch_offloaded_opt_state(state)
            gstate = state.replace(opt_state=None)
            gspecs = jax.tree.map(lambda _: P(), gstate).replace(
                params=param_specs(state.params))
            sharded = shard_map(
                functools.partial(
                    _overlap_tp_grads_body, accum_steps=grad_accum_steps,
                    label_smoothing=label_smoothing,
                    input_affine=input_affine),
                mesh,
                in_specs=(gspecs, bspec, P()),
                out_specs=(param_specs(state.params), P()),
            )
            grads, (loss, accuracy) = sharded(gstate, batch, rng)
            new_state, finite = commit_gradients(state, grads)
            metrics = {
                "loss": loss.astype(jnp.float32),
                "accuracy": accuracy,
                "loss_scale": new_state.loss_scale.scale,
                "grads_finite": finite.astype(jnp.float32),
            }
            if grad_norm_metric:
                # Outside the manual region: grads are GSPMD-global here
                # (rule-table shards), so the norm reduces globally.
                metrics["grad_norm"] = global_grad_norm(grads)
            return new_state, metrics

        fn = jax.jit(
            stepfn,
            in_shardings=(sshard, bshard, replicated(mesh)),
            out_shardings=(sshard, replicated(mesh)),
            donate_argnums=(0,) if donate else (),
        )
        cache[treedef] = fn
        return fn

    def step(state: TrainState, batch, rng):
        return ensure_jitted(state, batch)(state, batch, rng)

    step.lower = lambda state, batch, rng: ensure_jitted(state, batch).lower(
        state, batch, rng)
    return step


def make_shard_map_train_step(mesh: Mesh, donate: bool = True,
                              label_smoothing: float = 0.0,
                              input_affine: tuple | None = None,
                              grad_accum_steps: int = 1,
                              grad_norm_metric: bool = False) -> Callable:
    """Explicit-collective DP train step (``shard_map`` + ``lax.pmean``).

    The hand-written formulation of DDP's gradient all-reduce
    (``resnet/pytorch_ddp/ddp_train.py:70``): each device computes grads on
    its batch shard, then ``pmean`` over the ``data`` axis; params and
    optimizer state replicated. Used to pin down collective math in tests
    and as the template for SyncBN (the model's ``axis_name`` must be
    ``'data'`` so BatchNorm stats pmean over the same axis).

    ``grad_accum_steps > 1`` scans microbatches shard-locally before the
    one pmean + update (local-BN stats thread through the scan, then the
    final per-shard stats are averaged like the single-shot path).
    """
    if grad_accum_steps < 1:
        raise ValueError(
            f"grad_accum_steps must be >= 1, got {grad_accum_steps}")

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state: TrainState, batch, rng):
        sharded = shard_map(
            functools.partial(_step_body, axis_name=AXIS_DATA,
                              accum_steps=grad_accum_steps,
                              label_smoothing=label_smoothing,
                              input_affine=input_affine,
                              grad_norm_metric=grad_norm_metric),
            mesh,
            in_specs=(
                jax.tree.map(lambda _: P(), state),
                {"image": P(AXIS_DATA), "label": P(AXIS_DATA)},
                P(),
            ),
            out_specs=(jax.tree.map(lambda _: P(), state), P()),
        )
        return sharded(state, batch, rng)

    return step


def make_eval_step(mesh: Mesh | None = None,
                   input_affine: tuple | None = None) -> Callable:
    """Jitted eval step: per-batch (top1_count, top5_count, example_count).

    The reference builds a ``test_dataloader`` but never consumes it
    (SURVEY.md §2.5); this wires the missing eval pass so the
    ``--target_acc`` gate (``resnet/colossal/colossal_train.py:43-46``) is
    functional. ``batch['mask']`` (0/1 per example) handles the ragged last
    batch instead of DistributedSampler's pad-by-repeat.
    """

    def eval_body(state: TrainState, batch):
        _, logits, _ = _forward_and_loss(
            state, state.params, batch, jax.random.PRNGKey(0), train=False,
            input_affine=input_affine)
        labels = batch["label"]
        correct = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        # Top-5 (the second ImageNet-standard metric); degenerates to top-1
        # when the label space is smaller than 5.
        k = min(5, logits.shape[-1])
        _, topk = jax.lax.top_k(logits, k)
        correct5 = jnp.any(topk == labels[:, None], axis=-1).astype(jnp.float32)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(correct)
        return (jnp.sum(correct * mask), jnp.sum(correct5 * mask),
                jnp.sum(mask))

    if mesh is None:
        return jax.jit(eval_body)

    # One jitted wrapper per batch key-set (mask present or not), hoisted out
    # of the per-batch call so eval batches hit jit's C++ fastpath.
    cache: dict[tuple, Callable] = {}

    def step(state, batch):
        key = tuple(sorted(batch))
        fn = cache.get(key)
        if fn is None:
            shardings = {k: batch_sharding(mesh, batch[k].ndim) for k in batch}
            fn = jax.jit(
                eval_body,
                in_shardings=(None, shardings),
                out_shardings=(replicated(mesh),) * 3,
            )
            cache[key] = fn
        return fn(state, batch)

    return step
