"""Train state: the single pytree carried through the jitted step.

Replaces the reference's mutable per-rank objects (DDP-wrapped module +
optimizer + scaler inside ``model_engine`` / ``booster``) with one immutable
functional state — params, BatchNorm running stats, optimizer state, dynamic
loss-scale state, and the step counter — so the whole
fwd → bwd → all-reduce → update transition is a pure function
``(state, batch) -> (state, metrics)`` compiled once by XLA
(SURVEY.md §3 "Shared hot loop").
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import core, struct

from distributed_training_tpu.train.precision import LossScaleState


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: core.FrozenDict | dict
    batch_stats: core.FrozenDict | dict
    opt_state: optax.OptState
    loss_scale: LossScaleState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, tx, batch_stats=None, loss_scale=None):
        batch_stats = batch_stats if batch_stats is not None else {}
        opt_state = tx.init(params)
        # Parameter EMA (optim.with_ema): seed the BatchNorm-statistics
        # average here — optax init only sees params, but evaluating EMA
        # weights against live-weight BN stats would skew the metric, so
        # commit_gradients maintains this tree alongside ema_params. Seeded
        # at create time so the opt_state pytree structure never changes
        # mid-training (a lazy first-step init would retrigger compilation).
        from distributed_training_tpu.train.optim import EmaState

        if isinstance(opt_state, EmaState) and jax.tree.leaves(batch_stats):
            opt_state = opt_state._replace(
                ema_batch_stats=jax.tree.map(
                    lambda b: jnp.array(b, copy=True), batch_stats))
        return cls(
            step=jnp.int32(0),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            loss_scale=loss_scale if loss_scale is not None else
            LossScaleState(
                scale=jnp.float32(1.0), good_steps=jnp.int32(0),
                hysteresis_left=jnp.int32(1), dynamic=False),
            apply_fn=apply_fn,
            tx=tx,
        )

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state)


def init_train_state(
    model,
    rng: jax.Array,
    input_shape: tuple,
    tx: optax.GradientTransformation,
    loss_scale: LossScaleState | None = None,
    input_dtype=jnp.float32,
) -> TrainState:
    """Initialize params + batch_stats with a dummy batch (shape-only trace)."""
    dummy = jnp.zeros(input_shape, input_dtype)
    variables = model.init({"params": rng, "dropout": rng}, dummy, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState.create(
        apply_fn=model.apply, params=params, tx=tx,
        batch_stats=batch_stats, loss_scale=loss_scale)


def param_count(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))
