"""The Trainer: epoch loop, eval, checkpointing, logging.

The framework-level replacement for the reference's three per-backend
``__main__`` blocks + ``train_epoch`` functions (SURVEY.md §1 L2): one
engine parameterized by :class:`TrainConfig`, with every dangling surface of
the reference wired for real — the eval loop the reference never runs
(``test_dataloader`` built and dropped, ``resnet/pytorch_ddp/ddp_train.py:96``),
the ``--target_acc`` assertion (``resnet/colossal/colossal_train.py:43-46``),
and checkpoint save/resume (``:40-42``).
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_tpu import checkpoint as ckpt_lib
from distributed_training_tpu.config import TrainConfig, effective_batch_sizes
from distributed_training_tpu.data.pipeline import (
    SkipBatches,
    build_dataloaders,
    to_global_batch,
)
from distributed_training_tpu.data.prefetch import DevicePrefetcher
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import (
    batch_sharding,
    place_state,
    state_shardings,
)
from distributed_training_tpu.runtime.coordinator import Coordinator
from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh, data_axis_size
from distributed_training_tpu.train.optim import make_optimizer
from distributed_training_tpu.train.precision import LossScaleState, Policy
from distributed_training_tpu.train.step import (
    make_eval_step,
    make_shard_map_train_step,
    make_train_step,
)
from distributed_training_tpu.train.train_state import init_train_state, param_count
from distributed_training_tpu.observability import (
    AnomalyError,
    TrainObservability,
    forward_flops,
    train_step_flops,
)
from distributed_training_tpu.observability import trace as trace_lib
from distributed_training_tpu.resilience import retry as retry_lib
from distributed_training_tpu.resilience.async_ckpt import (
    AsyncCheckpointWriter,
)
from distributed_training_tpu.resilience.chaos import ChaosMonkey
from distributed_training_tpu.resilience import chaos as chaos_lib
from distributed_training_tpu.runtime.preemption import PreemptionGuard
from distributed_training_tpu.utils.logging import EpochBar, MetricMeter
from distributed_training_tpu.utils.metrics_io import MetricsWriter
from distributed_training_tpu.utils.profiling import WallClock, trace


class Trainer:
    """End-to-end training engine over a device mesh."""

    def __init__(self, cfg: TrainConfig, mesh=None):
        self.cfg = cfg
        self.coord = Coordinator()
        # Field-by-name conversion so every MeshSpec axis (incl. additions
        # like `pipe`) reaches the mesh — a hand-copied subset here would
        # silently reassign those devices to the inferred data axis.
        self.mesh = mesh if mesh is not None else create_mesh(
            MeshConfig(**dataclasses.asdict(cfg.mesh)))
        self.world_size = data_axis_size(self.mesh)

        if cfg.moe.enabled and not cfg.model.startswith("moe"):
            raise NotImplementedError(
                f"MoE is only wired into the moe_* models (models/moe.py); "
                f"model {cfg.model!r} would silently train dense")
        if cfg.model == "transformer_lm":
            raise NotImplementedError(
                "transformer_lm is a token model; this Trainer drives image "
                "classification. Use train.lm_step.make_lm_train_step with "
                "a (data × sequence) mesh (see tests/test_lm_sequence_parallel.py)")

        policy = Policy.from_config(cfg.precision)
        model_kwargs = {}
        if cfg.remat:
            # Only set when asked: models without a remat attr (moe_mlp)
            # then raise loudly instead of silently not checkpointing.
            model_kwargs["remat"] = True
        if cfg.model.startswith("moe"):
            mesh_shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            model_kwargs |= dict(
                num_experts=tuple(cfg.moe.num_experts),
                top_k=cfg.moe.top_k,
                capacity_factor=cfg.moe.capacity_factor,
                min_capacity=cfg.moe.min_capacity,
                noisy_gate_policy=cfg.moe.noisy_gate_policy,
                mlp_type=cfg.moe.mlp_type,
                expert_axis="expert" if mesh_shape.get("expert", 1) > 1 else None,
            )
        # GSPMD path: BN statistics reduce over the global (sharded) batch
        # automatically — SyncBN for free, no axis name needed. Local BN
        # (sync_batchnorm=False, the torch-DDP-default semantics) instead
        # uses the explicit shard_map step where each shard computes its own
        # statistics (model axis_name stays None there too: BN only syncs
        # when the model is given the mesh axis).
        self.model = get_model(
            cfg.model,
            num_classes=cfg.data.num_classes,
            dtype=policy.compute_dtype,
            axis_name=None,
            **model_kwargs,
        )
        self.tx = make_optimizer(cfg.optimizer, cfg.scheduler, self.world_size)

        rng = jax.random.PRNGKey(cfg.seed)
        self.rng, init_rng = jax.random.split(rng)
        input_shape = (
            max(1, cfg.data.batch_size),
            cfg.data.image_size, cfg.data.image_size, 3)
        state = init_train_state(
            self.model, init_rng, input_shape, self.tx,
            loss_scale=LossScaleState.create(cfg.precision))
        mesh_shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        self.tp_size = mesh_shape.get("model", 1)
        if self.tp_size > 1:
            # Megatron TP for image transformers (round 4: the rule table
            # covers ViT blocks). A model without matching rules would
            # silently replicate its weights over the model axis — idle
            # chips wearing a TP banner.
            if not cfg.model.startswith("vit"):
                raise NotImplementedError(
                    f"a model mesh axis of {self.tp_size} is only wired for "
                    f"the vit_* models (parallel/tensor_parallel.py rule "
                    f"table); {cfg.model!r} would replicate over it")
            # device_put fails opaquely on non-divisible dims; check here
            # where the message can name the knob (mirrors lm_trainer).
            # tp_overlap keeps the class head replicated (no num_classes
            # constraint) but ring-scatters the row-parallel outputs over
            # the hidden dim, which must divide instead.
            checks = [("num_heads", self.model.num_heads),
                      ("mlp_dim", self.model.mlp_dim)]
            checks.append(("hidden_size", self.model.hidden_size)
                          if cfg.tp_overlap
                          else ("num_classes", cfg.data.num_classes))
            for what, n in checks:
                if n % self.tp_size:
                    raise ValueError(
                        f"tensor parallelism size {self.tp_size} must "
                        f"divide {what} (= {n})")
            import functools

            from distributed_training_tpu.parallel.tensor_parallel import (
                tp_state_shardings,
            )

            shardings_fn = functools.partial(tp_state_shardings,
                                             overlap=cfg.tp_overlap)
        else:
            shardings_fn = state_shardings
        self.shardings = shardings_fn(state, self.mesh, cfg.zero.stage,
                                      cpu_offload=cfg.zero.cpu_offload)
        self.state = place_state(state, self.shardings)

        # Local-vs-sync BN only differs for models that actually carry
        # BatchNorm state; BN-free models (ViT, MoE-MLP) always take the
        # GSPMD path, where ZeRO placement composes.
        has_bn = bool(jax.tree.leaves(state.batch_stats))
        uses_gspmd_step = cfg.sync_batchnorm or not has_bn
        # Resolve DeepSpeed batch-triple semantics once, where world size is
        # known (accum may be derived from global_batch_size; both the
        # GSPMD and the shard_map local-BN steps accumulate).
        # batch_size is per *chip* (DDP parity: per-GPU mini-batch ×
        # world), so scale by every mesh device — under a data×expert mesh
        # the data axis is smaller than the chip count, but each chip still
        # contributes batch_size examples of work.
        self.train_gbs, self.eval_gbs, self.grad_accum = effective_batch_sizes(
            cfg, int(self.mesh.devices.size), allow_derive=True)
        # uint8 batches (decoded-cache loader) defer ToTensor/Normalize to
        # the device, fused into the first conv; the affine encodes the
        # augment mode's normalization. Float batches ignore it. Kept on
        # self so the precise-BN refresh normalizes identically.
        input_affine = self._input_affine = (
            (2.0 / 255.0, -1.0) if cfg.data.augment == "normalize_only"
            else (1.0 / 255.0, 0.0))
        if uses_gspmd_step:
            self.train_step = make_train_step(
                self.mesh, zero_stage=cfg.zero.stage,
                grad_accum_steps=self.grad_accum,
                label_smoothing=cfg.label_smoothing,
                input_affine=input_affine,
                cpu_offload=cfg.zero.cpu_offload,
                tensor_parallel=self.tp_size > 1,
                tp_overlap=cfg.tp_overlap and self.tp_size > 1,
                grad_norm_metric=cfg.observability.grad_norm)
        else:
            if cfg.zero.stage != 0:
                raise NotImplementedError(
                    "sync_batchnorm=False uses the explicit shard_map DP "
                    "step, which has no ZeRO sharding; use zero stage 0 "
                    "with local BN")
            if cfg.zero.cpu_offload:
                raise NotImplementedError(
                    "cpu_offload rides the ZeRO opt-state sharding of the "
                    "GSPMD step; the local-BN shard_map step has neither")
            self.train_step = make_shard_map_train_step(
                self.mesh, label_smoothing=cfg.label_smoothing,
                input_affine=input_affine,
                grad_accum_steps=self.grad_accum,
                grad_norm_metric=cfg.observability.grad_norm)
        self.eval_step = make_eval_step(self.mesh, input_affine=input_affine)
        self.meter = MetricMeter(cfg.log_interval)
        # Forensics default next to the run's durable artifacts.
        obs_dump_dir = cfg.observability.dump_dir or os.path.join(
            cfg.checkpoint.directory, "flight")
        # Span tracing (off by default → trace is None and every
        # integration point below stays span-free; observability/trace.py).
        self.trace, trace_path = trace_lib.session_for_run(
            cfg.observability.trace, default_dir=obs_dump_dir)
        # The clock always runs when the flight recorder (or the span
        # trace) does: goodput attribution costs two perf_counter reads
        # per phase, and the per-epoch report print stays gated on
        # wall_clock_breakdown.
        self.clock = WallClock(
            cfg.wall_clock_breakdown or cfg.observability.flight_recorder
            or self.trace is not None, trace=self.trace)
        self.metrics_writer = MetricsWriter(
            cfg.tensorboard_dir, cfg.metrics_jsonl,
            enabled=self.coord.is_master())
        # Flight instruments: analytic step FLOPs (effective batch — MFU is
        # accumulation-aware by construction) + the flush-boundary hooks.
        self.obs = TrainObservability(
            cfg.observability,
            step_flops=train_step_flops(forward_flops(
                self.model, image_size=cfg.data.image_size,
                batch=self.train_gbs)),
            n_devices=int(self.mesh.devices.size),
            clock=self.clock, is_master=self.coord.is_master(),
            printer=self.coord.print,
            dump_dir=obs_dump_dir,
            extra_provider=self._resilience_snapshot,
            trace=self.trace, trace_path=trace_path,
            num_processes=jax.process_count())
        # Resilience: deterministic fault injection + the background
        # checkpoint writer (single-process only — multihost snapshots
        # need orbax's own per-host gathers, so those save synchronously).
        self.chaos = (ChaosMonkey(cfg.chaos,
                                  process_index=jax.process_index(),
                                  trace=self.trace)
                      if cfg.chaos.active else None)
        self._ckpt_writer = None
        if cfg.checkpoint.async_save and jax.process_count() == 1:
            self._ckpt_writer = AsyncCheckpointWriter(
                post_save=(self.chaos.after_checkpoint_save
                           if self.chaos else None),
                printer=self.coord.print, trace=self.trace)
        self._sync_saves = 0
        self._guard: PreemptionGuard | None = None
        self._stats_refresh = None
        self._global_step = 0
        self._epoch_step = 0
        self.coord.print(
            f"[trainer] model={cfg.model} params={param_count(state.params):,} "
            f"mesh={dict(zip(self.mesh.axis_names, self.mesh.devices.shape))} "
            f"plugin={cfg.plugin} zero_stage={cfg.zero.stage} "
            f"dtype={cfg.precision.dtype}"
            + (f" grad_accum={self.grad_accum}" if self.grad_accum > 1 else ""))

    # -- resilience ---------------------------------------------------------
    def _save_ckpt(self, epoch: int, *, sync: bool = False, **kw) -> None:
        """One checkpoint save through the configured path: async writer
        (snapshot now, persist in background) or synchronous orbax.
        ``sync=True`` is the preemption contract — durable before return."""
        d = self.cfg.checkpoint.directory
        if self._ckpt_writer is not None:
            self._ckpt_writer.save(d, epoch, self.state, sync=sync, **kw)
            return
        path = ckpt_lib.save_checkpoint(d, epoch, self.state, **kw)
        self._sync_saves += 1
        if self.chaos is not None:
            self.chaos.after_checkpoint_save(path, epoch)

    def _prune_ckpts(self) -> None:
        """Retention sweep, ordered after any in-flight async save."""
        d, keep = self.cfg.checkpoint.directory, self.cfg.checkpoint.keep
        if self._ckpt_writer is not None:
            self._ckpt_writer.prune(d, keep)
        else:
            ckpt_lib.prune_checkpoints(d, keep)

    def _resilience_snapshot(self) -> dict:
        """Extra flight-dump section: checkpoint durability + I/O retry
        counters (rendered by tools/flight_report.py)."""
        c = {"io_retries": retry_lib.total_retries(),
             "saves_committed": self._sync_saves, "saves_failed": 0}
        if self._ckpt_writer is not None:
            c["saves_committed"] += \
                self._ckpt_writer.counters["saves_committed"]
            c["saves_failed"] = self._ckpt_writer.counters["saves_failed"]
        if self.chaos is not None:
            c["chaos_faults"] = dict(self.chaos.counters)
        return {"resilience": c}

    # -- data ---------------------------------------------------------------
    def make_loaders(self):
        # Train consumes effective batches (micro × accum × world); eval
        # stays micro-sized — accumulation exists because effective-batch
        # forwards don't fit.
        return build_dataloaders(
            self.cfg, self.coord, seed=self.cfg.seed,
            global_batch_size=self.train_gbs,
            eval_global_batch_size=self.eval_gbs)

    def _batch_shardings(self, batch):
        return {k: batch_sharding(self.mesh, v.ndim) for k, v in batch.items()}

    def _batches(self, loader):
        """Device-resident batches, prefetched ``cfg.data.prefetch`` ahead
        (host augment + DMA overlap the previous step's compute; the 'data'
        wall-clock phase then reads ~0 by construction). The synchronous
        prefetch=0 path keeps per-batch 'data' attribution."""
        place = lambda b: to_global_batch(  # noqa: E731
            b, self.mesh, self._batch_shardings(b))
        if self.cfg.data.prefetch < 1:
            def sync_gen():
                for b in loader:
                    with self.clock.phase("data"):
                        gb = place(b)
                    yield gb
            return sync_gen()
        return DevicePrefetcher(loader, place, depth=self.cfg.data.prefetch)

    # -- train --------------------------------------------------------------
    def train_epoch(self, epoch: int, loader, skip_steps: int = 0) -> dict:
        """One epoch; ``skip_steps`` drops that many leading batches of the
        epoch's deterministic shuffle (step-accurate preemption resume —
        the pre-preemption prefix must not train twice)."""
        loader.set_epoch(epoch)
        if skip_steps:
            self.coord.print(
                f"[trainer] resuming epoch {epoch} at step {skip_steps}")
            loader = SkipBatches(loader, skip_steps)
        self._epoch_step = skip_steps
        self.obs.on_epoch()  # boundary pause ≠ a straggler step
        bar = EpochBar(len(loader), epoch, self.cfg.num_epochs,
                       self.coord.is_master())
        gbatch = None
        for gbatch in self._batches(loader):
            with self.clock.phase("step"):
                self.rng, step_rng = jax.random.split(self.rng)
                self.state, metrics = self.train_step(
                    self.state, gbatch, step_rng)
            with self.clock.phase("log"):
                # Host-side counter: metrics stay device-resident until the
                # meter's interval flush — no per-step loss.item() sync.
                self._global_step += 1
                self._epoch_step += 1
                fetched = self.meter.push(self._global_step, metrics)
                # Chaos BEFORE the recorder's timestamp: an injected
                # slow-step stall then lands in THIS step's wall delta
                # (like a real straggler's would), so the cross-host
                # aggregation attributes the injected step itself.
                if self.chaos is not None:
                    self.chaos.on_step(self._global_step)
                self.obs.on_step(self._global_step)
                bar.update()
                if fetched:
                    extras = self.obs.on_flush(
                        self.meter.last, batch=gbatch, state=self.state,
                        step_fn=self.train_step, rng=self.rng)
                    bar.set_postfix(self.meter.last)
                    self.metrics_writer.write(
                        self.meter.last["step"],
                        {**self.meter.last, **extras})
            if self._guard is not None and self._guard.should_stop(
                    at_sync_point=fetched):
                break
        # Flush the epoch tail only if steps are actually pending — an
        # unconditional write would duplicate the last interval's point.
        if self.meter.pending:
            flushed = self.meter.flush()
            extras = self.obs.on_flush(
                flushed, batch=gbatch, state=self.state,
                step_fn=self.train_step, rng=self.rng)
            self.metrics_writer.write(flushed["step"], {**flushed, **extras})
        bar.set_postfix(self.meter.last)
        bar.close()
        if self.cfg.wall_clock_breakdown:
            self.coord.print(f"[wall_clock] {self.clock.report()}")
        return self.meter.last

    # -- eval ---------------------------------------------------------------
    def _eval_state(self):
        """The state evaluation sees: EMA params (and EMA BatchNorm stats —
        averaged weights need matching normalization statistics) when
        configured."""
        if (self.cfg.optimizer.ema_decay is not None
                and self.cfg.eval_with_ema):
            from distributed_training_tpu.train.optim import (
                ema_batch_stats,
                ema_params,
            )

            state = self.state.replace(
                params=ema_params(self.state.opt_state))
            ema_bs = ema_batch_stats(self.state.opt_state)
            if jax.tree.leaves(ema_bs):
                state = state.replace(batch_stats=ema_bs)
            return state
        return self.state

    def _refresh_batch_stats(self, train_loader, num_batches: int) -> None:
        """Precise-BN: re-estimate running stats with the CURRENT params
        (train-mode forwards, no optimizer) so eval normalizes with
        statistics that match the weights it is evaluating — the EMA lags
        by design and goes stale whenever params move fast.

        This is a TRUE average over the ``num_batches`` per-batch moments,
        not an EMA tick from the stale stats (which would leave a
        ``momentum**N`` stale residue — ~59% at N=5). A train-mode forward
        never *reads* the running stats (it normalizes by batch
        statistics), so ticking from a zero baseline returns exactly
        ``(1 - momentum) * batch_stat``; dividing recovers the raw moment,
        which is then averaged across batches."""
        import itertools

        if self._stats_refresh is None:
            from distributed_training_tpu.train.step import _input_images

            from distributed_training_tpu.models.resnet import BN_MOMENTUM

            affine = self._input_affine  # the step's input normalization
            # The zoo-wide BN momentum — needed to undo the single EMA tick
            # and recover the raw batch statistic.
            momentum = BN_MOMENTUM

            def batch_stat(state, batch, idx):
                rngs = {
                    "dropout": jax.random.fold_in(jax.random.PRNGKey(0), idx),
                    "gate": jax.random.fold_in(jax.random.PRNGKey(1), idx),
                }
                zeros = jax.tree.map(jnp.zeros_like, state.batch_stats)
                _, mut = state.apply_fn(
                    {"params": state.params, "batch_stats": zeros},
                    _input_images(batch, affine), train=True,
                    mutable=["batch_stats", "aux_loss"], rngs=rngs)
                ticked = dict(mut).get("batch_stats", zeros)
                return jax.tree.map(lambda s: s / (1.0 - momentum), ticked)

            self._stats_refresh = jax.jit(batch_stat)

        head = itertools.islice(iter(train_loader), num_batches)
        acc, n = None, 0
        for gbatch in self._batches(head):
            b = self._stats_refresh(self.state, gbatch, n)
            acc = b if acc is None else jax.tree.map(jnp.add, acc, b)
            n += 1
        if n:
            self.state = self.state.replace(
                batch_stats=jax.tree.map(lambda a: a / n, acc))

    def evaluate(self, loader, train_loader=None) -> float:
        """Top-1 accuracy (the ``target_acc`` metric); top-5 is kept on
        ``self.last_eval`` and written to the metric sinks."""
        k = self.cfg.eval_precise_bn_batches
        uses_ema_stats = (
            self.cfg.optimizer.ema_decay is not None
            and self.cfg.eval_with_ema)
        # Refresh only when eval will actually read self.state.batch_stats:
        # BN-free models have nothing to refresh, and the EMA-eval path
        # replaces the stats with the EMA copy (refreshing raw stats there
        # would be paid-for compute that eval never sees).
        if (k and train_loader is not None and not uses_ema_stats
                and jax.tree.leaves(self.state.batch_stats)):
            self._refresh_batch_stats(train_loader, k)
        eval_state = self._eval_state()
        correct = correct5 = total = 0.0
        for gbatch in self._batches(loader):
            c, c5, t = self.eval_step(eval_state, gbatch)
            correct += float(c)
            correct5 += float(c5)
            total += float(t)
        self.last_eval = {"top1": correct / max(total, 1.0),
                          "top5": correct5 / max(total, 1.0)}
        self.metrics_writer.write(
            self._global_step, self.last_eval, prefix="eval")
        return self.last_eval["top1"]

    # -- full run -----------------------------------------------------------
    def fit(self) -> dict:
        if self.chaos is not None:
            # Data loaders poll the process-global chaos registration for
            # transient-I/O injection; scoped to this fit only.
            chaos_lib.install(self.chaos)
        try:
            result = self._fit()
            # Surfaces a deferred anomaly raise whose trace window the
            # run's end cut short (forensics were dumped at trigger time).
            self.obs.close()
            return result
        except AnomalyError:
            raise
        except BaseException:
            # Crash forensics: the flight recorder's last ring of steps,
            # flushed metrics, and goodput — written before the exception
            # propagates (the process may be about to die).
            self.obs.on_crash()
            raise
        finally:
            if self.chaos is not None:
                chaos_lib.uninstall()
            if self._ckpt_writer is not None:
                # Drain + stop the writer thread; a background save
                # failure was already counted/printed — it must not mask
                # this run's real outcome or exception.
                self._ckpt_writer.close(raise_on_error=False)
            self.obs.close(raise_pending=False)  # idempotent trace teardown
            # Both exits (incl. preemption — the process is about to die in
            # its SIGTERM grace window — and the target_acc raise) must
            # flush buffered TensorBoard events.
            self.metrics_writer.close()

    def _fit(self) -> dict:
        cfg = self.cfg
        train_loader, eval_loader = self.make_loaders()

        start_epoch = 0
        start_step = 0
        resume = ckpt_lib.resolve_resume(cfg.checkpoint)
        if resume >= 0:
            self.state, start_epoch, start_step = ckpt_lib.restore_checkpoint(
                cfg.checkpoint.directory, resume, self.state)
            self.state = place_state(self.state, self.shardings)
            # Metric sinks must continue the restored step axis, not restart
            # at 1 and double back over the pre-preemption history.
            self._global_step = int(jax.device_get(self.state.step))
            self.coord.print(f"[trainer] resumed at epoch {start_epoch}")

        final_acc = None
        last_eval_epoch = -1
        preempted = False
        with trace(cfg.profile_dir), PreemptionGuard() as guard:
            self._guard = guard
            for epoch in range(start_epoch, cfg.num_epochs):
                self.train_epoch(
                    epoch, train_loader,
                    skip_steps=start_step if epoch == start_epoch else 0)
                if guard.should_stop():
                    # Preempted mid-epoch: next_epoch points back at this
                    # (partial) epoch, and epoch_step records how far into
                    # its deterministic shuffle training got — the resume
                    # skips exactly that prefix (no batch trains twice). A
                    # SIGTERM landing in the final log interval lets the
                    # epoch COMPLETE first; that save must roll over to the
                    # next epoch, or the resume would refuse a skip ==
                    # len(loader).
                    preempted = True
                    if cfg.checkpoint.save_on_preemption:
                        done = self._epoch_step >= len(train_loader)
                        next_ep = epoch + 1 if done else epoch
                        estep = 0 if done else self._epoch_step
                        with self.clock.phase("ckpt"):
                            # sync: the process dies in its grace window
                            # right after this — the save must be durable
                            # (and verified) before returning.
                            self._save_ckpt(epoch, sync=True,
                                            next_epoch=next_ep,
                                            epoch_step=estep)
                        self.coord.print(
                            f"[trainer] SIGTERM: saved preemption checkpoint "
                            f"(resumes at epoch {next_ep} step {estep})")
                    break
                if cfg.eval_every and (epoch + 1) % cfg.eval_every == 0:
                    with self.clock.phase("eval"):
                        final_acc = self.evaluate(eval_loader, train_loader)
                    last_eval_epoch = epoch + 1
                    self.coord.print(
                        f"[eval] epoch {epoch + 1}: top-1 {final_acc:.4f}")
                if cfg.checkpoint.interval and (
                        epoch + 1) % cfg.checkpoint.interval == 0:
                    with self.clock.phase("ckpt"):
                        self._save_ckpt(epoch)
                        self._prune_ckpts()
        self._guard = None
        if self._ckpt_writer is not None:
            # The run's saves must be durable before fit() reports done;
            # a background failure is surfaced as counters + a print, not
            # as a crash of the (successful) training run.
            self._ckpt_writer.wait(raise_on_error=False)
        if preempted:
            return {"final_acc": None, "preempted": True,
                    "last_metrics": self.meter.last,
                    "steps": int(jax.device_get(self.state.step))}

        # --target_acc gate, parsed-but-never-used in the reference
        # (colossal_train.py:43-46) — functional here. Re-evaluate if the
        # last eval predates the final epoch (eval_every ∤ num_epochs), so
        # the gate judges the *final* model, not a stale accuracy.
        if cfg.target_acc is not None:
            if final_acc is None or last_eval_epoch != cfg.num_epochs:
                final_acc = self.evaluate(eval_loader, train_loader)
            if final_acc < cfg.target_acc:
                raise RuntimeError(
                    f"target accuracy {cfg.target_acc} not reached "
                    f"(got {final_acc:.4f})")
        return {"final_acc": final_acc, "preempted": False,
                "last_metrics": self.meter.last,
                "steps": int(jax.device_get(self.state.step))}
