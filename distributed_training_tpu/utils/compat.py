"""JAX API + platform shims shared across the framework."""

from __future__ import annotations

import jax


def on_tpu() -> bool:
    """True when the first visible device is a TPU (Pallas ops use this to
    pick compiled vs interpret mode)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis, across jax versions.

    ``lax.axis_size`` only exists on newer jax; the classic spelling —
    ``psum`` of the constant 1 over the axis, which constant-folds to the
    (static) axis size — works everywhere a collective would.
    """
    lax = jax.lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def supports_partial_manual() -> bool:
    """True when this jax's ``shard_map`` accepts ``axis_names`` (jax >=
    0.6) — i.e. the partial-manual compositions (TP×SP, PP×TP, PP×EP,
    SP-accum, SP×PP) can run at all. The test suite gates its xfail marks
    on this so the known-broken compositions don't burn CI minutes
    re-raising the same TypeError on older jax, yet re-run (and XPASS,
    flagging the marks for removal) the moment the environment upgrades.
    """
    import inspect

    try:
        return "axis_names" in inspect.signature(_shard_map).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        return False


def shard_map(fn, mesh, in_specs, out_specs, axis_names=None):
    """``shard_map`` without replication checking, across jax versions.

    The replication-check flag was renamed ``check_rep`` → ``check_vma``;
    both spellings are handled here so callers don't each carry the
    try/except.

    ``axis_names`` selects *partial-manual* mode: only the named mesh axes
    are manual (specs refer to them); the remaining axes stay automatic, so
    GSPMD keeps propagating shardings through the body. This is how the
    explicit strategies compose with declarative TP: ring attention /
    pipeline collectives run manually over ``sequence``/``pipe`` while the
    megatron ``model``-axis psums are inserted by GSPMD inside the shards.
    """
    kwargs = {} if axis_names is None else {"axis_names": set(axis_names)}
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False, **kwargs)
    except TypeError:
        if axis_names is not None:
            raise RuntimeError(
                "this jax version's shard_map has no axis_names "
                "(partial-manual) support; TP×SP / PP×TP composition "
                "requires jax >= 0.6")
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
