"""JAX API + platform shims shared across the framework."""

from __future__ import annotations

import jax


def on_tpu() -> bool:
    """True when the first visible device is a TPU (Pallas ops use this to
    pick compiled vs interpret mode)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(fn, mesh, in_specs, out_specs):
    """``shard_map`` without replication checking, across jax versions.

    The replication-check flag was renamed ``check_rep`` → ``check_vma``;
    both spellings are handled here so callers don't each carry the
    try/except.
    """
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
