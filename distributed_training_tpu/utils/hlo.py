"""Compiled-HLO collective accounting.

The only multi-chip *scaling* evidence a single-host environment can
produce: for a compiled step, enumerate the communication ops XLA actually
materialized — kind, count, payload bytes — and compare them against what
the strategy's placement implies (DP all-reduce ≈ gradient bytes; ZeRO
reduce-scatter + all-gather; TP per-block psums; ring 2 ppermutes per hop).
This is the TPU analogue of inspecting the reference's NCCL call sites
(``/root/reference/resnet/pytorch_ddp/ddp_train.py:84`` — DDP's bucketed
all-reduce is *implicit* there too; the wire truth lives in the compiled
engine either way).

Counts are STATIC program counts: a collective inside a ``while``/``scan``
body appears once in the text regardless of trip count (the ring's
2·(n−1) dynamic ppermutes show as 2 static ops inside the loop body).
``tools/collective_accounting.py`` renders the committed artifact;
``tests/test_collectives.py`` asserts the per-strategy kinds.
"""

from __future__ import annotations

import re
from typing import Any

# HLO opcode → canonical kind. *-start forms are the async halves of the
# same op (the *-done half carries no payload and is skipped).
_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
    "ragged-all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "s4": 1, "u4": 1, "f8e4m3fn": 1,
    "f8e5m2": 1,
}

_ARRAY_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# First " op(" token after the shape text. Works for tuple shapes too
# (which may contain /*index=N*/ comments and layout annotations): no
# lowercase token directly followed by "(" occurs inside a shape, and the
# per-instruction metadata strings only appear after the opcode.
_OP_RE = re.compile(r"\s([a-z][a-z0-9\-]*)\(")


def _shape_bytes(shape_text: str) -> int:
    """Total payload bytes of an HLO shape string (array or tuple)."""
    total = 0
    for dtype, dims in _ARRAY_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_accounting(compiled_text: str) -> dict[str, dict[str, Any]]:
    """Parse compiled HLO text into ``{kind: {count, bytes}}``.

    ``bytes`` sums the output-shape payloads of every instance of the kind
    (for an all-reduce that IS the reduced tensor size; for an all-gather
    the gathered result; async ``*-start`` tuples include carried operand
    aliases, so bytes there are an upper bound).
    """
    out: dict[str, dict[str, Any]] = {}
    for line in compiled_text.splitlines():
        s = line.strip()
        if not (s.startswith("%") or s.startswith("ROOT ")):
            continue
        parts = s.split(" = ", 1)
        if len(parts) != 2:
            continue
        rhs = parts[1]
        m = _OP_RE.search(" " + rhs)
        if not m:
            continue
        op = m.group(1)
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[: -len("-start")]
        if op not in _COLLECTIVE_KINDS:
            continue
        shape_text = rhs[: m.start()]
        entry = out.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _shape_bytes(shape_text)
    return out


def step_collectives(step, state, batch, rng) -> dict[str, dict[str, Any]]:
    """Collective accounting for a step built by this framework's factories
    (anything exposing the ``.lower(state, batch, rng)`` AOT hook)."""
    compiled = step.lower(state, batch, rng).compile()
    return collective_accounting(compiled.as_text())
