"""Async, master-gated training logs.

The reference fetches ``loss.item()`` every step — a device→host sync that
serializes the pipeline (SURVEY.md §2.5) — and gates tqdm on the master rank
(``resnet/colossal/colossal_train.py:88``). Here metrics stay on device as
jax.Arrays; the meter keeps references and only calls ``.item()`` (blocking)
at ``log_interval`` boundaries, so the steady-state step never waits on the
host. tqdm is used when available, plain prints otherwise.

The no-hidden-transfer claim is a PINNED contract, not prose:
``tests/test_transfer_guard.py`` runs steady-state train steps (image and
LM) with the whole between-flush window wrapped in
``jax.transfer_guard("disallow")`` — any implicit transfer the backend
can observe fails the suite (on the CPU test mesh that is every hidden
host→device upload, e.g. an unplaced numpy batch; on a real accelerator
the same wrapper also rejects implicit device→host fetches like a stray
``float(metric)``). The meter's flush itself uses the explicit
``jax.device_get``, which the guard permits by design: explicit fetches at
log intervals ARE the contract. The observability hooks
(``observability/hooks.py``) keep the same rule — per-step cost is one
host ``perf_counter()`` ring write; MFU, memory telemetry, and anomaly
detection all read at flush boundaries from values the meter already
fetched.
"""

from __future__ import annotations

import time
from typing import Any

import jax

try:
    from tqdm import tqdm
except ImportError:  # pragma: no cover
    tqdm = None


class MetricMeter:
    """Holds device metric refs; materializes lazily at log intervals."""

    def __init__(self, log_interval: int = 100):
        self.log_interval = max(1, log_interval)
        self._pending: list[tuple[int, dict[str, Any]]] = []
        self.last: dict[str, float] = {}

    @property
    def pending(self) -> bool:
        """True when unfetched device metrics are queued (a flush now would
        materialize new values rather than repeat ``last``)."""
        return bool(self._pending)

    def push(self, step: int, metrics: dict[str, Any]) -> bool:
        """Record device metrics; returns True when a fetch happened."""
        self._pending.append((step, metrics))
        if len(self._pending) >= self.log_interval:
            self.flush()
            return True
        return False

    def flush(self) -> dict[str, float]:
        if not self._pending:
            return self.last
        # Only the newest entry is materialized; older refs are dropped
        # unfetched (their buffers were never copied to host).
        step, metrics = self._pending[-1]
        self._pending.clear()
        self.last = {k: float(jax.device_get(v)) for k, v in metrics.items()}
        self.last["step"] = step
        return self.last


class EpochBar:
    """Master-only progress bar: tqdm parity with interval postfix updates."""

    def __init__(self, total: int, epoch: int, num_epochs: int, is_master: bool):
        self.is_master = is_master
        desc = f"Epoch [{epoch + 1}/{num_epochs}]"
        if is_master and tqdm is not None:
            self.bar = tqdm(total=total, desc=desc)
        else:
            self.bar = None
            self.desc = desc
            self.total = total
            self.count = 0
            self.t0 = time.time()

    def update(self, n: int = 1) -> None:
        if self.bar is not None:
            self.bar.update(n)
        else:
            self.count += n

    def set_postfix(self, metrics: dict[str, float]) -> None:
        if self.bar is not None:
            self.bar.set_postfix(
                {k: f"{v:.4g}" for k, v in metrics.items() if k != "step"})
        elif self.is_master:
            body = " ".join(f"{k}={v:.4g}" for k, v in metrics.items())
            rate = self.count / max(time.time() - self.t0, 1e-9)
            print(f"{self.desc} {self.count}/{self.total} {body} ({rate:.1f} it/s)",
                  flush=True)

    def close(self) -> None:
        if self.bar is not None:
            self.bar.close()
