"""Metric sinks: TensorBoard scalars and/or JSONL event lines.

The reference's only observability is a per-step tqdm loss postfix
(SURVEY.md §5 "Metrics / logging": "No W&B/TensorBoard"); this module is
the durable-sink extension the survey plans ("optional TensorBoard
scalars"). Writes happen only at the meter's ``log_interval`` flushes —
the values are already on host then, so sinks add no device syncs.
"""

from __future__ import annotations

import json
import os
from typing import Any


class MetricsWriter:
    """Fan-out writer for flushed metric dicts (master process only).

    - ``tensorboard_dir``: scalar summaries via ``torch.utils.tensorboard``
      (imported lazily — it drags in protobuf/tensorboard only when asked).
    - ``jsonl_path``: one ``{"step": N, ...}`` object per line, appended;
      trivially greppable/plottable without any reader dependency.

    Both optional; with neither this is a no-op sink.
    """

    def __init__(self, tensorboard_dir: str | None = None,
                 jsonl_path: str | None = None, enabled: bool = True):
        self._tb = None
        self._jsonl = None
        if not enabled:
            return
        if tensorboard_dir:
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=tensorboard_dir)
        if jsonl_path:
            d = os.path.dirname(os.path.abspath(jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._jsonl = open(jsonl_path, "a", buffering=1)

    def write(self, step: int, metrics: dict[str, Any],
              prefix: str = "train") -> None:
        scalars = {k: float(v) for k, v in metrics.items() if k != "step"}
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(f"{prefix}/{k}", v, global_step=step)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(
                {"step": int(step), "prefix": prefix, **scalars}) + "\n")

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
            self._tb = None
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
