"""Profiling hooks.

The reference pins NVTX/DLProf wheels but never imports them, and ships
DeepSpeed's ``wall_clock_breakdown`` flag turned off
(``resnet/deepspeed/deepspeed_train.py:209``; SURVEY.md §5 "Tracing").
TPU-native equivalents:

- ``jax.profiler`` traces (TensorBoard trace viewer) via :func:`trace`;
- ``jax.named_scope`` as the NVTX-range analogue (re-exported);
- :class:`WallClock` — a working ``wall_clock_breakdown``: wall-time split
  into data / step / logging phases per epoch.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

named_scope = jax.named_scope


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class WallClock:
    """Phase timer: ``with clock.phase('data'): ...``; report per epoch.

    Attribution is EXCLUSIVE: entering a nested phase pauses the outer
    one (e.g. the eval loop's internal 'data' staging accrues to 'data',
    not double-counted under 'eval'), so the totals partition the tracked
    wall-time — which is what lets the flight recorder's goodput read
    them as fractions that sum to 1 (``observability/flight_recorder.py``).

    With a ``trace`` session attached, every phase additionally emits one
    complete span (entry → exit, INCLUSIVE of nested phases — the
    timeline wants the enclosing extent; exclusivity is the totals'
    concern) onto ``track``, which is how both trainers get their
    step/eval/ckpt Perfetto tracks without touching a single phase call
    site (``observability/trace.py``).
    """

    def __init__(self, enabled: bool = False, *, trace=None,
                 track: str = "train"):
        self.enabled = enabled
        self.trace = trace
        self.track = track
        self.totals: dict[str, float] = defaultdict(float)
        # Run-lifetime totals: ``report()`` clears ``totals`` per epoch,
        # but the flight recorder's goodput wants the whole run.
        self.lifetime: dict[str, float] = defaultdict(float)
        self._stack: list[list] = []  # [name, segment_start, entry] frames

    def _accrue(self, name: str, dt: float) -> None:
        self.totals[name] += dt
        self.lifetime[name] += dt

    @property
    def current_phase(self) -> str | None:
        """The innermost open phase name, or None outside any phase.
        Read lock-free from other threads (the /healthz endpoint): the
        stack only ever gains/loses whole frames under the GIL, and a
        transiently stale answer is fine for a liveness probe."""
        try:
            return self._stack[-1][0]
        except IndexError:  # popped between the probe's check and read
            return None

    @contextlib.contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        now = time.perf_counter()
        if self._stack:  # pause the outer phase
            outer = self._stack[-1]
            self._accrue(outer[0], now - outer[1])
        self._stack.append([name, now, now])
        try:
            yield
        finally:
            now = time.perf_counter()
            frame = self._stack.pop()
            self._accrue(frame[0], now - frame[1])
            if self._stack:  # resume the outer phase's segment
                self._stack[-1][1] = now
            if self.trace is not None:
                self.trace.complete(frame[0], frame[2], now,
                                    track=self.track)

    def snapshot(self) -> dict[str, float]:
        """Run-lifetime phase totals, never cleared (the flight
        recorder's goodput reads this at dump time; ``report`` keeps its
        clearing per-epoch semantics)."""
        return dict(self.lifetime)

    def report(self) -> dict[str, float]:
        out = dict(self.totals)
        self.totals.clear()
        return out
