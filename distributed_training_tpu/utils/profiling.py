"""Profiling hooks.

The reference pins NVTX/DLProf wheels but never imports them, and ships
DeepSpeed's ``wall_clock_breakdown`` flag turned off
(``resnet/deepspeed/deepspeed_train.py:209``; SURVEY.md §5 "Tracing").
TPU-native equivalents:

- ``jax.profiler`` traces (TensorBoard trace viewer) via :func:`trace`;
- ``jax.named_scope`` as the NVTX-range analogue (re-exported);
- :class:`WallClock` — a working ``wall_clock_breakdown``: wall-time split
  into data / step / logging phases per epoch.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

named_scope = jax.named_scope


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class WallClock:
    """Phase timer: ``with clock.phase('data'): ...``; report per epoch."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.totals: dict[str, float] = defaultdict(float)

    @contextlib.contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0

    def report(self) -> dict[str, float]:
        out = dict(self.totals)
        self.totals.clear()
        return out
