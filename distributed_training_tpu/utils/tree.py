"""Pytree path utilities shared by the sharding-rule modules."""

from __future__ import annotations


def path_keys(path) -> list[str]:
    """Key-path entries (DictKey/SequenceKey/attr) as a list of strings."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return parts


def path_str(path) -> str:
    """Key path joined as ``a/b/c`` — the form sharding rule tables match."""
    return "/".join(path_keys(path))
