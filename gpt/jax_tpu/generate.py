"""Text generation CLI for checkpoints trained by ``gpt/jax_tpu/train.py``.

Completes the LM workload's lifecycle (train → checkpoint → generate); the
reference has no inference surface at all (SURVEY.md §0). Model flags must
match the training run so the checkpoint restores; sampling flags control
the decode loop (``distributed_training_tpu/inference/sampler.py``).

Byte-level I/O: prompts are encoded as UTF-8 bytes (the LM's default
vocab is 256 = one token per byte), completions decoded the same way.
"""

from __future__ import annotations

import argparse
import sys


def add_argument() -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="TransformerLM generation")
    parser.add_argument("--prompt", type=str, default="The ",
                        help="UTF-8 prompt, byte-tokenized")
    parser.add_argument("--vocab-size", type=int, default=256)
    parser.add_argument("--num-layers", type=int, default=4)
    parser.add_argument("--num-heads", type=int, default=4)
    parser.add_argument("--hidden-dim", type=int, default=256)
    parser.add_argument("--max-len", type=int, default=2048)
    parser.add_argument("--dtype", type=str, default="fp32",
                        choices=["bf16", "fp16", "fp32"])
    parser.add_argument("--head-bias", action=argparse.BooleanOptionalAction,
                        default=False,
                        help="must mirror training (train.py defaults to no "
                             "head bias since round 5); a mismatched flag "
                             "fails the checkpoint tree restore")
    parser.add_argument("--logits-dtype", type=str, default="bf16",
                        choices=["fp32", "bf16"],
                        help="head compute dtype (train.py's default is "
                             "bf16; params are unaffected, so this only "
                             "needs to match for bit-identical logits)")
    # MoE model flags (must match training, or the checkpoint tree won't
    # restore — the decode path runs MoE FFNs position-wise like training).
    parser.add_argument("--moe", action="store_true", default=False)
    parser.add_argument("--num-experts", type=int, nargs="+", default=[8])
    parser.add_argument("--moe-top-k", type=int, default=1,
                        help="MoE gate top-k (train.py calls this --top-k; "
                             "here --top-k is the sampling filter)")
    parser.add_argument("--min-capacity", type=int, default=0)
    parser.add_argument("--mlp-type", type=str, default="standard",
                        choices=["standard", "residual"])
    parser.add_argument("-c", "--checkpoint", type=str, default="./checkpoint")
    parser.add_argument("-r", "--resume", type=int, default=-1,
                        help="epoch to load; -1 = latest (random init if "
                             "no checkpoint exists)")
    parser.add_argument("--ema-decay", type=float, default=None,
                        help="must mirror training: an --ema-decay run saves "
                             "an EMA-wrapped opt_state, and the restore "
                             "template has to match the checkpoint tree")
    parser.add_argument("--use-ema", action="store_true", default=False,
                        help="sample from the EMA parameter average instead "
                             "of the raw params (requires --ema-decay)")
    parser.add_argument("--max-new-tokens", type=int, default=128)
    parser.add_argument("--temperature", type=float, default=1.0,
                        help="0 = greedy")
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--eos-id", type=int, default=None)
    parser.add_argument("--num-beams", type=int, default=None,
                        help="deterministic beam search instead of sampling")
    parser.add_argument("--length-penalty", type=float, default=0.0,
                        help="(beam) GNMT length-penalty alpha")
    parser.add_argument("--seed", type=int, default=0)
    return parser.parse_args()


def main() -> int:
    args = add_argument()

    import jax
    import numpy as np

    from distributed_training_tpu.inference import Generator, SampleConfig
    from distributed_training_tpu.inference.restore import (
        build_lm_and_restore,
        moe_kwargs_from_flags,
    )

    moe_kwargs = moe_kwargs_from_flags(
        enabled=args.moe, num_experts=args.num_experts,
        top_k=args.moe_top_k, min_capacity=args.min_capacity,
        mlp_type=args.mlp_type)

    model, params, _ = build_lm_and_restore(
        vocab_size=args.vocab_size,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        hidden_dim=args.hidden_dim,
        max_len=args.max_len,
        dtype=args.dtype,
        head_bias=args.head_bias,
        logits_dtype=args.logits_dtype,
        moe_kwargs=moe_kwargs,
        checkpoint=args.checkpoint,
        resume=args.resume,
        ema_decay=args.ema_decay,
        use_ema=args.use_ema,
        seed=args.seed,
        printer=lambda msg: print(f"[generate] {msg}"),
    )

    prompt = np.frombuffer(args.prompt.encode("utf-8"), np.uint8)
    if (prompt >= args.vocab_size).any():
        bad = sorted(set(int(b) for b in prompt[prompt >= args.vocab_size]))
        raise SystemExit(
            f"prompt bytes {bad} are outside vocab_size={args.vocab_size}; "
            "byte-level prompts need --vocab-size 256 (or an ASCII-only "
            "prompt for smaller vocabs)")
    prompt = prompt.astype(np.int32)

    def decode_bytes(toks):
        return bytes(int(t) % 256 for t in toks).decode(
            "utf-8", errors="replace")

    if args.num_beams:
        from distributed_training_tpu.inference import BeamConfig, BeamSearcher

        beams, scores = BeamSearcher(model, params, BeamConfig(
            num_beams=args.num_beams,
            max_new_tokens=args.max_new_tokens,
            eos_id=args.eos_id,
            length_penalty=args.length_penalty,
        ))(prompt)
        for i in range(args.num_beams):
            print(f"[generate] beam {i} (score {float(scores[0, i]):.3f}): "
                  f"{args.prompt!r} -> {decode_bytes(beams[0, i])!r}")
        return 0

    gen = Generator(model, params, SampleConfig(
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        eos_id=args.eos_id,
    ))
    out = gen(prompt, rng=jax.random.PRNGKey(args.seed))[0]
    print(f"[generate] {args.prompt!r} -> {decode_bytes(out)!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
