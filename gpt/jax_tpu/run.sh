python train.py --sp 4 -b 8 --seq-len 512 -c ./ckpt-lm
