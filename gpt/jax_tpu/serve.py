"""Continuous-batching serving CLI for TransformerLM checkpoints.

The consumer end of ``distributed_training_tpu/serving/``: reads one
prompt per line (stdin by default, or ``--prompts-file``), serves them
all through the continuous-batching engine — up to ``--max-batch``
sequences decode together, freed slots refill mid-flight — and prints
completions in submission order plus an SLA summary (TTFT/TPOT
percentiles, throughput, queue depth).

Model flags must mirror the training run so the checkpoint restores
(same contract as ``generate.py``); byte-level I/O (vocab 256 = one
token per byte) like the rest of the gpt/jax_tpu surface.

    echo -e "The \\nOnce upon" | python gpt/jax_tpu/serve.py \\
        -c ./checkpoint --max-batch 8 --max-new-tokens 64
"""

from __future__ import annotations

import argparse
import os
import sys

# Script-style backend dir (like tools/serve_bench.py): make the package
# importable when run from anywhere, not just the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def add_argument() -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="TransformerLM continuous-batching serving")
    parser.add_argument("--prompts-file", type=str, default=None,
                        help="one UTF-8 prompt per line; default: stdin")
    # Serving knobs (ServeConfig).
    parser.add_argument("--max-batch", type=int, default=8,
                        help="decode slots (sequences batched/iteration)")
    parser.add_argument("--max-len", type=int, default=None,
                        help="per-slot KV-cache tokens (prompt + output); "
                             "default: the model's --max-len table")
    parser.add_argument("--max-new-tokens", type=int, default=128)
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="0 = greedy")
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--eos-id", type=int, default=None)
    parser.add_argument("--kv-page-size", type=int, default=8,
                        help="paged KV cache (docs/SERVING.md): KV "
                             "memory is a pool of this-many-token pages "
                             "with per-slot page tables; pages allocate "
                             "as written, so admission gates on actual "
                             "footprint, not max-len. 0 = legacy "
                             "contiguous per-slot reservation")
    parser.add_argument("--kv-pages", type=int, default=None,
                        help="KV pool size in pages; default max_batch x "
                             "ceil(budget/page_size) = the legacy "
                             "capacity. Smaller oversubscribes: bursts "
                             "queue on pages instead of slots")
    parser.add_argument("--prefill-chunk", type=int, default=64,
                        help="chunked prefill (paged mode): prompt "
                             "tokens prefilled per decode iteration, "
                             "riding the fused step so admission never "
                             "blocks decode")
    parser.add_argument("--prefill-bucket", type=int, default=64,
                        help="LEGACY prefill (--kv-page-size 0): prompt "
                             "lengths pad to a multiple of this (bounds "
                             "prefill compile count)")
    parser.add_argument("--prefix-cache",
                        action=argparse.BooleanOptionalAction,
                        default=False,
                        help="radix-tree prefix cache over the paged "
                             "pool (docs/SERVING.md 'Prefix caching'): "
                             "finished requests' KV page chains stay "
                             "indexed and a prompt sharing a "
                             "page-aligned prefix aliases them, "
                             "prefilling only the tail — shared system "
                             "prompts prefill once. Bitwise-neutral; "
                             "flushed at every hot-swap barrier. "
                             "Requires paged mode (--kv-page-size > 0)")
    parser.add_argument("--prefix-cache-pages", type=int, default=None,
                        help="cap on pool pages the prefix-cache trie "
                             "may hold (LRU leaves evict past it); "
                             "default unbounded within the pool")
    # Speculative decoding (docs/SERVING.md "Speculative decoding").
    parser.add_argument("--spec-k", type=int, default=0,
                        help="speculative decoding: draft tokens "
                             "proposed per slot per iteration and "
                             "verified by the serving model in one "
                             "fixed-width dispatch; acceptance is "
                             "lossless (greedy output stays bitwise "
                             "identical to sequential decode, sampled "
                             "output distribution-identical). 0 = off")
    parser.add_argument("--spec-drafter", type=str, default="ngram",
                        choices=["ngram", "gpt"],
                        help="'ngram' = prompt-lookup drafter (zero "
                             "extra params); 'gpt' = greedy draft "
                             "model over a --spec-draft-window token "
                             "window, self-drafting with the serving "
                             "weights (hot-swap keeps it fresh). A "
                             "separately trained draft checkpoint "
                             "plugs in via the Engine API "
                             "(serving/speculative.py::GPTDrafter)")
    parser.add_argument("--spec-ngram", type=int, default=3,
                        help="longest context suffix the n-gram "
                             "drafter matches (backs off to 1)")
    parser.add_argument("--spec-draft-window", type=int, default=16,
                        help="gpt drafter: context tokens re-run per "
                             "draft step")
    # Quantized execution (docs/SERVING.md "Quantized execution").
    parser.add_argument("--quantize-weights", action="store_true",
                        default=False,
                        help="symmetric per-channel int8 for the "
                             "transformer matmul weights (embedding, "
                             "attention, MLP); layernorms, biases and "
                             "the logits head stay full precision. "
                             "Quantization happens ONCE at engine "
                             "construction and at hot-swap staging "
                             "time on the watcher thread — never "
                             "inside the decode loop. Deterministic: "
                             "two quantized runs are bitwise-identical")
    parser.add_argument("--kv-dtype", type=str, default=None,
                        choices=["int8"],
                        help="paged KV cache storage dtype: 'int8' "
                             "stores pool pages as int8 with per-row "
                             "per-head scales, quantizing on scatter "
                             "and dequantizing in the gather inside "
                             "the same compiled programs (inventory "
                             "stays at 2). Requires paged mode "
                             "(--kv-page-size > 0). Default: model "
                             "dtype")
    # SLO tiers + multi-tenant fairness (docs/SERVING.md "Tiered
    # scheduling & preemption").
    parser.add_argument("--num-tiers", type=int, default=1,
                        help="SLO tiers: priority 0 = highest "
                             "(interactive); larger tiers are shed and "
                             "preempted first under overload. 1 = the "
                             "single-FIFO behavior")
    parser.add_argument("--priority", type=int, default=0,
                        help="SLO tier for this CLI's prompts (a "
                             "multi-tier deployment submits per-request "
                             "via Engine.submit(priority=, tenant=))")
    parser.add_argument("--tenant", type=str, default="default",
                        help="tenant principal for this CLI's prompts "
                             "(per-tenant quota + weighted-fair "
                             "admission)")
    parser.add_argument("--tenant-quota", type=int, default=None,
                        help="max concurrently seated requests per "
                             "tenant (None = uncapped)")
    parser.add_argument("--tier-reserved-slots", type=int, default=0,
                        help="decode slots held back from non-top "
                             "tiers so tier-0 arrivals always find "
                             "headroom")
    parser.add_argument("--tier-reserved-pages", type=int, default=0,
                        help="KV pool pages held back from non-top "
                             "tiers (paged engine)")
    parser.add_argument("--no-preempt", action="store_true",
                        default=False,
                        help="disable lossless preempt-and-requeue of "
                             "lower tiers (tiers then only order the "
                             "queue)")
    # Graceful degradation (resilience round; docs/RESILIENCE.md).
    parser.add_argument("--max-queue-depth", type=int, default=None,
                        help="bounded admission: beyond this depth the "
                             "newest queued lower-tier request is shed "
                             "to admit higher-tier work; the incoming "
                             "request itself is shed with a typed "
                             "QueueFullError when nothing lower-tier "
                             "is queued")
    parser.add_argument("--ttft-deadline-ms", type=float, default=None,
                        help="evict requests still queued past this "
                             "time-to-first-token deadline (finish "
                             "reason 'timeout')")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="evict requests still decoding past this "
                             "total deadline (partial tokens returned, "
                             "finish reason 'timeout')")
    # Live weight hot-swap (docs/SERVING.md "Live weight hot-swap").
    parser.add_argument("--watch-ckpt-dir", type=str, default=None,
                        help="zero-drain continuous deployment: watch "
                             "this checkpoint directory and hot-swap "
                             "each newly COMMITTED epoch into the "
                             "running engine at a decode-iteration "
                             "boundary (verified staging; torn/corrupt "
                             "candidates are quarantined and never "
                             "touch the engine). SIGHUP triggers one "
                             "immediate poll; SIGUSR1 re-arms the "
                             "previously served weights (rollback)")
    parser.add_argument("--watch-interval", type=float, default=2.0,
                        help="seconds between checkpoint-watcher polls")
    # Crash-durable serving (serving/journal.py; docs/RESILIENCE.md
    # "Crash-durable serving").
    parser.add_argument("--journal-dir", type=str, default=None,
                        help="write-ahead request journal: accepted "
                             "requests are durable before submit "
                             "returns; on restart with the same flags "
                             "the log replays BEFORE serving — "
                             "finished results re-deliver exactly "
                             "once, unfinished requests resume and "
                             "complete bitwise-equal to the "
                             "uninterrupted run, and already-consumed "
                             "prompt lines are skipped")
    parser.add_argument("--journal-fsync", type=str, default="batch",
                        choices=["none", "batch", "always"],
                        help="journal durability: 'none' = OS page "
                             "cache (survives kill -9, not power "
                             "loss), 'batch' = one fsync per writer "
                             "flush, 'always' = fsync per record")
    parser.add_argument("--journal-segment-bytes", type=int,
                        default=1 << 20,
                        help="journal segment rotation threshold "
                             "(live state compacts into a fresh "
                             "segment past this; bounded growth)")
    parser.add_argument("--flight-dump", type=str, default=None,
                        help="write a flight-recorder JSON here at exit "
                             "(tools/flight_report.py renders it)")
    parser.add_argument("--ledger-out", type=str, default=None,
                        help="write each completed request's latency "
                             "ledger (serving/ledger.py) as one "
                             "strict-JSON list: per-request (cause, "
                             "start, end) intervals partitioning its "
                             "wall lifetime — queue wait, prefill, "
                             "decode, preemption requeue/recompute, "
                             "swap barriers, journal admission, "
                             "crash-recovery downtime — plus the "
                             "conservation verdict")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="live telemetry plane: /metrics (Prometheus "
                             "text, incl. TTFT/TPOT histograms + KV/slot "
                             "utilization), /healthz (serving/swapping/"
                             "draining/drained phase + weights_epoch and "
                             "swap counters), /vars, /timeseries and "
                             "/alerts, scrapeable while the engine "
                             "serves (loopback; 0 = ephemeral)")
    # Serving control room (serving/timeseries.py + serving/alerts.py;
    # docs/OBSERVABILITY.md "Serving SLO alerting & incident capture").
    parser.add_argument("--slo-rules", type=str, default=None,
                        help="SLO burn-rate alerting: 'default' for "
                             "the built-in rule set, or ';'-separated "
                             "name:metric[/den]>objective[@fast,slow]"
                             "[xburn][~clear] clauses "
                             "(serving/alerts.py); evaluated every "
                             "--sample-every iterations; off when "
                             "unset")
    parser.add_argument("--incident-dir", type=str, default=None,
                        help="write one atomic incident bundle per "
                             "alert fire (firing alert + alert log + "
                             "last time-series window + flight "
                             "snapshot) into this directory, off the "
                             "hot path (tools/incident_report.py "
                             "renders them); requires --slo-rules")
    parser.add_argument("--sample-every", type=int, default=16,
                        help="telemetry time-series sample cadence in "
                             "iterations (never wall time)")
    parser.add_argument("--trace", action=argparse.BooleanOptionalAction,
                        default=False,
                        help="span-level Perfetto trace: one track per "
                             "decode slot with each request's queued/"
                             "prefill/decode lifecycle (open in "
                             "ui.perfetto.dev or tools/trace_report.py)")
    parser.add_argument("--trace-dir", type=str, default="./trace",
                        help="trace output directory")
    parser.add_argument("--json", action="store_true", default=False,
                        help="emit the SLA stats as one JSON line")
    # Model flags (mirror training; generate.py contract).
    parser.add_argument("--vocab-size", type=int, default=256)
    parser.add_argument("--num-layers", type=int, default=4)
    parser.add_argument("--num-heads", type=int, default=4)
    parser.add_argument("--hidden-dim", type=int, default=256)
    parser.add_argument("--model-max-len", type=int, default=2048,
                        help="positional-table length used at training")
    parser.add_argument("--dtype", type=str, default="fp32",
                        choices=["bf16", "fp16", "fp32"])
    parser.add_argument("--head-bias", action=argparse.BooleanOptionalAction,
                        default=False)
    parser.add_argument("--logits-dtype", type=str, default="bf16",
                        choices=["fp32", "bf16"])
    # MoE model flags (must match training; generate.py contract — the
    # engine's vmapped decode runs MoE FFNs position-wise like training).
    parser.add_argument("--moe", action="store_true", default=False)
    parser.add_argument("--num-experts", type=int, nargs="+", default=[8])
    parser.add_argument("--moe-top-k", type=int, default=1)
    parser.add_argument("--min-capacity", type=int, default=0)
    parser.add_argument("--mlp-type", type=str, default="standard",
                        choices=["standard", "residual"])
    parser.add_argument("-c", "--checkpoint", type=str, default="./checkpoint")
    parser.add_argument("-r", "--resume", type=int, default=-1,
                        help="epoch to load; -1 = latest (random init if "
                             "no checkpoint exists)")
    parser.add_argument("--ema-decay", type=float, default=None,
                        help="must mirror training (restore-template tree)")
    parser.add_argument("--use-ema", action="store_true", default=False,
                        help="serve the EMA parameter average")
    parser.add_argument("--seed", type=int, default=0)
    return parser.parse_args()


def main() -> int:
    args = add_argument()

    import numpy as np

    from distributed_training_tpu.config import ServeConfig
    from distributed_training_tpu.inference.restore import (
        build_lm_and_restorer,
        moe_kwargs_from_flags,
    )
    from distributed_training_tpu.inference.sampler import CacheBudgetError
    from distributed_training_tpu.runtime.preemption import PreemptionGuard
    from distributed_training_tpu.serving import (
        DrainingError,
        Engine,
        HotSwapper,
        QueueFullError,
    )

    moe_kwargs = moe_kwargs_from_flags(
        enabled=args.moe, num_experts=args.num_experts,
        top_k=args.moe_top_k, min_capacity=args.min_capacity,
        mlp_type=args.mlp_type)

    model, params, restored_epoch, restore_fn = build_lm_and_restorer(
        vocab_size=args.vocab_size,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        hidden_dim=args.hidden_dim,
        max_len=args.model_max_len,
        dtype=args.dtype,
        head_bias=args.head_bias,
        logits_dtype=args.logits_dtype,
        moe_kwargs=moe_kwargs,
        checkpoint=args.checkpoint,
        resume=args.resume,
        ema_decay=args.ema_decay,
        use_ema=args.use_ema,
        seed=args.seed,
        printer=lambda msg: print(f"[serve] {msg}", file=sys.stderr),
    )

    from distributed_training_tpu.observability.trace import (
        session_for_cli,
    )

    trace, trace_path = session_for_cli(args.trace, args.trace_dir,
                                        "serve")

    engine = Engine(model, params, ServeConfig(
        max_batch=args.max_batch,
        max_len=args.max_len,
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        eos_id=args.eos_id,
        kv_page_size=args.kv_page_size or None,
        kv_pages=args.kv_pages,
        prefill_chunk=args.prefill_chunk,
        prefill_bucket=args.prefill_bucket,
        prefix_cache=args.prefix_cache,
        prefix_cache_pages=args.prefix_cache_pages,
        spec_k=args.spec_k,
        spec_drafter=args.spec_drafter,
        spec_ngram=args.spec_ngram,
        spec_draft_window=args.spec_draft_window,
        quantize_weights=args.quantize_weights,
        kv_dtype=args.kv_dtype,
        num_tiers=args.num_tiers,
        tenant_quota=args.tenant_quota,
        tier_reserved_slots=args.tier_reserved_slots,
        tier_reserved_pages=args.tier_reserved_pages,
        preempt=not args.no_preempt,
        max_queue_depth=args.max_queue_depth,
        ttft_deadline_ms=args.ttft_deadline_ms,
        deadline_ms=args.deadline_ms,
        journal_dir=args.journal_dir,
        journal_fsync=args.journal_fsync,
        journal_segment_bytes=args.journal_segment_bytes,
        sample_every=args.sample_every,
        slo_rules=args.slo_rules,
        incident_dir=args.incident_dir,
        seed=args.seed,
    ), trace=trace, weights_epoch=restored_epoch)

    # Zero-drain live weight hot-swap (docs/SERVING.md): a background
    # watcher streams newly COMMITTED epochs from --watch-ckpt-dir
    # through the resilience verification path into the running engine.
    # SIGHUP wakes the watcher for one immediate poll; SIGUSR1 asks the
    # watcher thread to re-arm the previously served weights (rollback).
    # Both handlers only set events — signal-safe: the rollback itself
    # takes the engine's swap lock, which the serving loop (this very
    # thread) holds around the barrier, so it must run on the watcher
    # thread, never on the signal frame.
    swapper = None
    if args.watch_ckpt_dir is not None:
        import signal as signal_mod

        watch_dir = args.watch_ckpt_dir
        swapper = HotSwapper(
            engine, watch_dir,
            lambda e: restore_fn(e, watch_dir),
            printer=lambda msg: print(msg, file=sys.stderr, flush=True))
        swapper.start(interval_s=args.watch_interval)
        if hasattr(signal_mod, "SIGHUP"):
            signal_mod.signal(signal_mod.SIGHUP,
                              lambda *_: swapper.trigger())
        if hasattr(signal_mod, "SIGUSR1"):
            signal_mod.signal(signal_mod.SIGUSR1,
                              lambda *_: swapper.request_rollback())
        print(f"[serve] hot-swap watcher on {watch_dir} "
              f"(every {args.watch_interval:g}s; SIGHUP = poll now, "
              f"SIGUSR1 = rollback)", file=sys.stderr, flush=True)

    # Live telemetry plane: scrape the engine while it serves. The
    # handler thread reads host-side telemetry the decode loop already
    # materialized (engine.flight_snapshot never flushes or syncs).
    exporter = None
    if args.metrics_port is not None:
        from distributed_training_tpu.observability.exporter import (
            attach_engine,
        )

        exporter = attach_engine(
            engine, args.metrics_port, component="serve",
            printer=lambda msg: print(msg, file=sys.stderr, flush=True))

    # Crash-durable serving: replay the write-ahead journal BEFORE the
    # prompt stream (the exporter is already up, so /healthz reads
    # 'recovering' while this runs). Finished-but-undelivered results
    # re-surface in the final report exactly once; unfinished requests
    # re-seat through the resume path and complete bitwise; the
    # journaled line cursor skips prompts this process already
    # consumed on a previous life.
    report = engine.recover()
    recovered = (report["redelivered"]
                 + report["completed_at_replay"])
    lines_consumed = int(report["notes"].get("lines_consumed", 0))
    if recovered or report["resumed"] or lines_consumed:
        print(f"[serve] journal recovery: {len(recovered)} "
              f"redelivered/expired, {report['resumed']} resumed; "
              f"skipping {lines_consumed} already-consumed prompt "
              f"line(s)", file=sys.stderr)

    if args.prompts_file:
        with open(args.prompts_file) as fh:
            lines = [ln.rstrip("\n") for ln in fh]
    else:
        lines = [ln.rstrip("\n") for ln in sys.stdin]
    lines = [ln for ln in lines if ln]
    if not lines and not (recovered or report["resumed"]):
        raise SystemExit("no prompts (stdin/--prompts-file was empty)")
    lines = lines[lines_consumed:]

    # Graceful drain: SIGTERM latches (PreemptionGuard); the submit loop
    # then closes admission — remaining prompts are rejected with the
    # typed DrainingError — and the engine completes every request it
    # already accepted before the SLA/flight dump is emitted. A second
    # SIGTERM re-raises through the previous handler ("now" semantics).
    texts: dict[int, str] = {}
    with PreemptionGuard() as guard:
        print("[serve] engine ready", file=sys.stderr, flush=True)
        for text in lines:
            if guard.triggered:
                engine.queue.close()  # idempotent; typed rejects below
            if engine.journal is not None:
                # The line cursor persists BEFORE the line is acted on:
                # a crash inside this loop body drops a line that was
                # never durably accepted (at-most-once) — it never
                # duplicates one on restart.
                lines_consumed += 1
                # Enqueue-only: the admit below persists the same
                # ordered batch (one fsync per line, not two); a
                # skipped/rejected line's cursor rides the writer
                # thread's next flush.
                engine.journal.log_note(
                    {"lines_consumed": lines_consumed}, flush=False)
            tokens = np.frombuffer(text.encode("utf-8"), np.uint8)
            if (tokens >= args.vocab_size).any():
                print(f"[serve] SKIP (bytes outside vocab "
                      f"{args.vocab_size}): {text!r}", file=sys.stderr)
                continue
            try:
                req = engine.submit(tokens.astype(np.int32),
                                    priority=args.priority,
                                    tenant=args.tenant)
            except DrainingError as e:
                print(f"[serve] DRAINING, reject {text!r}: {e}",
                      file=sys.stderr)
                continue
            except (CacheBudgetError, QueueFullError) as e:
                print(f"[serve] REJECT {text!r}: {e}", file=sys.stderr)
                continue
            texts[req.uid] = text

        # One-shot CLI: no more submits are coming, so ending through
        # drain() is free for the normal path and makes the SIGTERM path
        # identical — close admission, finish in-flight, then report.
        # Journal recoveries (redelivered + completed-at-replay) join
        # the report: they are this process's deliveries too.
        done = recovered + engine.drain()
        if guard.triggered:
            print(f"[serve] SIGTERM: drained {len(done)} in-flight "
                  f"request(s), admission closed", file=sys.stderr)
    if swapper is not None:
        swapper.close()
        print(f"[serve] hot-swap: {swapper.counters['armed']} armed / "
              f"{swapper.counters['rejected']} rejected over "
              f"{swapper.counters['polls']} polls; serving weights "
              f"epoch {engine.weights_epoch}", file=sys.stderr)

    def decode_bytes(toks):
        return bytes(int(t) % 256 for t in toks).decode(
            "utf-8", errors="replace")

    for fin in sorted(done, key=lambda f: f.uid):
        ttft = ("-" if fin.ttft_ms is None else f"{fin.ttft_ms:.1f} ms")
        # A recovered request's prompt text predates this process; its
        # byte tokens reconstruct it (vocab 256 = one token per byte).
        text = texts.get(fin.uid, decode_bytes(fin.prompt))
        print(f"[serve] #{fin.uid} ({fin.finish_reason}, "
              f"ttft {ttft}): "
              f"{text!r} -> {decode_bytes(fin.tokens)!r}")
    if engine.journal is not None:
        # Client cursor: the completions above are consumed — a future
        # recovery must not redeliver them, and compaction may drop
        # them.
        engine.journal.ack([f.uid for f in done])
        engine.journal.shutdown()

    stats = engine.stats()
    if args.json:
        import json

        print(json.dumps(stats, allow_nan=False))
    else:
        print(f"[serve] {stats['requests_finished']} requests, "
              f"{stats['tokens_emitted']} tokens, "
              f"{stats['throughput_tok_s']:.1f} tok/s | "
              f"ttft p50 {stats['ttft_p50_ms']:.1f} / "
              f"p95 {stats['ttft_p95_ms']:.1f} ms | "
              f"tpot p50 {stats['tpot_p50_ms']:.2f} / "
              f"p95 {stats['tpot_p95_ms']:.2f} ms | "
              f"queue depth max {stats['queue_depth_max']}",
              file=sys.stderr)
        if args.slo_rules:
            print(f"[serve] alerts: {stats['alerts_fired']} fired, "
                  f"{stats['alerts_cleared']} cleared, "
                  f"{stats['alerts_active']} active | "
                  f"incidents {stats['incidents_captured']}",
                  file=sys.stderr)
    if args.ledger_out:
        from distributed_training_tpu.serving.ledger import dump_ledgers

        n_rows, bad = dump_ledgers(args.ledger_out, done)
        print(f"[serve] latency ledgers: {args.ledger_out} "
              f"({n_rows} requests, {bad} conservation violation(s))",
              file=sys.stderr)
    if args.flight_dump:
        engine.dump_flight(args.flight_dump)
        print(f"[serve] flight record: {args.flight_dump}", file=sys.stderr)
    # Drain the incident writer so every captured bundle is on disk
    # before the process exits (same discipline as journal.shutdown).
    engine.close_incidents()
    if args.incident_dir and engine.incidents is not None:
        print(f"[serve] incidents: {args.incident_dir} "
              f"({engine.incidents.captured} captured, "
              f"{engine.incidents.write_errors} write error(s))",
              file=sys.stderr)
    if trace is not None:
        trace.save(trace_path)
        print(f"[serve] trace: {trace_path} ({len(trace)} events)",
              file=sys.stderr)
    if exporter is not None:
        exporter.close()  # daemon thread; close just frees the port early
    return 0


if __name__ == "__main__":
    sys.exit(main())
