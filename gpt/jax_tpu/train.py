"""Transformer-LM trainer CLI (JAX/TPU backend, sibling-directory layout).

The reference's plugin boundary is a directory per backend under the
workload dir (``resnet/{pytorch_ddp,deepspeed,colossal}``, SURVEY.md §1 L1);
this directory extends the same layout to the framework's long-context LM
workload — a model family the reference does not have (SURVEY.md §5
"Long-context": absent).

The parallel strategy is the mesh: ``--sp 4`` rings the sequence over 4
devices, ``--tp 4`` megatron-shards the layers, ``--pp 4`` pipelines them;
the rest of the devices form the data axis. ZeRO stages compose with TP/DP
via ``--stage``.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def add_argument() -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="TransformerLM on TPU")
    parser.add_argument("-b", "--batch_size", type=int, default=32,
                        help="per-data-shard batch size")
    parser.add_argument("-e", "--epochs", type=int, default=5)
    parser.add_argument("--gradient-accumulation-steps", type=int, default=1,
                        help="microbatches per optimizer update (tensor/dp "
                             "strategy; effective batch scales by this)")
    parser.add_argument("--remat", action="store_true", default=False,
                        help="activation-checkpoint each decoder block")
    parser.add_argument("--ema-decay", type=float, default=None,
                        help="parameter EMA decay; eval uses the average")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--vocab-size", type=int, default=256)
    parser.add_argument("--num-layers", type=int, default=4)
    parser.add_argument("--num-heads", type=int, default=4)
    parser.add_argument("--hidden-dim", type=int, default=256)
    parser.add_argument("--max-len", type=int, default=2048)
    parser.add_argument("--corpus", type=str, default=None,
                        help="byte-level text file; default synthetic tokens")
    parser.add_argument("--attn-impl", type=str, default="exact",
                        choices=["exact", "flash"],
                        help="flash = Pallas blockwise kernel; under --sp it "
                             "becomes the per-hop ring compute")
    parser.add_argument("--ce-chunk-size", type=int, default=None,
                        help="chunked cross-entropy: tokens per lm_head+CE "
                             "chunk (never materializes [B,T,vocab] logits; "
                             "for long-context × large-vocab runs)")
    parser.add_argument("--logits-dtype", type=str, default="bf16",
                        choices=["fp32", "bf16"],
                        help="head/logits compute dtype. Default bf16 "
                             "(round 5): halves the [B,T,vocab] HBM "
                             "traffic, CE still reduces in fp32, and 3- "
                             "and 8-epoch chip A/Bs track fp32 step-for-"
                             "step (final ppl 1.0784 vs 1.0785, "
                             "BASELINE.md); fp32 remains selectable")
    parser.add_argument("--ce-save-probs", action="store_true", default=False,
                        help="CE backward from saved bf16 softmax probs: "
                             "+2%% tok/s under --logits-dtype fp32 (its "
                             "niche); refused with --ce-chunk-size, warns "
                             "under bf16 logits (measured slower there)")
    parser.add_argument("--head-bias", action=argparse.BooleanOptionalAction,
                        default=False,
                        help="lm_head bias. Default off (round 5): GPT-2's "
                             "real head has none, and its gradient costs a "
                             "full HBM pass over the [B,T,vocab] logits")
    # MoE surface (DeepSpeed flag names, resnet/deepspeed parity) — here
    # they swap alternating decoder FFNs for expert-parallel MoE layers.
    parser.add_argument("--moe", action="store_true", default=False)
    parser.add_argument("--ep-world-size", type=int, default=1,
                        help="expert mesh axis size")
    parser.add_argument("--num-experts", type=int, nargs="+", default=[8])
    parser.add_argument("--moe-every", type=int, default=2,
                        help="swap every Nth decoder FFN for MoE (GShard "
                             "alternating at 2); 1 = every layer — the "
                             "homogeneous layout the pipeline strategy "
                             "(--pp) can carry")
    parser.add_argument("--top-k", type=int, default=1)
    parser.add_argument("--min-capacity", type=int, default=0)
    parser.add_argument("--noisy-gate-policy", type=str, default=None,
                        choices=[None, "RSample", "Jitter"])
    parser.add_argument("--mlp-type", type=str, default="standard",
                        choices=["standard", "residual"])
    parser.add_argument("--dtype", type=str, default="fp32",
                        choices=["bf16", "fp16", "fp32"])
    parser.add_argument("--stage", type=int, default=0, choices=[0, 1, 2, 3],
                        help="ZeRO stage (composes with --tp / pure DP)")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel (model axis) size")
    parser.add_argument("--tp-overlap", action="store_true", default=False,
                        help="ring-overlapped tensor parallelism: decompose "
                             "the megatron layer collectives into ppermute "
                             "rings fused with the partial matmuls "
                             "(latency-hiding collective matmul; needs "
                             "--tp > 1 to do anything, and seq_len/--sp "
                             "divisible by --tp)")
    parser.add_argument("--pp", type=int, default=1,
                        help="pipeline-parallel (pipe axis) size")
    parser.add_argument("--sp", type=int, default=1,
                        help="sequence-parallel (ring) size")
    parser.add_argument("--virtual-stages", type=int, default=1,
                        help="interleaved/circular pipeline: layer chunks "
                             "per pipe device (1 = GPipe); cuts the bubble "
                             "to (S-1)/(v*M+S-1)")
    parser.add_argument("--microbatches", type=int, default=2,
                        help="GPipe microbatches (only with --pp)")
    parser.add_argument("-c", "--checkpoint", type=str, default="./checkpoint")
    parser.add_argument("-i", "--interval", type=int, default=5)
    parser.add_argument("-r", "--resume", type=int, default=-1)
    parser.add_argument("--log-interval", type=int, default=50)
    parser.add_argument("--steps-per-epoch", type=int, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--wall-clock-breakdown", action="store_true")
    parser.add_argument("--profile-dir", type=str, default=None)
    parser.add_argument("--auto-resume", action="store_true", default=False,
                        help="resume from the newest checkpoint if present")
    parser.add_argument("--tensorboard-dir", type=str, default=None)
    parser.add_argument("--metrics-jsonl", type=str, default=None)
    # Observability (flight instruments; docs/OBSERVABILITY.md).
    parser.add_argument("--flight-recorder",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="ring buffer of per-step timestamps + flushed "
                             "metrics; step-time p50/p95 + goodput, dumped "
                             "to JSON on anomaly/crash (read it with "
                             "tools/flight_report.py)")
    parser.add_argument("--flight-dir", type=str, default=None,
                        help="where anomaly/crash forensics land (flight "
                             "JSON, offending batch, HLO, profiler trace)")
    parser.add_argument("--trace", action=argparse.BooleanOptionalAction,
                        default=False,
                        help="span-level Perfetto trace: step/eval/ckpt "
                             "phases, the async checkpoint writer's own "
                             "track, chaos injections — written at run "
                             "end (open in ui.perfetto.dev, or summarize "
                             "with tools/trace_report.py)")
    parser.add_argument("--trace-dir", type=str, default=None,
                        help="trace output directory (default: "
                             "<flight dir>/trace)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="live telemetry plane: serve /metrics "
                             "(Prometheus text), /healthz and /vars from "
                             "a background thread on this port while the "
                             "run is alive (loopback; 0 = ephemeral; "
                             "master process only). Scrapes read cached "
                             "host-side summaries — never a device value")
    parser.add_argument("--grad-norm-metric", action="store_true",
                        default=False,
                        help="global L2 grad norm as an on-device step "
                             "metric (no extra host syncs; also arms the "
                             "anomaly detector's spike rule)")
    parser.add_argument("--anomaly-detection", action="store_true",
                        default=False,
                        help="NaN/Inf-loss + grad-norm-spike detection at "
                             "meter flushes; on trigger: flight dump + "
                             "batch/HLO save + N-step profiler trace, then "
                             "--anomaly-action")
    parser.add_argument("--anomaly-action", default="raise",
                        choices=["raise", "skip"])
    parser.add_argument("--anomaly-trace-steps", type=int, default=3,
                        help="profiler-trace steps captured after an "
                             "anomaly trigger (0 = no trace)")
    add_chaos_arguments(parser)
    return parser.parse_args()


def add_chaos_arguments(parser: argparse.ArgumentParser) -> None:
    """Deterministic fault injection (resilience/chaos.py;
    docs/RESILIENCE.md). All defaults inert. resnet/jax_tpu/train.py
    mirrors this flag group inline (the backend dirs are deliberately
    self-contained scripts, like the observability flags) — keep the
    two in sync when adding knobs."""
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument("--chaos-kill-at-step", type=int, default=None,
                        help="deliver --chaos-kill-signal from inside the "
                             "step loop at this global step (simulated "
                             "TPU eviction)")
    parser.add_argument("--chaos-kill-signal", type=str, default="sigterm",
                        choices=["sigterm", "kill"],
                        help="sigterm = graceful grace-window eviction "
                             "(preemption save); kill = SIGKILL, hard "
                             "death with no save")
    parser.add_argument("--chaos-torn-ckpt-epoch", type=int, default=None,
                        help="after this epoch's checkpoint save lands, "
                             "truncate it and drop its COMMITTED marker "
                             "(torn write; auto-resume must fall back)")
    parser.add_argument("--chaos-torn-bytes", type=int, default=64,
                        help="bytes to leave in the torn file")
    parser.add_argument("--chaos-corrupt-ckpt-epoch", type=int,
                        default=None,
                        help="tear-AFTER-commit: corrupt this epoch's "
                             "save payload while keeping its COMMITTED "
                             "marker (checksum-level bit rot; the "
                             "hot-swap watcher's verify stage must "
                             "quarantine it)")
    parser.add_argument("--chaos-data-error-rate", type=float, default=0.0,
                        help="seeded per-key probability of a one-shot "
                             "transient data-read error (the retry "
                             "policy must absorb it)")
    parser.add_argument("--chaos-slow-step-every", type=int, default=None,
                        help="inject a host stall every N steps "
                             "(straggler simulation)")
    parser.add_argument("--chaos-slow-step-ms", type=float, default=50.0)
    parser.add_argument("--chaos-slow-step-host", type=int, default=None,
                        help="restrict the slow-step injection to this "
                             "process index (multihost straggler drill: "
                             "one slow host for the flight aggregation "
                             "to attribute); default: every host")


def chaos_config_from_flags(args: argparse.Namespace):
    from distributed_training_tpu.config import ChaosConfig

    return ChaosConfig(
        seed=args.chaos_seed,
        kill_at_step=args.chaos_kill_at_step,
        kill_signal=args.chaos_kill_signal,
        torn_ckpt_epoch=args.chaos_torn_ckpt_epoch,
        torn_truncate_bytes=args.chaos_torn_bytes,
        corrupt_ckpt_epoch=args.chaos_corrupt_ckpt_epoch,
        data_error_rate=args.chaos_data_error_rate,
        slow_step_every=args.chaos_slow_step_every,
        slow_step_ms=args.chaos_slow_step_ms,
        slow_step_host=args.chaos_slow_step_host,
    )


def build_config(args: argparse.Namespace):
    from distributed_training_tpu.config import (
        CheckpointConfig,
        DataConfig,
        LMConfig,
        MeshSpec,
        MoEConfig,
        ObservabilityConfig,
        TraceConfig,
        TrainConfig,
        ZeroConfig,
    )

    cfg = TrainConfig(model="transformer_lm")
    if args.ema_decay is not None:
        cfg = cfg.replace(
            optimizer=dataclasses.replace(
                cfg.optimizer, ema_decay=args.ema_decay))
    return cfg.replace(
        moe=MoEConfig(
            enabled=args.moe,
            ep_world_size=args.ep_world_size,
            num_experts=tuple(args.num_experts),
            every=args.moe_every,
            top_k=args.top_k,
            min_capacity=args.min_capacity,
            noisy_gate_policy=args.noisy_gate_policy,
            mlp_type=args.mlp_type,
        ),
        num_epochs=args.epochs,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        remat=args.remat,
        tp_overlap=args.tp_overlap,
        seed=args.seed,
        log_interval=args.log_interval,
        wall_clock_breakdown=args.wall_clock_breakdown,
        profile_dir=args.profile_dir,
        tensorboard_dir=args.tensorboard_dir,
        metrics_jsonl=args.metrics_jsonl,
        observability=ObservabilityConfig(
            flight_recorder=args.flight_recorder,
            dump_dir=args.flight_dir,
            metrics_port=args.metrics_port,
            grad_norm=args.grad_norm_metric or args.anomaly_detection,
            anomaly_detection=args.anomaly_detection,
            anomaly_action=args.anomaly_action,
            anomaly_trace_steps=args.anomaly_trace_steps,
            trace=TraceConfig(enabled=args.trace, dir=args.trace_dir),
        ),
        chaos=chaos_config_from_flags(args),
        precision=dataclasses.replace(cfg.precision, dtype=args.dtype),
        zero=ZeroConfig(stage=args.stage),
        # expert gated on --moe: a dense run must keep the full data axis
        # (an expert axis under a dense model would just replicate compute).
        mesh=MeshSpec(data=-1, model=args.tp, pipe=args.pp, sequence=args.sp,
                      expert=args.ep_world_size if args.moe else 1),
        checkpoint=CheckpointConfig(
            directory=args.checkpoint,
            interval=args.interval,
            resume=args.resume,
            auto_resume=args.auto_resume,
        ),
        data=DataConfig(
            batch_size=args.batch_size,
            max_steps_per_epoch=args.steps_per_epoch,
        ),
        lm=LMConfig(
            seq_len=args.seq_len,
            vocab_size=args.vocab_size,
            num_layers=args.num_layers,
            num_heads=args.num_heads,
            hidden_dim=args.hidden_dim,
            max_len=args.max_len,
            num_microbatches=args.microbatches,
            virtual_stages=args.virtual_stages,
            attn_impl=args.attn_impl,
            ce_chunk_size=args.ce_chunk_size,
            ce_save_probs=args.ce_save_probs,
            logits_dtype=args.logits_dtype,
            head_bias=args.head_bias,
            corpus_path=args.corpus,
        ),
    )


def main() -> int:
    args = add_argument()

    from distributed_training_tpu.runtime.distributed import (
        initialize_distributed,
    )
    from distributed_training_tpu.train.lm_trainer import LMTrainer

    initialize_distributed()
    cfg = build_config(args)
    trainer = LMTrainer(cfg)
    result = trainer.fit()
    trainer.coord.print(f"[done] {result}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
