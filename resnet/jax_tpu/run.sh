python train.py -p torch_ddp_fp16 -c ./ckpt-fp16
