"""JAX/TPU backend trainer — sibling of the reference's per-backend dirs.

The per-backend-directory layout IS the plugin boundary
(``resnet/{pytorch_ddp,deepspeed,colossal}/`` in the reference;
BASELINE.json north star: "a JAX/TPU backend added as a sibling"). This CLI
subsumes the union of all three reference trainers' surfaces:

- DDP style (``resnet/pytorch_ddp/ddp_train.py:107-114``): defaults —
  5 epochs, batch 100/device, Adam lr 1e-3 × world_size.
- DeepSpeed style (``resnet/deepspeed/deepspeed_train.py:27-129``):
  ``--dtype``, ``--stage``, the full MoE flag set, ``--log-interval``,
  ``--deepspeed``/``--deepspeed_config`` passthrough, and the in-code
  ds_config dict (``:172-220``) ingested via ``from_ds_config``.
- ColossalAI style (``resnet/colossal/colossal_train.py:30-50``):
  ``-p/--plugin``, ``-r/--resume``, ``-c/--checkpoint``, ``-i/--interval``,
  ``--target_acc`` — all functional here (the reference parses but never
  wires resume/checkpoint/target_acc; SURVEY.md §2.5).

Unlike the reference there is no per-rank process fan-out (``mp.spawn``) —
JAX is one process per host; multi-host runs call
``initialize_distributed()`` from the launcher env (RANK/WORLD_SIZE/
MASTER_ADDR), and all device parallelism lives in the compiled mesh program.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def add_argument() -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="CIFAR on TPU (JAX backend)")

    # -- model / plugin (Colossal style) ------------------------------------
    parser.add_argument("-p", "--plugin", type=str, default="torch_ddp",
                        choices=["torch_ddp", "torch_ddp_fp16",
                                 "low_level_zero", "gemini", "deepspeed"],
                        help="parallelism plugin to use")
    parser.add_argument("--model", type=str, default="resnet18",
                        help="model name from the registry")
    parser.add_argument("-r", "--resume", type=int, default=-1,
                        help="resume from the epoch's checkpoint")
    parser.add_argument("-c", "--checkpoint", type=str, default="./checkpoint",
                        help="checkpoint directory")
    parser.add_argument("-i", "--interval", type=int, default=5,
                        help="interval of saving checkpoint (epochs)")
    parser.add_argument("--precise-bn-batches", type=int, default=0,
                        help="refresh BatchNorm running stats with N "
                             "train-mode forwards before each eval (the EMA "
                             "stats lag fast-moving params; 0 = raw stats)")
    parser.add_argument("--target_acc", type=float, default=None,
                        help="target accuracy; raise if not reached")
    parser.add_argument("--local-rank", "--local_rank", type=int, default=-1,
                        help="accepted for launcher compat; unused (JAX is "
                             "one process per host)")

    # -- train (DeepSpeed style) --------------------------------------------
    parser.add_argument("-b", "--batch_size", type=int, default=100,
                        help="per-device mini-batch size")
    parser.add_argument("-e", "--epochs", type=int, default=5,
                        help="number of total epochs")
    parser.add_argument("--gradient-accumulation-steps", type=int, default=1,
                        help="microbatches accumulated per optimizer update "
                             "(effective batch = batch_size × world × this)")
    parser.add_argument("--label-smoothing", type=float, default=0.0,
                        help="uniform label smoothing for the train CE")
    parser.add_argument("--remat", action="store_true", default=False,
                        help="activation checkpointing per block (fit "
                             "bigger batches; ~30%% extra backward FLOPs)")

    # -- optimizer overrides (None = keep the plugin preset) ----------------
    parser.add_argument("--optimizer", type=str, default=None,
                        choices=["adam", "adamw", "sgd", "lamb",
                                 "hybrid_adam"])
    parser.add_argument("--lr", type=float, default=None)
    parser.add_argument("--momentum", type=float, default=None,
                        help="SGD momentum (sgd only)")
    parser.add_argument("--nesterov", action="store_true", default=False)
    parser.add_argument("--weight-decay", type=float, default=None)
    parser.add_argument("--weight-decay-mask", type=str, default=None,
                        choices=["all", "no_1d"],
                        help="no_1d = don't decay biases/norm params "
                             "(ImageNet recipe)")
    parser.add_argument("--ema-decay", type=float, default=None,
                        help="parameter EMA decay (e.g. 0.9999); eval uses "
                             "the averaged params")
    parser.add_argument("--log-interval", type=int, default=100,
                        help="steps between metric fetches/logs")
    parser.add_argument("--dtype", type=str, default="fp32",
                        choices=["bf16", "fp16", "fp32"],
                        help="compute datatype")
    parser.add_argument("--stage", type=int, default=0, choices=[0, 1, 2, 3],
                        help="ZeRO optimization stage (deepspeed plugin)")
    parser.add_argument("--deepspeed", action="store_true", default=False,
                        help="accepted for launcher compat (config comes "
                             "from --deepspeed_config / built-in defaults)")
    parser.add_argument("--deepspeed_config", type=str, default=None,
                        help="path to a DeepSpeed-style JSON config to ingest")

    # -- MoE (DeepSpeed style, deepspeed_train.py:61-106) -------------------
    parser.add_argument("--moe", action="store_true", default=False,
                        help="use mixture of experts")
    parser.add_argument("--ep-world-size", type=int, default=1,
                        help="(moe) expert parallel world size")
    parser.add_argument("--num-experts", type=int, nargs="+", default=[1],
                        help="number of experts list, MoE related.")
    parser.add_argument("--mlp-type", type=str, default="standard",
                        help="only applicable when num-experts > 1; "
                             "accepts [standard, residual]")
    parser.add_argument("--top-k", type=int, default=1,
                        help="(moe) gating top 1 and 2 supported")
    parser.add_argument("--min-capacity", type=int, default=0,
                        help="(moe) minimum expert capacity")
    parser.add_argument("--noisy-gate-policy", type=str, default=None,
                        help="(moe) None, RSample, or Jitter")
    parser.add_argument("--moe-param-group", action="store_true",
                        default=False,
                        help="(moe) separate moe param groups for ZeRO")

    # -- data / misc --------------------------------------------------------
    parser.add_argument("--dataset", type=str, default="cifar10",
                        choices=["cifar10", "synthetic_cifar",
                                 "synthetic_cifar_hard",
                                 "synthetic_imagenet", "imagefolder"])
    parser.add_argument("--data-path", type=str, default=None,
                        help="dataset root (default: $DATA or ../data); "
                             "imagefolder expects <root>/train and "
                             "<root>/val class-directory trees")
    parser.add_argument("--decoded-cache", action="store_true", default=False,
                        help="(imagefolder) decode the tree once into a "
                             "uint8 memmap cache under <root>/.decoded_cache "
                             "and serve epochs from it — decode-bound hosts "
                             "become augment-bound (DALI-cache analogue)")
    parser.add_argument("--image-size", type=int, default=None,
                        help="square input size (default: 224 for "
                             "imagenet-style datasets, 32 for CIFAR)")
    parser.add_argument("--num-classes", type=int, default=None,
                        help="label count (default by dataset)")
    parser.add_argument("--steps-per-epoch", type=int, default=None,
                        help="cap train steps per epoch (smoke runs)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--wall-clock-breakdown", action="store_true",
                        default=False)
    parser.add_argument("--profile-dir", type=str, default=None,
                        help="jax.profiler trace output directory")
    parser.add_argument("--auto-resume", action="store_true", default=False,
                        help="resume from the newest checkpoint if present "
                             "(pairs with SIGTERM preemption saves)")
    parser.add_argument("--tensorboard-dir", type=str, default=None,
                        help="TensorBoard scalar log directory")
    parser.add_argument("--metrics-jsonl", type=str, default=None,
                        help="append metric flushes to this JSONL file")
    # Observability (flight instruments; docs/OBSERVABILITY.md). Same
    # surface as gpt/jax_tpu/train.py.
    parser.add_argument("--flight-recorder",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="ring buffer of per-step timestamps + flushed "
                             "metrics (step-time percentiles, goodput; "
                             "dumped on anomaly/crash)")
    parser.add_argument("--flight-dir", type=str, default=None,
                        help="anomaly/crash forensics directory")
    parser.add_argument("--trace", action=argparse.BooleanOptionalAction,
                        default=False,
                        help="span-level Perfetto trace (step/eval/ckpt "
                             "phases, ckpt-writer track, chaos marks); "
                             "summarize with tools/trace_report.py")
    parser.add_argument("--trace-dir", type=str, default=None,
                        help="trace output directory (default: "
                             "<flight dir>/trace)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="live telemetry plane: /metrics (Prometheus "
                             "text), /healthz and /vars served from a "
                             "background thread on this port while the "
                             "run is alive (loopback; 0 = ephemeral; "
                             "master process only)")
    parser.add_argument("--grad-norm-metric", action="store_true",
                        default=False,
                        help="global L2 grad norm as an on-device metric")
    parser.add_argument("--anomaly-detection", action="store_true",
                        default=False,
                        help="NaN/Inf-loss + grad-norm-spike detection at "
                             "meter flushes (flight dump + batch/HLO + "
                             "profiler trace on trigger)")
    parser.add_argument("--anomaly-action", default="raise",
                        choices=["raise", "skip"])
    parser.add_argument("--anomaly-trace-steps", type=int, default=3)

    # Chaos harness (resilience/chaos.py; docs/RESILIENCE.md) — mirrors
    # gpt/jax_tpu/train.py::add_chaos_arguments (backend dirs are
    # self-contained scripts; keep in sync). All defaults inert.
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument("--chaos-kill-at-step", type=int, default=None,
                        help="deliver --chaos-kill-signal at this global "
                             "step (simulated TPU eviction)")
    parser.add_argument("--chaos-kill-signal", type=str, default="sigterm",
                        choices=["sigterm", "kill"])
    parser.add_argument("--chaos-torn-ckpt-epoch", type=int, default=None,
                        help="tear this epoch's save after it lands "
                             "(truncate + drop COMMITTED; auto-resume "
                             "must fall back)")
    parser.add_argument("--chaos-torn-bytes", type=int, default=64)
    parser.add_argument("--chaos-corrupt-ckpt-epoch", type=int,
                        default=None,
                        help="tear-AFTER-commit: corrupt this epoch's "
                             "save payload, COMMITTED marker intact "
                             "(checksum pass must catch it)")
    parser.add_argument("--chaos-data-error-rate", type=float, default=0.0,
                        help="seeded one-shot transient data-read faults "
                             "(the retry policy must absorb them)")
    parser.add_argument("--chaos-slow-step-every", type=int, default=None)
    parser.add_argument("--chaos-slow-step-ms", type=float, default=50.0)
    parser.add_argument("--chaos-slow-step-host", type=int, default=None,
                        help="restrict slow-step injection to this "
                             "process index (straggler drill)")

    return parser.parse_args()


# The DeepSpeed trainer's in-code engine config
# (resnet/deepspeed/deepspeed_train.py:172-220), reproduced as the default
# ds_config for the 'deepspeed' plugin; --dtype/--stage patch it exactly the
# way the reference's args do.
def default_ds_config(dtype: str, stage: int, batch_size: int) -> dict:
    return {
        "train_batch_size": batch_size,
        "steps_per_print": 2000,
        "optimizer": {
            "type": "Adam",
            "params": {
                "lr": 0.001,
                "betas": [0.8, 0.999],
                "eps": 1e-8,
                "weight_decay": 3e-7,
            },
        },
        "scheduler": {
            "type": "WarmupLR",
            "params": {
                "warmup_min_lr": 0,
                "warmup_max_lr": 0.001,
                "warmup_num_steps": 1000,
            },
        },
        "gradient_clipping": 1.0,
        "prescale_gradients": False,
        "bf16": {"enabled": dtype == "bf16"},
        "fp16": {
            "enabled": dtype == "fp16",
            "fp16_master_weights_and_grads": False,
            "loss_scale": 0,
            "loss_scale_window": 500,
            "hysteresis": 2,
            "min_loss_scale": 1,
            "initial_scale_power": 15,
        },
        "wall_clock_breakdown": False,
        "zero_optimization": {
            "stage": stage,
            "allgather_partitions": True,
            "reduce_scatter": True,
            "allgather_bucket_size": 50000000,
            "reduce_bucket_size": 50000000,
            "overlap_comm": True,
            "contiguous_gradients": True,
            "cpu_offload": False,
        },
    }


def build_config(args: argparse.Namespace):
    from distributed_training_tpu.config import (
        ChaosConfig,
        CheckpointConfig,
        DataConfig,
        MoEConfig,
        ObservabilityConfig,
        TraceConfig,
        TrainConfig,
        from_ds_config,
    )

    cfg = TrainConfig.from_plugin(args.plugin)

    if args.moe and not args.model.startswith("moe"):
        # The reference parses --moe but trains a dense ResNet regardless
        # (deepspeed_train.py:223); here the flag selects the MoE model.
        print(f"[moe] switching model {args.model!r} -> 'moe_mlp'")
        args.model = "moe_mlp"

    if args.plugin == "deepspeed":
        if args.deepspeed_config:
            with open(args.deepspeed_config) as fh:
                ds = json.load(fh)
        else:
            ds = default_ds_config(args.dtype, args.stage, args.batch_size)
        cfg = from_ds_config(ds, base=cfg)
    else:
        cfg = cfg.replace(
            precision=dataclasses.replace(cfg.precision, dtype=args.dtype)
            if args.dtype != "fp32" else cfg.precision)

    imagenet_style = args.dataset in ("synthetic_imagenet", "imagefolder")
    num_classes = args.num_classes or (1000 if imagenet_style else 10)
    image_size = args.image_size or (224 if imagenet_style else 32)
    augment = ("normalize_only" if args.plugin == "deepspeed"
               else "pad_crop_flip")  # DS normalizes; DDP/Colossal crop+flip

    cfg = cfg.replace(
        model=args.model,
        num_epochs=args.epochs,
        gradient_accumulation_steps=args.gradient_accumulation_steps,
        label_smoothing=args.label_smoothing,
        # --remat opts in; never clobber a remat=True the ds_config set.
        remat=args.remat or cfg.remat,
        seed=args.seed,
        log_interval=args.log_interval,
        target_acc=args.target_acc,
        eval_precise_bn_batches=args.precise_bn_batches,
        wall_clock_breakdown=args.wall_clock_breakdown,
        profile_dir=args.profile_dir,
        tensorboard_dir=args.tensorboard_dir,
        metrics_jsonl=args.metrics_jsonl,
        observability=ObservabilityConfig(
            flight_recorder=args.flight_recorder,
            dump_dir=args.flight_dir,
            metrics_port=args.metrics_port,
            grad_norm=args.grad_norm_metric or args.anomaly_detection,
            anomaly_detection=args.anomaly_detection,
            anomaly_action=args.anomaly_action,
            anomaly_trace_steps=args.anomaly_trace_steps,
            trace=TraceConfig(enabled=args.trace, dir=args.trace_dir),
        ),
        chaos=ChaosConfig(
            seed=args.chaos_seed,
            kill_at_step=args.chaos_kill_at_step,
            kill_signal=args.chaos_kill_signal,
            torn_ckpt_epoch=args.chaos_torn_ckpt_epoch,
            torn_truncate_bytes=args.chaos_torn_bytes,
            corrupt_ckpt_epoch=args.chaos_corrupt_ckpt_epoch,
            data_error_rate=args.chaos_data_error_rate,
            slow_step_every=args.chaos_slow_step_every,
            slow_step_ms=args.chaos_slow_step_ms,
            slow_step_host=args.chaos_slow_step_host,
        ),
        checkpoint=CheckpointConfig(
            directory=args.checkpoint,
            interval=args.interval,
            resume=args.resume,
            auto_resume=args.auto_resume,
        ),
        data=DataConfig(
            dataset=args.dataset,
            data_path=args.data_path,
            batch_size=args.batch_size,
            augment=augment,
            image_size=image_size,
            num_classes=num_classes,
            max_steps_per_epoch=args.steps_per_epoch,
            decoded_cache=args.decoded_cache,
        ),
        moe=MoEConfig(
            enabled=args.moe,
            ep_world_size=args.ep_world_size,
            num_experts=tuple(args.num_experts),
            mlp_type=args.mlp_type,
            top_k=args.top_k,
            min_capacity=args.min_capacity,
            noisy_gate_policy=args.noisy_gate_policy,
            moe_param_group=args.moe_param_group,
        ),
        # The Trainer engages expert sharding from the mesh, not MoEConfig
        # (train/trainer.py decides expert_axis from the realized mesh shape),
        # so --ep-world-size must size the expert axis here — matching the
        # gpt CLI's wiring. DeepSpeed's flag (deepspeed_train.py:64-66) has
        # the same contract: ep_world_size is the expert-parallel degree.
        # Gated on --moe: a dense run must keep the full data axis (an
        # expert axis under a dense model would just replicate compute).
        mesh=dataclasses.replace(
            cfg.mesh, expert=args.ep_world_size if args.moe else 1),
    )

    # Optimizer overrides on top of the plugin preset (None = keep preset).
    opt_overrides = {
        k: v for k, v in (
            ("name", args.optimizer),
            ("lr", args.lr),
            ("momentum", args.momentum),
            ("weight_decay", args.weight_decay),
            ("weight_decay_mask", args.weight_decay_mask),
            ("ema_decay", args.ema_decay),
        ) if v is not None
    }
    if args.nesterov:
        opt_overrides["nesterov"] = True
    if opt_overrides:
        cfg = cfg.replace(
            optimizer=dataclasses.replace(cfg.optimizer, **opt_overrides))
    return cfg


def main() -> int:
    args = add_argument()

    from distributed_training_tpu.runtime.distributed import (
        initialize_distributed,
    )
    from distributed_training_tpu.train.trainer import Trainer

    initialize_distributed()  # no-op single-process; env-driven multi-host
    cfg = build_config(args)
    trainer = Trainer(cfg)
    result = trainer.fit()
    trainer.coord.print(f"[done] {result}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
