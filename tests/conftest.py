"""Test harness: a virtual 8-device CPU mesh.

Multi-device-without-hardware strategy per SURVEY.md §4. Two subtleties of
this environment:

- The axon TPU plugin's sitecustomize runs at interpreter start and calls
  ``jax.config.update("jax_platforms", "axon,cpu")``, overriding the
  ``JAX_PLATFORMS`` env var. Tests must run on CPU (the tunnel exposes one
  real chip and wedges under concurrent backend inits), so we override the
  *config* back to cpu here — conftest imports before any backend init, so
  this wins.
- ``xla_force_host_platform_device_count`` is read at CPU client creation;
  setting it here (before the first device use) is early enough.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402

from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh  # noqa: E402
from distributed_training_tpu.utils.compat import supports_partial_manual  # noqa: E402

# Known pre-existing failure, kept visible but not red: every composition
# that needs PARTIAL-MANUAL shard_map (axis_names=..., so the strategy's
# own axes are manual while model/expert stay automatic for GSPMD) raises
# on the baked jax 0.4.37 — the axis_names kwarg landed in jax 0.6
# (utils/compat.py::shard_map; CHANGES.md rounds 6/7). run= skips the
# deterministic re-raise on old jax (it only burns CI minutes) but
# re-executes on jax>=0.6, where strict=False turns survivors into loud
# XPASSes flagging the marks for removal.
needs_partial_manual = pytest.mark.xfail(
    strict=False,
    run=supports_partial_manual(),
    reason="partial-manual shard_map (axis_names) needs jax>=0.6; "
           "pre-existing on the baked jax 0.4.37 (CHANGES.md round 6/7)")


@pytest.fixture
def compile_watch():
    """Compiled-program sanitizer hook (observability/sanitizer.py): a
    CompileWatch marked at test start. Tests exercising warm paths call
    ``compile_watch.check_no_growth(...)`` to pin that nothing retraced;
    the first use installs the process-global jax.monitoring listener."""
    from distributed_training_tpu.observability.sanitizer import CompileWatch

    with CompileWatch() as watch:
        yield watch


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh(devices):
    return create_mesh(MeshConfig(data=-1))


@pytest.fixture(scope="session")
def mesh2x4(devices):
    """data=2 × fsdp=4 mesh for ZeRO/FSDP tests."""
    return create_mesh(MeshConfig(data=2, fsdp=4))


def load_cli_module(relpath, name=None):
    """Import a per-backend CLI script (e.g. ``resnet/jax_tpu/train.py``)
    as a module; the backend dirs are script-style, not packages."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, relpath)
    name = name or relpath.replace("/", "_").removesuffix(".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
