"""Anomaly detection + triggered forensics (observability/anomaly, hooks).

The acceptance-criteria test lives here: forcing a NaN loss mid-run must
trigger a flight-recorder dump + profiler trace capture + offending
batch/HLO save, then skip or raise per config — driven through the REAL
trainers (both engines), not a mocked loop.
"""

import glob
import json
import math
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.config import (
    CheckpointConfig,
    DataConfig,
    LMConfig,
    ObservabilityConfig,
    TrainConfig,
)
from distributed_training_tpu.observability import (
    AnomalyDetector,
    AnomalyError,
)


class TestDetector:
    def test_nan_and_inf_loss_flagged(self):
        d = AnomalyDetector()
        assert d.check({"loss": 1.0}) == []
        assert "non-finite loss" in d.check({"loss": float("nan")})[0]
        assert "non-finite loss" in d.check({"loss": float("inf")})[0]

    def test_grad_norm_spike_vs_ema(self):
        d = AnomalyDetector(spike_factor=10.0)
        assert d.check({"grad_norm": 1.0}) == []  # seeds the EMA
        assert d.check({"grad_norm": 2.0}) == []  # healthy drift
        reasons = d.check({"grad_norm": 50.0})
        assert reasons and "spike" in reasons[0]
        # The spike must NOT be ingested into the EMA — a second spike of
        # the same size still flags.
        assert d.grad_norm_ema == pytest.approx(0.9 * 1.0 + 0.1 * 2.0)
        assert d.check({"grad_norm": 50.0}) != []

    def test_non_finite_grad_norm_flagged(self):
        d = AnomalyDetector()
        assert "non-finite grad norm" in d.check(
            {"grad_norm": float("nan")})[0]

    def test_missing_keys_degrade_gracefully(self):
        assert AnomalyDetector().check({"accuracy": 0.5}) == []

    def test_fp16_scaler_skip_is_not_an_anomaly(self):
        # grads_finite=0 only happens under the dynamic fp16 scaler,
        # whose skip-on-overflow IS the designed response — the detector
        # must not shoot down an fp16 run doing scale discovery.
        d = AnomalyDetector()
        assert d.check({"loss": float("inf"), "grad_norm": float("nan"),
                        "grads_finite": 0.0}) == []
        # Same values with a committed update (bf16/fp32 inert scaler
        # pins grads_finite=1): flagged.
        assert d.check({"loss": float("inf"), "grads_finite": 1.0}) != []

    def test_spike_factor_validated(self):
        with pytest.raises(ValueError, match="spike_factor"):
            AnomalyDetector(spike_factor=1.0)

    def test_config_validates_action(self):
        with pytest.raises(ValueError, match="anomaly_action"):
            ObservabilityConfig(anomaly_action="explode")
        with pytest.raises(ValueError, match="anomaly_trace_steps"):
            ObservabilityConfig(anomaly_trace_steps=-1)


def _image_cfg(tmp_path, **obs_kw):
    return TrainConfig(
        model="resnet_micro",
        num_epochs=2,
        log_interval=2,
        eval_every=0,
        data=DataConfig(dataset="synthetic_cifar", batch_size=4,
                        max_steps_per_epoch=4, prefetch=0),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ckpt"),
                                    interval=0),
        metrics_jsonl=str(tmp_path / "metrics.jsonl"),
        observability=ObservabilityConfig(
            grad_norm=True, anomaly_detection=True,
            dump_dir=str(tmp_path / "flight"), **obs_kw),
    )


def _poison_after(trainer, n_calls):
    """Wrap the train step so call n_calls NaNs every parameter — the
    realistic divergence signature: all later losses are non-finite."""
    real_step = trainer.train_step
    calls = []

    def step(state, batch, rng):
        state, metrics = real_step(state, batch, rng)
        calls.append(1)
        if len(calls) == n_calls:
            state = state.replace(params=jax.tree.map(
                lambda x: (x * jnp.nan).astype(x.dtype), state.params))
        return state, metrics

    step.lower = real_step.lower  # keep the HLO-forensics hook
    trainer.train_step = step
    return calls


class TestTrainerAnomalyInjection:
    def test_nan_loss_skip_dumps_and_completes(self, mesh, tmp_path):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _image_cfg(tmp_path, anomaly_action="skip",
                         anomaly_trace_steps=2)
        tr = Trainer(cfg, mesh=mesh)
        _poison_after(tr, 2)
        result = tr.fit()  # skip: the run COMPLETES despite the anomaly
        assert result["preempted"] is False
        assert math.isnan(result["last_metrics"]["loss"])

        dumps = glob.glob(str(tmp_path / "flight" / "anomaly_step*_flight.json"))
        assert len(dumps) == 1, "forensics fire exactly once per run"
        snap = json.load(open(dumps[0]))
        assert snap["anomalies"] and "non-finite loss" in str(
            snap["anomalies"][0]["reasons"])
        assert snap["reason"].startswith("anomaly")
        # Goodput/wall-clock rode along (the clock runs under the
        # default flight-recorder knob).
        assert snap["wall_clock"]["goodput"] > 0
        # Offending batch captured for replay.
        npz = glob.glob(str(tmp_path / "flight" / "anomaly_step*_batch.npz"))
        assert npz
        arrays = np.load(npz[0])
        assert {"image", "label"} <= set(arrays.files)
        # Step HLO captured via the factories' AOT lower hook.
        assert glob.glob(str(tmp_path / "flight" / "anomaly_step*_hlo.txt"))
        # N-step profiler trace captured after the trigger.
        traces = glob.glob(str(tmp_path / "flight" / "anomaly_step*_trace"))
        assert traces and os.path.isdir(traces[0])
        assert glob.glob(traces[0] + "/**/*", recursive=True), \
            "trace dir is empty — stop_trace never ran"

    def test_nan_loss_raise_after_trace_window(self, mesh, tmp_path):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _image_cfg(tmp_path, anomaly_action="raise",
                         anomaly_trace_steps=1)
        tr = Trainer(cfg, mesh=mesh)
        _poison_after(tr, 2)
        with pytest.raises(AnomalyError, match="non-finite loss"):
            tr.fit()
        # Forensics were written before the raise.
        assert glob.glob(str(tmp_path / "flight" / "anomaly_step*_flight.json"))
        assert glob.glob(str(tmp_path / "flight" / "anomaly_step*_trace"))

    def test_raise_with_no_trace_window_is_immediate(self, mesh, tmp_path):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _image_cfg(tmp_path, anomaly_action="raise",
                         anomaly_trace_steps=0)
        tr = Trainer(cfg, mesh=mesh)
        calls = _poison_after(tr, 2)
        with pytest.raises(AnomalyError):
            tr.fit()
        # log_interval=2: the NaN (poisoned after call 2) is seen at the
        # step-4 flush and raises there — not at the end of the run.
        assert len(calls) == 4

    def test_grad_norm_metric_reaches_sinks(self, mesh, tmp_path):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _image_cfg(tmp_path).replace(
            num_epochs=1,
            observability=ObservabilityConfig(
                grad_norm=True, dump_dir=str(tmp_path / "flight")))
        Trainer(cfg, mesh=mesh).fit()
        rows = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
        train_rows = [r for r in rows if r["prefix"] == "train"]
        assert train_rows
        assert all(math.isfinite(r["grad_norm"]) and r["grad_norm"] > 0
                   for r in train_rows)
        # MFU plumbing: flops-rate rides along on every flush after the
        # first (CPU has no peak-FLOPs entry, so mfu itself is absent).
        assert any("model_flops_per_sec" in r for r in train_rows)


class TestLMTrainerAnomalyInjection:
    def test_nan_loss_skip_on_lm_engine(self, mesh, tmp_path):
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm",
            num_epochs=1,
            log_interval=2,
            eval_every=0,
            data=DataConfig(batch_size=2, prefetch=0),
            checkpoint=CheckpointConfig(directory=str(tmp_path / "ckpt"),
                                        interval=0),
            lm=LMConfig(seq_len=16, vocab_size=64, num_layers=1,
                        num_heads=2, hidden_dim=32, max_len=32,
                        train_sequences=64, eval_sequences=16),
            observability=ObservabilityConfig(
                grad_norm=True, anomaly_detection=True,
                anomaly_action="skip", anomaly_trace_steps=1,
                dump_dir=str(tmp_path / "flight")),
        )
        tr = LMTrainer(cfg, mesh=mesh)
        _poison_after(tr, 2)
        result = tr.fit()
        assert result["preempted"] is False
        dumps = glob.glob(str(tmp_path / "flight" / "anomaly_step*_flight.json"))
        assert len(dumps) == 1
        snap = json.load(open(dumps[0]))
        assert "non-finite loss" in str(snap["anomalies"][0]["reasons"])
        npz = glob.glob(str(tmp_path / "flight" / "anomaly_step*_batch.npz"))
        assert npz and {"tokens", "targets"} <= set(np.load(npz[0]).files)

    def test_preemption_still_works_with_observability(self, mesh, tmp_path):
        """The anomaly/observability path must not disturb the SIGTERM
        stop-at-sync-point machinery (the multihost barrier path)."""
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _image_cfg(tmp_path, anomaly_action="skip").replace(
            checkpoint=CheckpointConfig(directory=str(tmp_path / "ckpt"),
                                        interval=0, auto_resume=True))
        tr = Trainer(cfg, mesh=mesh)
        real_step = tr.train_step
        calls = []

        def step_then_signal(state, batch, rng):
            out = real_step(state, batch, rng)
            calls.append(1)
            if len(calls) == 2:
                signal.raise_signal(signal.SIGTERM)
            return out

        step_then_signal.lower = real_step.lower
        tr.train_step = step_then_signal
        result = tr.fit()
        assert result["preempted"] is True
        result2 = Trainer(cfg, mesh=mesh).fit()
        assert result2["preempted"] is False and result2["steps"] == 8


class TestCrashDump:
    def test_crash_writes_flight_record(self, mesh, tmp_path):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _image_cfg(tmp_path).replace(observability=ObservabilityConfig(
            dump_dir=str(tmp_path / "flight")))
        tr = Trainer(cfg, mesh=mesh)
        real_step = tr.train_step
        calls = []

        def exploding_step(state, batch, rng):
            calls.append(1)
            if len(calls) == 3:
                raise RuntimeError("boom")
            return real_step(state, batch, rng)

        tr.train_step = exploding_step
        with pytest.raises(RuntimeError, match="boom"):
            tr.fit()
        path = tmp_path / "flight" / "flight_crash.json"
        assert path.exists()
        snap = json.load(open(path))
        assert snap["reason"] == "crash"
        # The ring holds the pre-crash steps — the forensics a hung/dead
        # run otherwise takes to the grave.
        assert [s for s, _ in snap["steps"]] == [1, 2]
