"""Beam-search tests.

Oracles: (a) num_beams=1 must equal greedy sampling; (b) with the beam as
wide as the whole search space (K = V^N), beam search is exhaustive and
must find the global-argmax sequence — checked against brute force over
every possible continuation on a tiny model.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.inference import (
    BeamConfig,
    BeamSearcher,
    Generator,
    SampleConfig,
)
from distributed_training_tpu.models import get_model

VOCAB = 7


@pytest.fixture(scope="module")
def lm():
    # head_bias=True: the beam tests force token orderings by adding a
    # large lm_head bias (the model default is bias-less since round 5).
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=2, num_heads=2,
        hidden_dim=32, max_len=64, head_bias=True)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params


def full_logits(model, params, tokens):
    return model.apply({"params": params}, tokens, train=False)


def brute_force_best(model, params, prompt, n_new):
    """Enumerate all VOCAB^n_new continuations; return (best_seq, best_lp)."""
    best_seq, best_lp = None, -np.inf
    for cont in itertools.product(range(VOCAB), repeat=n_new):
        seq = jnp.concatenate(
            [prompt, jnp.asarray([cont], jnp.int32)], axis=1)
        logits = full_logits(model, params, seq)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = sum(
            float(lp[0, prompt.shape[1] - 1 + i, cont[i]])
            for i in range(n_new))
        if total > best_lp:
            best_seq, best_lp = cont, total
    return list(best_seq), best_lp


class TestBeamSearch:
    def test_single_beam_equals_greedy(self, lm):
        model, params = lm
        prompt = np.array([[1, 2, 3], [4, 5, 6]])
        beams, scores = BeamSearcher(model, params, BeamConfig(
            num_beams=1, max_new_tokens=6))(prompt)
        greedy = Generator(model, params, SampleConfig(
            max_new_tokens=6, temperature=0.0))(prompt)
        np.testing.assert_array_equal(beams[:, 0, :], greedy)
        assert beams.shape == (2, 1, 6)
        assert (scores <= 0).all()  # log-probabilities

    def test_exhaustive_beam_finds_global_argmax(self, lm):
        """K = V^N makes beam search exact: compare with brute force."""
        model, params = lm
        prompt = jnp.asarray([[2, 4]], jnp.int32)
        n_new = 2
        k = VOCAB ** n_new  # 49 beams cover the whole space
        beams, scores = BeamSearcher(model, params, BeamConfig(
            num_beams=k, max_new_tokens=n_new))(np.asarray(prompt))
        want_seq, want_lp = brute_force_best(model, params, prompt, n_new)
        assert beams[0, 0].tolist() == want_seq
        np.testing.assert_allclose(float(scores[0, 0]), want_lp, rtol=1e-4)

    def test_beam_score_beats_or_matches_greedy(self, lm):
        """Wider beams can only improve (or match) the best total log-prob."""
        model, params = lm
        prompt = np.array([[1, 5]])
        lp1 = BeamSearcher(model, params, BeamConfig(
            num_beams=1, max_new_tokens=5))(prompt)[1][0, 0]
        lp4 = BeamSearcher(model, params, BeamConfig(
            num_beams=4, max_new_tokens=5))(prompt)[1][0, 0]
        assert float(lp4) >= float(lp1) - 1e-5

    def test_beams_are_distinct_and_sorted(self, lm):
        model, params = lm
        beams, scores = BeamSearcher(model, params, BeamConfig(
            num_beams=4, max_new_tokens=4))(np.array([[3, 1]]))
        assert beams.shape == (1, 4, 4)
        rows = {tuple(r) for r in beams[0].tolist()}
        assert len(rows) == 4  # distinct hypotheses
        s = scores[0]
        assert all(s[i] >= s[i + 1] for i in range(3))  # best-first

    def test_eos_freezes_beam_with_pad_tail(self, lm):
        """Bias the head so EOS dominates: every beam should emit EOS then
        pad, with the score unchanged by the padding."""
        model, params = lm
        eos = 5
        biased = dict(params)
        head = dict(biased["lm_head"])
        head["bias"] = head["bias"].at[eos].add(1e3)
        biased["lm_head"] = head
        beams, scores = BeamSearcher(model, biased, BeamConfig(
            num_beams=2, max_new_tokens=5, eos_id=eos, pad_id=0))(
                np.array([[1, 2]]))
        assert beams[0, 0, 0] == eos
        assert (beams[0, 0, 1:] == 0).all()
        # Score ≈ lp(eos) only — padding contributed zero.
        assert float(scores[0, 0]) > -1.0

    def test_length_penalty_changes_ranking_shape(self, lm):
        model, params = lm
        plain = BeamSearcher(model, params, BeamConfig(
            num_beams=3, max_new_tokens=4))(np.array([[2, 2]]))
        pen = BeamSearcher(model, params, BeamConfig(
            num_beams=3, max_new_tokens=4, length_penalty=1.0))(
                np.array([[2, 2]]))
        # Same hypothesis space; penalized scores are scaled (larger, as
        # scores are negative and penalty > 1).
        assert float(pen[1][0, 0]) >= float(plain[1][0, 0])

    def test_length_counts_live_pad_tokens(self, lm):
        """pad_id (byte 0) is a legitimate live token: without EOS every
        beam runs the full horizon, so the penalized score must equal
        score / ((5+N)/6)^alpha even when token 0 appears mid-sequence."""
        model, params = lm
        biased = dict(params)
        head = dict(biased["lm_head"])
        head["bias"] = head["bias"].at[0].add(5.0)  # favor token 0 (== pad)
        biased["lm_head"] = head
        n = 4
        plain_seqs, plain_scores = BeamSearcher(model, biased, BeamConfig(
            num_beams=2, max_new_tokens=n))(np.array([[1, 2]]))
        pen_seqs, pen_scores = BeamSearcher(model, biased, BeamConfig(
            num_beams=2, max_new_tokens=n, length_penalty=1.0))(
                np.array([[1, 2]]))
        assert (plain_seqs[0, 0] == 0).any()  # token 0 actually emitted
        np.testing.assert_array_equal(plain_seqs, pen_seqs)
        np.testing.assert_allclose(
            pen_scores, plain_scores / ((5.0 + n) / 6.0), rtol=1e-5)

    def test_cache_overflow_rejected(self, lm):
        model, params = lm
        bs = BeamSearcher(model, params, BeamConfig(
            num_beams=2, max_new_tokens=60))
        with pytest.raises(ValueError, match="exceeds the KV cache"):
            bs(np.zeros((1, 10), np.int32))

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError, match="num_beams"):
            BeamConfig(num_beams=0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            BeamConfig(max_new_tokens=0)
