"""Bench regression gate tests (tools/bench_compare.py).

The gate's contract: 0 = no regression, 1 = regression (direction-aware
per metric), 2 = malformed input — and it must ingest every bench
format the repo emits (serve_bench SLA line, bench.py JSON lines among
human log lines, the driver's BENCH wrapper object).
"""

import json

import pytest

from conftest import load_cli_module

SLA = {
    "metric_absent": "ignored",
    "throughput_tok_s": 1000.0,
    "ttft_p95_ms": 20.0,
    "tpot_p95_ms": 2.0,
    "requests_finished": 8,
    "tokens_emitted": 64,
    "kv_reserved_vs_written": 4.0,
}


@pytest.fixture(scope="module")
def bc():
    return load_cli_module("tools/bench_compare.py")


def _write(tmp_path, name, obj):
    path = tmp_path / name
    path.write_text(obj if isinstance(obj, str) else json.dumps(obj))
    return str(path)


class TestVerdicts:
    def test_identical_files_pass(self, bc, tmp_path, capsys):
        p = _write(tmp_path, "base.json", SLA)
        assert bc.main([p, p]) == 0
        assert "REGRESSION" not in capsys.readouterr().out

    def test_throughput_drop_fails_direction_higher(self, bc, tmp_path):
        cur = dict(SLA, throughput_tok_s=400.0)  # -60% < 50% allowance
        assert bc.main([_write(tmp_path, "b.json", SLA),
                        _write(tmp_path, "c.json", cur)]) == 1

    def test_latency_growth_fails_direction_lower(self, bc, tmp_path):
        cur = dict(SLA, ttft_p95_ms=100.0)  # 5x > the 3.0 allowance
        assert bc.main([_write(tmp_path, "b.json", SLA),
                        _write(tmp_path, "c.json", cur)]) == 1

    def test_latency_improvement_never_fails(self, bc, tmp_path):
        cur = dict(SLA, ttft_p95_ms=0.1, throughput_tok_s=9999.0)
        assert bc.main([_write(tmp_path, "b.json", SLA),
                        _write(tmp_path, "c.json", cur)]) == 0

    def test_dropped_request_fails_zero_tolerance(self, bc, tmp_path):
        cur = dict(SLA, requests_finished=7)
        assert bc.main([_write(tmp_path, "b.json", SLA),
                        _write(tmp_path, "c.json", cur)]) == 1

    def test_metric_override_and_only(self, bc, tmp_path):
        cur = dict(SLA, throughput_tok_s=400.0, ttft_p95_ms=100.0)
        b = _write(tmp_path, "b.json", SLA)
        c = _write(tmp_path, "c.json", cur)
        # Loosen throughput, gate only it: the latency cliff is ignored.
        assert bc.main([b, c, "--metric", "throughput_tok_s=0.9",
                        "--only", "throughput_tok_s"]) == 0
        # Tighten it instead: now it trips.
        assert bc.main([b, c, "--metric", "throughput_tok_s=0.1",
                        "--only", "throughput_tok_s"]) == 1

    def test_both_direction_gates_deterministic_counters_two_sided(
            self, bc, tmp_path):
        """kv accounting is workload-deterministic: drift in EITHER
        direction is breakage — an inflated written count (ratio down)
        must trip the gate just like over-reservation growth (up)."""
        b = _write(tmp_path, "b.json", SLA)
        down = dict(SLA, kv_reserved_vs_written=2.0)  # written inflated
        up = dict(SLA, kv_reserved_vs_written=8.0)
        assert bc.main([b, _write(tmp_path, "d.json", down),
                        "--only", "kv_reserved_vs_written"]) == 1
        assert bc.main([b, _write(tmp_path, "u.json", up),
                        "--only", "kv_reserved_vs_written"]) == 1
        same = dict(SLA, kv_reserved_vs_written=4.01)  # within 5%
        assert bc.main([b, _write(tmp_path, "s.json", same),
                        "--only", "kv_reserved_vs_written"]) == 0

    def test_metric_missing_from_current_fails(self, bc, tmp_path):
        cur = {k: v for k, v in SLA.items() if k != "throughput_tok_s"}
        assert bc.main([_write(tmp_path, "b.json", SLA),
                        _write(tmp_path, "c.json", cur)]) == 1

    def test_zero_baseline_skipped_not_failed(self, bc, tmp_path):
        base = dict(SLA, ttft_p95_ms=0.0)
        cur = dict(SLA, ttft_p95_ms=50.0)
        assert bc.main([_write(tmp_path, "b.json", base),
                        _write(tmp_path, "c.json", cur)]) == 0

    def test_swaps_rejected_zero_tolerance_from_zero_baseline(
            self, bc, tmp_path):
        """Hot-swap gate: swaps_rejected is not-allowed-to-grow even
        from a zero baseline (the generic zero-baseline skip would
        otherwise let rejection drift through unseen), while an equal
        zero current stays clean and swaps_completed drift trips the
        both-direction zero tolerance."""
        base = dict(SLA, swaps_completed=1, swaps_rejected=0)
        b = _write(tmp_path, "b.json", base)
        clean = dict(base)
        assert bc.main([b, _write(tmp_path, "ok.json", clean),
                        "--only", "swaps_completed,swaps_rejected"]) == 0
        rejected = dict(base, swaps_rejected=2)
        assert bc.main([b, _write(tmp_path, "rej.json", rejected),
                        "--only", "swaps_rejected"]) == 1
        lost_swap = dict(base, swaps_completed=0)
        assert bc.main([b, _write(tmp_path, "lost.json", lost_swap),
                        "--only", "swaps_completed"]) == 1

    def test_json_output_machine_readable(self, bc, tmp_path, capsys):
        cur = dict(SLA, throughput_tok_s=1.0)
        rc = bc.main([_write(tmp_path, "b.json", SLA),
                      _write(tmp_path, "c.json", cur), "--json"])
        assert rc == 1
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["regressed"] is True
        verdicts = {v["metric"]: v["status"]
                    for v in out["records"][0]["comparisons"]}
        assert verdicts["throughput_tok_s"] == "REGRESSION"
        assert verdicts["ttft_p95_ms"] == "ok"


class TestInputFormats:
    def test_bench_wrapper_parsed_object(self, bc, tmp_path):
        """The driver's BENCH_rXX wrapper: compare the 'parsed' record."""
        wrap = {"n": 5, "cmd": "python bench.py", "rc": 0,
                "parsed": {"metric": "resnet50 throughput",
                           "value": 2581.4, "unit": "images/sec/chip"}}
        worse = {"parsed": {"metric": "resnet50 throughput",
                            "value": 1000.0, "unit": "images/sec/chip"}}
        b = _write(tmp_path, "b.json", wrap)
        assert bc.main([b, b]) == 0
        assert bc.main([b, _write(tmp_path, "c.json", worse)]) == 1

    def test_json_lines_matched_by_metric_name(self, bc, tmp_path):
        """bench.py emits image + LM lines among human log lines;
        records pair by their 'metric' field, not position."""
        base = ("[bench] warm-up done\n"
                + json.dumps({"metric": "image", "value": 100.0}) + "\n"
                + json.dumps({"metric": "lm", "value": 50.0}) + "\n")
        cur = (json.dumps({"metric": "lm", "value": 49.0}) + "\n"
               + json.dumps({"metric": "image", "value": 10.0}) + "\n")
        rc = bc.main([_write(tmp_path, "b.json", base),
                      _write(tmp_path, "c.json", cur), "--json"])
        assert rc == 1
        # swapped order still matched right: 'lm' ok, 'image' regressed

    def test_malformed_inputs_exit_2(self, bc, tmp_path, capsys):
        good = _write(tmp_path, "good.json", SLA)
        assert bc.main([good, str(tmp_path / "missing.json")]) == 2
        assert bc.main([good,
                        _write(tmp_path, "junk.json", "not json\n")]) == 2
        assert bc.main([good, good, "--metric", "nonsense"]) == 2
        assert bc.main([good, good, "--only", "no_such_metric"]) == 2
        err = capsys.readouterr().err
        assert "bench_compare: error:" in err

    def test_real_committed_baseline_loads(self, bc):
        """The committed CI baseline stays parseable and self-compares
        clean — a drift here means the gate step is broken."""
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(repo, "profiles", "serve_smoke_baseline.json")
        recs = bc.load_records(path)
        assert recs and recs[0]["requests_finished"] == 8
        assert bc.main([path, path]) == 0
