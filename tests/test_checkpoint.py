"""Checkpoint/resume round-trip (the surface the reference leaves unwired —
``resnet/colossal/colossal_train.py:40-42``, SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu import checkpoint as ckpt_lib
from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.train_state import init_train_state


@pytest.fixture()
def state():
    model = get_model("resnet_micro", num_classes=10, stem="cifar")
    tx = optax.adam(1e-3)
    return init_train_state(
        model, jax.random.PRNGKey(0), (2, 8, 8, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp16")))


def _mutate(state):
    new_params = jax.tree.map(lambda x: x + 1.0, state.params)
    return state.replace(
        step=state.step + 7,
        params=new_params,
        loss_scale=state.loss_scale.update(jnp.bool_(False)),
    )


def test_save_restore_roundtrip(tmp_path, state):
    mutated = _mutate(state)
    ckpt_lib.save_checkpoint(str(tmp_path), epoch=3, state=mutated)

    restored, start_epoch, _ = ckpt_lib.restore_checkpoint(
        str(tmp_path), 3, state)
    assert start_epoch == 4  # resume at the NEXT epoch
    assert int(restored.step) == 7
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(mutated.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Dynamic loss-scale state round-trips too (scale untouched after one
    # overflow with hysteresis=2, but the credit was consumed).
    assert float(restored.loss_scale.scale) == float(mutated.loss_scale.scale)
    assert int(restored.loss_scale.hysteresis_left) == 1


def test_restore_missing_raises(tmp_path, state):
    with pytest.raises(FileNotFoundError):
        ckpt_lib.restore_checkpoint(str(tmp_path), 0, state)


def test_restore_pre_next_epoch_format(tmp_path, state):
    """Saves from before the next_epoch meta carry only {epoch}; the format
    is detected from the on-disk structure (not exception retry) and the
    old epoch+1 resume semantics apply."""
    import orbax.checkpoint as ocp
    from flax import serialization

    mutated = _mutate(state)
    payload = {
        "state": serialization.to_state_dict(mutated),
        "meta": {"epoch": np.int32(5)},
    }
    path = str(tmp_path / "epoch_5")
    ocp.PyTreeCheckpointer().save(path, payload, force=True)

    restored, start_epoch, _ = ckpt_lib.restore_checkpoint(
        str(tmp_path), 5, state)
    assert start_epoch == 6
    assert int(restored.step) == 7


def test_restore_migrates_legacy_resnet_block_names(tmp_path, state):
    """Checkpoints from before the stage{i}_block{j} rename (Flax auto-names
    BasicBlock_0..7 in creation order) restore through the key-migration
    shim — params, batch_stats, AND the param-shaped Adam moments."""
    import orbax.checkpoint as ocp
    from flax import serialization

    mutated = _mutate(state)
    sd = serialization.to_state_dict(mutated)

    # Rebuild the old on-disk layout: creation order = (stage, block) order.
    new_names = sorted(
        (k for k in sd["params"] if k.startswith("stage")),
        key=lambda k: tuple(
            int(x) for x in k.replace("stage", "").split("_block")))
    to_legacy = {n: f"BasicBlock_{i}" for i, n in enumerate(new_names)}

    def rename(tree):
        if isinstance(tree, dict):
            return {to_legacy.get(k, k): rename(v) for k, v in tree.items()}
        return tree

    payload = {"state": rename(sd),
               "meta": {"epoch": np.int32(2), "next_epoch": np.int32(3)}}
    ocp.PyTreeCheckpointer().save(str(tmp_path / "epoch_2"), payload,
                                  force=True)

    restored, start_epoch, _ = ckpt_lib.restore_checkpoint(
        str(tmp_path), 2, state)
    assert start_epoch == 3
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(mutated.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(restored.opt_state),
                    jax.tree.leaves(mutated.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_tree_mismatch_surfaces_real_error(tmp_path, state):
    """A genuinely incompatible save must raise the orbax error once, not a
    confusing second error from an exception-driven format retry."""
    import orbax.checkpoint as ocp

    ocp.PyTreeCheckpointer().save(
        str(tmp_path / "epoch_0"),
        {"state": {"params": {"totally": np.zeros(3)}},
         "meta": {"epoch": np.int32(0), "next_epoch": np.int32(1)}},
        force=True)
    with pytest.raises(Exception) as ei:
        ckpt_lib.restore_checkpoint(str(tmp_path), 0, state)
    # the real structural mismatch, not a missing-next_epoch secondary error
    assert "next_epoch" not in str(ei.value)


def test_latest_epoch_and_prune(tmp_path, state):
    assert ckpt_lib.latest_epoch(str(tmp_path)) is None
    for e in (0, 1, 2, 3):
        ckpt_lib.save_checkpoint(str(tmp_path), e, state)
    assert ckpt_lib.latest_epoch(str(tmp_path)) == 3
    ckpt_lib.prune_checkpoints(str(tmp_path), keep=2)
    assert ckpt_lib.latest_epoch(str(tmp_path)) == 3
    restored, start, _ = ckpt_lib.restore_checkpoint(str(tmp_path), 3, state)
    assert start == 4
    with pytest.raises(FileNotFoundError):
        ckpt_lib.restore_checkpoint(str(tmp_path), 0, state)


def test_legacy_migration_rejects_shape_mismatch(tmp_path, state):
    """Same block count but different shapes (e.g. legacy resnet34 into a
    resnet50 template) must NOT be migrated — the plain structural error
    should surface instead of a confusing shape error on migrated keys."""
    from distributed_training_tpu.checkpoint import _legacy_block_rename
    from flax import serialization

    sd = serialization.to_state_dict(_mutate(state))["params"]
    new_names = sorted(
        (k for k in sd if k.startswith("stage")),
        key=lambda k: tuple(
            int(x) for x in k.replace("stage", "").split("_block")))
    # Matching-shape mapping is built...
    legacy = {f"BasicBlock_{i}": sd[n] for i, n in enumerate(new_names)}
    legacy |= {k: v for k, v in sd.items() if not k.startswith("stage")}
    assert _legacy_block_rename({"params": legacy}, {"params": sd})
    # ...but a per-block shape mismatch kills it.
    import numpy as np
    bad = dict(legacy)
    first = f"BasicBlock_0"
    bad[first] = jax.tree.map(lambda x: np.zeros(np.shape(x) + (1,)),
                              bad[first])
    assert _legacy_block_rename({"params": bad}, {"params": sd}) == {}


def test_skip_batches_guard_and_cheap_skip():
    """_SkipBatches refuses an out-of-range resume step and uses the
    loader's index-level iter_from when available."""
    from distributed_training_tpu.data.pipeline import ShardedDataLoader
    from distributed_training_tpu.data.pipeline import SkipBatches

    images = np.arange(8 * 4 * 4 * 3, dtype=np.float32).reshape(8, 4, 4, 3)
    labels = np.arange(8, dtype=np.int32)
    loader = ShardedDataLoader(
        images, labels, global_batch_size=2, shuffle=True, augment="none",
        process_index=0, process_count=1)
    loader.set_epoch(0)
    full = [b["label"].tolist() for b in loader]
    skipped = [b["label"].tolist() for b in SkipBatches(loader, 2)]
    assert skipped == full[2:]  # same shuffle, prefix dropped
    assert len(SkipBatches(loader, 2)) == len(full) - 2
    with pytest.raises(ValueError, match="epoch geometry"):
        SkipBatches(loader, 4)
