"""Checkpoint/resume round-trip (the surface the reference leaves unwired —
``resnet/colossal/colossal_train.py:40-42``, SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu import checkpoint as ckpt_lib
from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.train_state import init_train_state


@pytest.fixture()
def state():
    model = get_model("resnet18", num_classes=10, stem="cifar")
    tx = optax.adam(1e-3)
    return init_train_state(
        model, jax.random.PRNGKey(0), (2, 8, 8, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp16")))


def _mutate(state):
    new_params = jax.tree.map(lambda x: x + 1.0, state.params)
    return state.replace(
        step=state.step + 7,
        params=new_params,
        loss_scale=state.loss_scale.update(jnp.bool_(False)),
    )


def test_save_restore_roundtrip(tmp_path, state):
    mutated = _mutate(state)
    ckpt_lib.save_checkpoint(str(tmp_path), epoch=3, state=mutated)

    restored, start_epoch = ckpt_lib.restore_checkpoint(
        str(tmp_path), 3, state)
    assert start_epoch == 4  # resume at the NEXT epoch
    assert int(restored.step) == 7
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(mutated.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Dynamic loss-scale state round-trips too (scale untouched after one
    # overflow with hysteresis=2, but the credit was consumed).
    assert float(restored.loss_scale.scale) == float(mutated.loss_scale.scale)
    assert int(restored.loss_scale.hysteresis_left) == 1


def test_restore_missing_raises(tmp_path, state):
    with pytest.raises(FileNotFoundError):
        ckpt_lib.restore_checkpoint(str(tmp_path), 0, state)


def test_latest_epoch_and_prune(tmp_path, state):
    assert ckpt_lib.latest_epoch(str(tmp_path)) is None
    for e in (0, 1, 2, 3):
        ckpt_lib.save_checkpoint(str(tmp_path), e, state)
    assert ckpt_lib.latest_epoch(str(tmp_path)) == 3
    ckpt_lib.prune_checkpoints(str(tmp_path), keep=2)
    assert ckpt_lib.latest_epoch(str(tmp_path)) == 3
    restored, start = ckpt_lib.restore_checkpoint(str(tmp_path), 3, state)
    assert start == 4
    with pytest.raises(FileNotFoundError):
        ckpt_lib.restore_checkpoint(str(tmp_path), 0, state)
