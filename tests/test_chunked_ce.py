"""Chunked cross-entropy: [B, T, vocab] logits never materialize.

Equivalence is the load-bearing property: chunked CE must reproduce the
whole-logits loss, gradients, and training trajectory bitwise (same fp32
head matmul, just sliced over time). The memory win itself is measured on
hardware (BASELINE.md: B8·T16384·V50304 fp32 logits = 26 GB > HBM).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import (
    DataConfig,
    LMConfig,
    MeshSpec,
    PrecisionConfig,
    TrainConfig,
)
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state
from distributed_training_tpu.train.lm_step import (
    chunked_ce_and_accuracy,
    make_lm_batch,
    make_lm_train_step,
    make_tp_lm_train_step,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.train_state import init_train_state

VOCAB = 37


def _model(**kw):
    return get_model("transformer_lm", num_classes=VOCAB, num_layers=2,
                     num_heads=2, hidden_dim=32, max_len=64, **kw)


def _state(model, tx):
    return init_train_state(
        model, jax.random.PRNGKey(0), (2, 8), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
        input_dtype=jnp.int32)


class TestHelper:
    def test_matches_full_ce(self):
        rng = np.random.RandomState(0)
        hidden = jnp.asarray(rng.randn(2, 16, 8), jnp.float32)
        w = jnp.asarray(rng.randn(8, VOCAB), jnp.float32)
        b = jnp.asarray(rng.randn(VOCAB), jnp.float32)
        targets = jnp.asarray(rng.randint(0, VOCAB, (2, 16)), jnp.int32)
        logits = hidden @ w + b
        want_ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
        want_acc = jnp.mean(
            (jnp.argmax(logits, -1) == targets).astype(jnp.float32))
        for chunk in (4, 8, 16):
            ce, acc = chunked_ce_and_accuracy(
                hidden, {"kernel": w, "bias": b}, targets, chunk)
            np.testing.assert_allclose(float(ce), float(want_ce), rtol=1e-6)
            np.testing.assert_allclose(float(acc), float(want_acc), rtol=1e-6)

    def test_grads_match_full_ce(self):
        rng = np.random.RandomState(1)
        hidden = jnp.asarray(rng.randn(2, 12, 8), jnp.float32)
        w = jnp.asarray(rng.randn(8, VOCAB), jnp.float32)
        b = jnp.zeros((VOCAB,), jnp.float32)
        targets = jnp.asarray(rng.randint(0, VOCAB, (2, 12)), jnp.int32)

        def full(h, w):
            return optax.softmax_cross_entropy_with_integer_labels(
                h @ w + b, targets).mean()

        def chunked(h, w):
            return chunked_ce_and_accuracy(
                h, {"kernel": w, "bias": b}, targets, 4)[0]

        ga = jax.grad(full, argnums=(0, 1))(hidden, w)
        gb = jax.grad(chunked, argnums=(0, 1))(hidden, w)
        for a, b_ in zip(ga, gb):
            np.testing.assert_allclose(a, b_, rtol=1e-5, atol=1e-7)

    def test_indivisible_chunk_rejected(self):
        hidden = jnp.zeros((1, 10, 4))
        with pytest.raises(ValueError, match="divide"):
            chunked_ce_and_accuracy(
                hidden, {"kernel": jnp.zeros((4, VOCAB)),
                         "bias": jnp.zeros(VOCAB)},
                jnp.zeros((1, 10), jnp.int32), 3)


class TestStepEquivalence:
    def test_tp_step_chunked_matches_plain(self, mesh):
        model = _model(seq_axis=None)
        tx = optax.adam(1e-3)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (8, 17)), jnp.int32)
        batch = make_lm_batch(tokens)
        rng = jax.random.PRNGKey(5)

        def run(ce_chunk):
            step = make_tp_lm_train_step(
                mesh, model=model, donate=False, ce_chunk=ce_chunk)
            state = _state(model, tx)
            state = place_state(state, step.state_shardings(state))
            new_state, m = step(state, batch, rng)
            return jax.device_get(new_state.params), m

        pa, ma = run(None)
        pb, mb = run(4)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            pa, pb)
        for k in ("loss", "accuracy", "perplexity"):
            np.testing.assert_allclose(
                float(ma[k]), float(mb[k]), rtol=1e-5)

    def test_sequence_step_chunked_matches_plain(self):
        from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh

        mesh = create_mesh(MeshConfig(data=2, sequence=4))
        model = _model(seq_axis="sequence")
        tx = optax.adam(1e-3)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (4, 17)), jnp.int32)
        batch = make_lm_batch(tokens)  # T=16, 4 per sequence shard
        rng = jax.random.PRNGKey(5)

        def run(ce_chunk):
            step = make_lm_train_step(
                mesh, model=model, donate=False, ce_chunk=ce_chunk)
            state = _state(model, tx)
            new_state, m = step(state, batch, rng)
            return jax.device_get(new_state.params), m

        pa, ma = run(None)
        pb, mb = run(2)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            pa, pb)
        np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                                   rtol=1e-5)


class TestTrainerWiring:
    def test_lm_trainer_chunked_fit(self, mesh):
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm", num_epochs=1, log_interval=2,
            data=DataConfig(batch_size=2, max_steps_per_epoch=3),
            lm=LMConfig(seq_len=16, vocab_size=VOCAB, num_layers=1,
                        num_heads=2, hidden_dim=16, max_len=32,
                        ce_chunk_size=4, train_sequences=64,
                        eval_sequences=32),
        )
        result = LMTrainer(cfg, mesh=mesh).fit()
        assert np.isfinite(result["final_perplexity"])

    def test_lm_trainer_save_probs_fit(self, mesh):
        """ce_save_probs reaches the product surface (config → trainer →
        step builder), not just the bench harness."""
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm", num_epochs=1, log_interval=2,
            data=DataConfig(batch_size=2, max_steps_per_epoch=3),
            lm=LMConfig(seq_len=16, vocab_size=VOCAB, num_layers=1,
                        num_heads=2, hidden_dim=16, max_len=32,
                        ce_save_probs=True, train_sequences=64,
                        eval_sequences=32),
        )
        result = LMTrainer(cfg, mesh=mesh).fit()
        assert np.isfinite(result["final_perplexity"])

    def test_pipeline_composes_with_chunking(self, devices):
        """ce_chunk through the pipeline executor (round-3; the step-level
        equivalence is pinned by test_pp_ce_chunk_matches_full_logits) —
        the trainer wires it end-to-end."""
        import numpy as np

        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm", num_epochs=1, eval_every=1,
            mesh=MeshSpec(data=-1, pipe=2),
            data=DataConfig(batch_size=4, max_steps_per_epoch=2),
            lm=LMConfig(seq_len=16, vocab_size=VOCAB, num_layers=2,
                        num_heads=2, hidden_dim=16, max_len=32,
                        num_microbatches=2, ce_chunk_size=4,
                        train_sequences=64, eval_sequences=32),
        )
        result = LMTrainer(cfg).fit()
        assert np.isfinite(result["final_perplexity"])

    @pytest.mark.parametrize("bad_chunk", [5, -4, 0])
    def test_invalid_chunk_rejected_at_construction(self, mesh, bad_chunk):
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm",
            data=DataConfig(batch_size=2),
            lm=LMConfig(seq_len=16, vocab_size=VOCAB, num_layers=1,
                        num_heads=2, hidden_dim=16, max_len=32,
                        ce_chunk_size=bad_chunk),
        )
        with pytest.raises(ValueError, match="ce_chunk_size"):
            LMTrainer(cfg, mesh=mesh)


class TestLogitsDtype:
    """The bf16-logits throughput lever (models/gpt.py::make_lm_head)."""

    def test_fused_ce_matches_optax_fp32(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(4, 16, VOCAB) * 5, jnp.float32)
        targets = jnp.asarray(rng.randint(0, VOCAB, (4, 16)), jnp.int32)
        from distributed_training_tpu.train.lm_step import _fused_softmax_ce

        want = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
        got = _fused_softmax_ce(logits, targets)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)
        gw = jax.grad(lambda l: optax.softmax_cross_entropy_with_integer_labels(
            l, targets).mean())(logits)
        gg = jax.grad(lambda l: _fused_softmax_ce(l, targets))(logits)
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gw),
                                   atol=1e-7, rtol=1e-5)

    def test_bf16_logits_model_emits_bf16_and_tracks_fp32_loss(self):
        model32 = _model(dtype=jnp.bfloat16)
        model16 = _model(dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16)
        params = model32.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (2, 17)), jnp.int32)
        batch = make_lm_batch(toks)
        lo16 = model16.apply({"params": params}, batch["tokens"])
        lo32 = model32.apply({"params": params}, batch["tokens"])
        assert lo16.dtype == jnp.bfloat16
        from distributed_training_tpu.train.lm_step import _fused_softmax_ce

        ce16 = _fused_softmax_ce(lo16, batch["targets"])
        ce32 = _fused_softmax_ce(lo32, batch["targets"])
        assert ce16.dtype == jnp.float32
        # bf16 rounding of the logits perturbs the loss by O(2^-8) relative.
        np.testing.assert_allclose(np.asarray(ce16), np.asarray(ce32),
                                   rtol=3e-2)

    def test_chunked_ce_honors_logits_dtype(self):
        """ce_chunk × logits_dtype=bf16: the chunked path must compute the
        same bf16-logit CE as the unchunked head, not silently fp32."""
        model = _model(dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (2, 17)), jnp.int32)
        batch = make_lm_batch(toks)
        logits = model.apply({"params": params}, batch["tokens"])
        from distributed_training_tpu.train.lm_step import _fused_softmax_ce

        want = _fused_softmax_ce(logits, batch["targets"])
        hidden = model.apply({"params": params}, batch["tokens"],
                             return_hidden=True)
        ce, _ = chunked_ce_and_accuracy(
            hidden, params["lm_head"], batch["targets"], 8,
            logits_dtype=jnp.bfloat16)
        np.testing.assert_allclose(np.asarray(ce), np.asarray(want),
                                   rtol=1e-5)


class TestCEVariants:
    """Round-5 CE levers: accuracy derived from the CE max (deletes the
    argmax HBM pass) and the saved-probs backward (deletes the exp
    recompute from both head matmul fusions)."""

    def _data(self, dtype=jnp.float32):
        rng = np.random.RandomState(7)
        logits = jnp.asarray(rng.randn(4, 9, VOCAB) * 4, dtype)
        targets = jnp.asarray(rng.randint(0, VOCAB, (4, 9)), jnp.int32)
        return logits, targets

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_accuracy_from_max_matches_argmax(self, dtype):
        from distributed_training_tpu.train.lm_step import _fused_ce_rows

        logits, targets = self._data(dtype)
        _, correct = _fused_ce_rows(logits, targets, with_correct=True)
        want = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
        np.testing.assert_array_equal(np.asarray(correct), np.asarray(want))

    def test_accuracy_tie_semantics(self):
        """Ties count as correct (tie-inclusive top-1): when the label
        logit exactly equals another index's max, argmax-first would call
        it wrong, the max-equality form calls it right. Documented, not a
        bug — continuous logits tie with measure zero."""
        from distributed_training_tpu.train.lm_step import _fused_ce_rows

        logits = jnp.zeros((1, 1, VOCAB)).at[0, 0, 3].set(5.0)
        logits = logits.at[0, 0, 11].set(5.0)
        targets = jnp.asarray([[11]], jnp.int32)
        assert int(jnp.argmax(logits, -1)[0, 0]) == 3  # argmax says wrong
        _, correct = _fused_ce_rows(logits, targets, with_correct=True)
        assert float(correct[0, 0]) == 1.0

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_saved_probs_forward_bit_identical(self, dtype):
        from distributed_training_tpu.train.lm_step import (
            _ce_rows_saved_probs,
            _fused_ce_rows,
        )

        logits, targets = self._data(dtype)
        r1, c1 = _fused_ce_rows(logits, targets, with_correct=True)
        r2, c2 = _ce_rows_saved_probs(logits, targets, with_correct=True)
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))

    def test_saved_probs_grad_within_bf16_rounding(self):
        from distributed_training_tpu.train.lm_step import (
            _ce_rows_saved_probs,
            _fused_ce_rows,
        )

        logits, targets = self._data()
        g1 = jax.grad(lambda lg: _fused_ce_rows(lg, targets).mean())(logits)
        g2 = jax.jit(jax.grad(
            lambda lg: _ce_rows_saved_probs(lg, targets).mean()))(logits)
        # p is rounded to bf16 (~2^-8 relative); the onehot term is exact.
        scale = float(jnp.max(jnp.abs(g1)))
        np.testing.assert_allclose(np.asarray(g2), np.asarray(g1),
                                   atol=5e-3 * scale)

    def test_saved_probs_refuses_ce_chunk(self, mesh):
        """ce_chunk remats per-chunk logits, which would silently discard
        the saved probs — the combination must refuse at construction."""
        model = _model(seq_axis=None)
        with pytest.raises(ValueError, match="ce_save_probs"):
            make_tp_lm_train_step(mesh, model=model, ce_chunk=4,
                                  ce_save_probs=True)

    def test_saved_probs_step_metrics_match(self, mesh):
        """Forward math is bit-identical, so step metrics must agree
        exactly; only the gradient sees the bf16-rounded probs."""
        model = _model(seq_axis=None)
        tx = optax.adam(1e-3)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (8, 17)), jnp.int32)
        batch = make_lm_batch(tokens)
        rng = jax.random.PRNGKey(5)

        def run(save_probs):
            step = make_tp_lm_train_step(
                mesh, model=model, donate=False, ce_save_probs=save_probs)
            state = _state(model, tx)
            state = place_state(state, step.state_shardings(state))
            _, m = step(state, batch, rng)
            return m

        ma, mb = run(False), run(True)
        for k in ("loss", "accuracy", "perplexity"):
            np.testing.assert_allclose(float(ma[k]), float(mb[k]),
                                       rtol=1e-6)


class TestHeadBias:
    """head_bias=False (GPT-2's real head has none): the param disappears,
    forward stays finite, and the chunked CE tolerates the missing bias."""

    def test_no_bias_param_and_chunked_ce_matches(self):
        model = _model(head_bias=False)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
        assert "bias" not in params["lm_head"]
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (2, 17)), jnp.int32)
        batch = make_lm_batch(toks)
        logits = model.apply({"params": params}, batch["tokens"])
        from distributed_training_tpu.train.lm_step import _fused_softmax_ce

        want = _fused_softmax_ce(logits, batch["targets"])
        hidden = model.apply({"params": params}, batch["tokens"],
                             return_hidden=True)
        ce, _ = chunked_ce_and_accuracy(
            hidden, params["lm_head"], batch["targets"], 8)
        np.testing.assert_allclose(np.asarray(ce), np.asarray(want),
                                   rtol=1e-5)
