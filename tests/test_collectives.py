"""Compiled-HLO collective accounting per strategy (VERDICT r2 #6).

The multi-chip scaling evidence this environment can produce: assert the
communication each strategy's compiled 8-device step actually contains —
DP's gradient all-reduce sized like the gradients, ZeRO-1's param
all-gather, TP's per-block psums, the ring's and pipeline's ppermutes.
``tools/collective_accounting.py`` commits the full table to
``profiles/collectives_8dev.json``; these tests pin the load-bearing kinds
so a sharding regression (a collective silently disappearing or the grad
reduce ballooning) fails loudly.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import (
    place_state,
    state_shardings,
)
from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
from distributed_training_tpu.train.lm_step import (
    make_lm_batch,
    make_lm_train_step,
    make_pp_lm_train_step,
    make_tp_lm_train_step,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.step import make_train_step
from distributed_training_tpu.train.train_state import (
    TrainState,
    init_train_state,
    param_count,
)
from distributed_training_tpu.utils.hlo import (
    collective_accounting,
    step_collectives,
)

VOCAB = 32


def _image_case(zero_stage, mesh_kw):
    mesh = create_mesh(MeshConfig(**mesh_kw), devices=jax.devices())
    model = get_model("resnet_micro", num_classes=10, stem="cifar")
    state = init_train_state(
        model, jax.random.PRNGKey(0), (8, 8, 8, 3), optax.adam(1e-3),
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    state = place_state(state, state_shardings(state, mesh, zero_stage))
    rng = np.random.RandomState(0)
    batch = {"image": rng.rand(16, 8, 8, 3).astype(np.float32),
             "label": rng.randint(0, 10, 16).astype(np.int32)}
    step = make_train_step(mesh, zero_stage=zero_stage, donate=False)
    return step_collectives(step, state, batch, jax.random.PRNGKey(1)), state


def _lm_state(model):
    return init_train_state(
        model, jax.random.PRNGKey(0), (2, 8), optax.adam(1e-3),
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
        input_dtype=jnp.int32)


def _lm_batch(step):
    tokens = np.random.RandomState(0).randint(0, VOCAB, (8, 17)).astype(
        np.int32)
    return jax.device_put(
        {k: jnp.asarray(v) for k, v in make_lm_batch(tokens).items()},
        step.batch_shardings)


def test_dp_allreduce_is_the_gradient():
    """Plain DP compiles to one bucketed all-reduce whose payload covers
    the fp32 gradients (+ BN stats and metric scalars), with no gathers or
    permutes — the wire-level DDP contract."""
    acct, state = _image_case(0, dict(data=-1))
    grad_bytes = 4 * param_count(state.params)
    assert "all-reduce" in acct
    assert acct["all-reduce"]["bytes"] >= grad_bytes
    assert acct["all-reduce"]["bytes"] < 2 * grad_bytes  # not ballooning
    assert "all-gather" not in acct
    assert "collective-permute" not in acct


def test_zero3_gathers_params_on_use():
    """Stage 3 stores params sharded; the step must all-gather them for
    consumption (FSDP gather-on-use) — absent entirely at stage 0."""
    acct0, _ = _image_case(0, dict(data=-1))
    acct3, _ = _image_case(3, dict(data=-1))
    assert "all-gather" not in acct0
    assert "all-gather" in acct3
    assert acct3["all-gather"]["bytes"] > 0


def test_ring_permutes_and_fused_grad_allreduce():
    """The sequence strategy's only collectives: K/V ppermutes in the ring
    loop (2 per attention layer, fwd + transposed bwd) and the grad-pmean
    all-reduce whose payload covers the fp32 gradients without ballooning.
    (On TPU the combiner fuses the per-leaf reduces into ONE bucket — the
    committed artifact pins count == 1; this backend's combiner may leave
    them per-leaf, so the live assertion pins the payload, not the static
    op count.)"""
    mesh = create_mesh(MeshConfig(data=4, sequence=2), devices=jax.devices())
    model = get_model("transformer_lm", num_classes=VOCAB,
                      seq_axis="sequence", num_layers=2, num_heads=2,
                      hidden_dim=16, max_len=64)
    step = make_lm_train_step(mesh, model=model, donate=False)
    state = _lm_state(model)
    state = place_state(state, step.state_shardings(state))
    acct = step_collectives(step, state, _lm_batch(step),
                            jax.random.PRNGKey(1))
    assert acct["collective-permute"]["count"] >= 2 * model.num_layers
    grad_bytes = 4 * param_count(state.params)
    assert acct["all-reduce"]["bytes"] >= grad_bytes
    assert acct["all-reduce"]["bytes"] < 2 * grad_bytes  # not ballooning
    assert "all-gather" not in acct


def test_sp_zero1_adds_param_allgather():
    """SP×ZeRO-1's wire signature: the all-gather of updated params
    (sharded Adam slices → replicated params), absent at stage 0."""
    mesh = create_mesh(MeshConfig(data=4, sequence=2), devices=jax.devices())
    model = get_model("transformer_lm", num_classes=VOCAB,
                      seq_axis="sequence", num_layers=2, num_heads=2,
                      hidden_dim=16, max_len=64)
    accts = {}
    for stage in (0, 1):
        step = make_lm_train_step(mesh, model=model, donate=False,
                                  zero_stage=stage)
        state = _lm_state(model)
        state = place_state(state, step.state_shardings(state))
        accts[stage] = step_collectives(step, state, _lm_batch(step),
                                        jax.random.PRNGKey(1))
    assert "all-gather" not in accts[0]
    assert accts[1]["all-gather"]["bytes"] > 0


def test_tp_emits_per_block_psums():
    """Megatron TP: GSPMD inserts the row-parallel psums — at least one
    all-reduce per decoder block per pass direction, far more than DP's
    single fused grad reduce."""
    mesh = create_mesh(MeshConfig(data=4, model=2), devices=jax.devices())
    model = get_model("transformer_lm", num_classes=VOCAB, seq_axis=None,
                      num_layers=2, num_heads=2, hidden_dim=16, max_len=64)
    step = make_tp_lm_train_step(mesh, model=model, donate=False)
    state = _lm_state(model)
    state = place_state(state, step.state_shardings(state))
    acct = step_collectives(step, state, _lm_batch(step),
                            jax.random.PRNGKey(1))
    assert acct["all-reduce"]["count"] >= 2 * model.num_layers


def test_tp_overlap_swaps_psums_for_permute_chains():
    """The ring-overlapped TP schedule's wire signature: the per-block
    megatron collectives become collective-permute chains (≥ 4 rings per
    block: qkv/out/fc1/fc2, forward + ring-overlapped backward), the
    monolithic layer all-reduces shrink to the gradient pmean +
    replicated-leaf completions, and NO reduce-scatter or extra all-gather
    materializes in their place."""
    mesh = create_mesh(MeshConfig(data=4, model=2), devices=jax.devices())
    model = get_model("transformer_lm", num_classes=VOCAB, seq_axis=None,
                      num_layers=2, num_heads=2, hidden_dim=16, max_len=64)

    def acct_for(overlap):
        step = make_tp_lm_train_step(mesh, model=model, donate=False,
                                     tp_overlap=overlap)
        state = _lm_state(model)
        state = place_state(state, step.state_shardings(state))
        return step_collectives(step, state, _lm_batch(step),
                                jax.random.PRNGKey(1))

    plain, overlap = acct_for(False), acct_for(True)
    assert "collective-permute" not in plain
    assert overlap["collective-permute"]["count"] >= 4 * model.num_layers
    # The [B, T, D]-sized per-block psums are gone — only the grad pmean
    # and the replicated-leaf completions remain as all-reduce payload.
    assert overlap["all-reduce"]["bytes"] < plain["all-reduce"]["bytes"]
    assert "reduce-scatter" not in overlap
    assert (overlap.get("all-gather", {}).get("bytes", 0)
            <= plain.get("all-gather", {}).get("bytes", 0))


def test_pp_stage_hops_are_permutes():
    """GPipe's stage-to-stage activation hops compile to
    collective-permute (fwd + the autodiff-transposed reverse hop)."""
    mesh = create_mesh(MeshConfig(data=4, pipe=2), devices=jax.devices())
    model = get_model("transformer_lm", num_classes=VOCAB, seq_axis=None,
                      num_layers=2, num_heads=2, hidden_dim=16, max_len=64)
    step = make_pp_lm_train_step(mesh, model=model, num_microbatches=2,
                                 donate=False)
    state = TrainState.create(
        apply_fn=step.pipelined.apply_fn,
        params=step.pipelined.init_params(jax.random.PRNGKey(0)),
        tx=optax.adam(1e-3),
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    state = place_state(state, step.state_shardings(state))
    acct = step_collectives(step, state, _lm_batch(step),
                            jax.random.PRNGKey(1))
    assert acct["collective-permute"]["count"] >= 2


def test_committed_artifact_covers_all_strategies():
    """profiles/collectives_8dev.json is the committed evidence table: it
    must exist, cover every dryrun strategy, and every strategy must have
    recorded at least one collective."""
    path = os.path.join(os.path.dirname(__file__), "..", "profiles",
                        "collectives_8dev.json")
    with open(path) as fh:
        report = json.load(fh)
    assert report["devices"] == 8
    strategies = report["strategies"]
    for expected in ("image dp (zero-0)", "image dp×fsdp zero-1",
                     "image dp zero-3", "lm dp×tp zero-1", "lm dp×pp (gpipe)",
                     "lm dp×pp zero-1", "lm dp×pp circular (v=2)",
                     "lm dp×ep (moe)", "image vit dp×tp zero-1",
                     "lm dp×sp (ring)", "lm dp×sp zero-1",
                     "lm dp×sp×tp", "lm dp×sp×ep",
                     "lm dp×pp×ep zero-1 (moe stages)",
                     "lm dp×pp×sp zero-1 (ring-in-stage)",
                     "lm dp×tp overlap", "lm dp×sp×tp overlap",
                     "image vit dp×tp overlap"):
        assert expected in strategies, expected
        assert strategies[expected]["collectives"], expected
        assert strategies[expected]["grad_bytes_fp32"] > 0
    # Substance, not just coverage: the recorded numbers must satisfy the
    # same wire invariants the live tests assert, so a regenerated
    # artifact from drifted builders fails here.
    dp = strategies["image dp (zero-0)"]
    assert dp["collectives"]["all-reduce"]["bytes"] >= dp["grad_bytes_fp32"]
    assert "all-gather" not in dp["collectives"]
    assert "all-gather" in strategies["image dp zero-3"]["collectives"]
    sp = strategies["lm dp×sp (ring)"]["collectives"]
    assert sp["collective-permute"]["count"] >= 4
    # PP×EP (round 5): the pipeline's ppermutes AND the ZeRO-1 opt-state
    # all-gather must both appear — an artifact regenerated from a builder
    # that dropped either composition half fails here.
    ppe = strategies["lm dp×pp×ep zero-1 (moe stages)"]["collectives"]
    assert ppe["collective-permute"]["count"] >= 2
    assert "all-gather" in ppe
    # SP×PP: MORE ppermutes than the plain pipeline (pipe hops + the
    # ring's per-tick K/V rotation) — a K/V all-gather materialization
    # regression would collapse the count back.
    spp = strategies["lm dp×pp×sp zero-1 (ring-in-stage)"]["collectives"]
    gpipe = strategies["lm dp×pp (gpipe)"]["collectives"]
    assert (spp["collective-permute"]["count"]
            > gpipe["collective-permute"]["count"])
    assert sp["all-reduce"]["count"] == 1
    assert "all-gather" not in sp
    assert "all-gather" in strategies["lm dp×sp zero-1"]["collectives"]
    assert "collective-permute" in strategies["lm dp×pp (gpipe)"][
        "collectives"]
    # Round 4: PP×ZeRO-1 adds the opt-state all-gather beside the GPipe
    # ppermute; the circular schedule keeps the SAME static ppermute count
    # (the ring wraps v× — more trips, not more compiled collectives).
    ppz = strategies["lm dp×pp zero-1"]["collectives"]
    assert "all-gather" in ppz and "collective-permute" in ppz
    assert "all-gather" not in strategies["lm dp×pp (gpipe)"]["collectives"]
    circ = strategies["lm dp×pp circular (v=2)"]["collectives"]
    assert circ["collective-permute"]["count"] == \
        strategies["lm dp×pp (gpipe)"]["collectives"][
            "collective-permute"]["count"]
    # ViT×TP: row-parallel psums (> the one DP grad all-reduce) + zero-1
    # gathers.
    vit = strategies["image vit dp×tp zero-1"]["collectives"]
    assert vit["all-reduce"]["count"] > 2
    assert "all-gather" in vit
    # Ring-overlapped TP rows (round 6): collective-permute chains stand in
    # for the monolithic layer collectives — at least one ring per
    # projection per block per direction — with no reduce-scatter anywhere;
    # the SP×TP composition adds the K/V ring's ppermutes on top of the
    # matmul rings.
    for row in ("lm dp×tp overlap", "lm dp×sp×tp overlap",
                "image vit dp×tp overlap"):
        ov = strategies[row]["collectives"]
        assert ov["collective-permute"]["count"] >= 8, row
        assert "reduce-scatter" not in ov, row
    assert (strategies["lm dp×sp×tp overlap"]["collectives"]
            ["collective-permute"]["count"]
            > strategies["lm dp×tp overlap"]["collectives"]
            ["collective-permute"]["count"])
    assert "all-gather" not in strategies["image vit dp×tp overlap"][
        "collectives"]


def test_parser_handles_tuple_and_async_forms():
    """The HLO parser itself: bucketed tuple all-reduces (with /*index*/
    comments), async *-start/-done pairs (counted once), and layout
    annotations."""
    text = "\n".join([
        "  %all-reduce.1 = (f32[16]{0}, /*index=1*/f32[2,8]{1,0}) "
        "all-reduce(%a, %b), replica_groups={{0,1}}",
        "  %ag = f32[64,32]{1,0:T(8,128)} all-gather-start(%x), dim=0",
        "  %agd = f32[64,32]{1,0} all-gather-done(%ag)",
        "  %cp = bf16[4,8]{1,0} collective-permute(%y), "
        "source_target_pairs={{0,1}}",
        "  %f = f32[8]{0} fusion(%z), kind=kLoop",
    ])
    acct = collective_accounting(text)
    assert acct["all-reduce"] == {"count": 1, "bytes": 16 * 4 + 16 * 4}
    assert acct["all-gather"] == {"count": 1, "bytes": 64 * 32 * 4}
    assert acct["collective-permute"] == {"count": 1, "bytes": 4 * 8 * 2}
    assert "fusion" not in acct
