"""Config system tests: plugin presets + ds_config ingestion round-trip."""

import pytest

from distributed_training_tpu.config import (
    PLUGINS,
    TrainConfig,
    from_ds_config,
)


def _reference_ds_config(dtype="bf16", stage=0):
    # Mirrors resnet/deepspeed/deepspeed_train.py:172-220 field-for-field.
    return {
        "train_batch_size": 96,
        "steps_per_print": 2000,
        "optimizer": {
            "type": "Adam",
            "params": {"lr": 0.001, "betas": [0.8, 0.999], "eps": 1e-8,
                       "weight_decay": 3e-7},
        },
        "scheduler": {
            "type": "WarmupLR",
            "params": {"warmup_min_lr": 0, "warmup_max_lr": 0.001,
                       "warmup_num_steps": 1000},
        },
        "gradient_clipping": 1.0,
        "prescale_gradients": False,
        "bf16": {"enabled": dtype == "bf16"},
        "fp16": {
            "enabled": dtype == "fp16",
            "fp16_master_weights_and_grads": False,
            "loss_scale": 0,
            "loss_scale_window": 500,
            "hysteresis": 2,
            "min_loss_scale": 1,
            "initial_scale_power": 15,
        },
        "wall_clock_breakdown": False,
        "zero_optimization": {
            "stage": stage,
            "allgather_partitions": True,
            "reduce_scatter": True,
            "allgather_bucket_size": 50000000,
            "reduce_bucket_size": 50000000,
            "overlap_comm": True,
            "contiguous_gradients": True,
            "cpu_offload": False,
        },
    }


def test_reference_ds_config_ingests_losslessly():
    cfg = from_ds_config(_reference_ds_config())
    assert cfg.optimizer.lr == 0.001
    assert cfg.optimizer.betas == (0.8, 0.999)
    assert cfg.optimizer.eps == 1e-8
    assert cfg.optimizer.weight_decay == 3e-7
    assert cfg.optimizer.grad_clip_norm == 1.0
    assert cfg.scheduler.name == "warmup_lr"
    assert cfg.scheduler.warmup_num_steps == 1000
    assert cfg.precision.dtype == "bf16"
    assert cfg.zero.stage == 0
    assert cfg.zero.reduce_bucket_size == 50_000_000
    assert cfg.data.global_batch_size == 96
    assert cfg.log_interval == 2000
    assert cfg.wall_clock_breakdown is False


def test_ds_config_fp16_scaler_fields():
    cfg = from_ds_config(_reference_ds_config(dtype="fp16", stage=2))
    assert cfg.precision.dtype == "fp16"
    assert cfg.precision.initial_scale_power == 15
    assert cfg.precision.loss_scale_window == 500
    assert cfg.precision.hysteresis == 2
    assert cfg.precision.min_loss_scale == 1
    assert cfg.precision.static_loss_scale is None  # loss_scale: 0 → dynamic
    assert cfg.zero.stage == 2


def test_ds_config_static_loss_scale():
    ds = _reference_ds_config(dtype="fp16")
    ds["fp16"]["loss_scale"] = 1024
    cfg = from_ds_config(ds)
    assert cfg.precision.static_loss_scale == 1024.0


def test_ds_config_adamw_maps_to_decoupled_decay():
    ds = _reference_ds_config()
    ds["optimizer"]["type"] = "AdamW"
    cfg = from_ds_config(ds)
    assert cfg.optimizer.name == "adamw"


def test_ds_config_rejects_unknown_keys():
    ds = _reference_ds_config()
    ds["not_a_real_knob"] = True
    with pytest.raises(ValueError, match="not_a_real_knob"):
        from_ds_config(ds)
    ds = _reference_ds_config()
    ds["zero_optimization"]["typo_knob"] = 1
    with pytest.raises(ValueError, match="typo_knob"):
        from_ds_config(ds)


def test_plugin_presets():
    assert TrainConfig.from_plugin("torch_ddp").precision.dtype == "fp32"
    fp16 = TrainConfig.from_plugin("torch_ddp_fp16")
    assert fp16.precision.dtype == "fp16"
    llz = TrainConfig.from_plugin("low_level_zero")
    assert llz.zero.stage == 1
    assert llz.precision.initial_scale_power == 5  # colossal initial_scale=2**5
    gem = TrainConfig.from_plugin("gemini")
    assert gem.zero.stage == 3
    ds = TrainConfig.from_plugin("deepspeed")
    assert ds.optimizer.betas == (0.8, 0.999)
    assert ds.optimizer.grad_clip_norm == 1.0
    with pytest.raises(ValueError):
        TrainConfig.from_plugin("bogus")
    assert set(PLUGINS) == {
        "torch_ddp", "torch_ddp_fp16", "low_level_zero", "gemini", "deepspeed"}


def test_lr_world_scaling_preset():
    # DDP/Colossal linear scaling rule: lr = 1e-3 * world_size.
    from distributed_training_tpu.train.optim import make_schedule

    cfg = TrainConfig.from_plugin("torch_ddp")
    assert cfg.optimizer.scale_lr_by_world
    sched = make_schedule(cfg.optimizer, cfg.scheduler, world_size=8)
    assert float(sched(0)) == pytest.approx(8e-3)


def test_warmup_lr_schedule_shape():
    from distributed_training_tpu.config import OptimizerConfig, SchedulerConfig
    from distributed_training_tpu.train.optim import make_schedule

    sched = make_schedule(
        OptimizerConfig(),
        SchedulerConfig(name="warmup_lr", warmup_min_lr=0.0,
                        warmup_max_lr=1e-3, warmup_num_steps=1000))
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(500)) == pytest.approx(5e-4)
    assert float(sched(1000)) == pytest.approx(1e-3)
    assert float(sched(5000)) == pytest.approx(1e-3)  # constant after warmup


def test_logits_dtype_config_default_matches_clis(monkeypatch):
    """ADVICE r5: LMConfig.logits_dtype defaulted to fp32 while every CLI
    (gpt/jax_tpu/train.py, generate.py, bench.py) defaulted to bf16 — a
    bare LMTrainer(TrainConfig(...)) run silently trained a different head
    dtype than a bare CLI run. Pin config default == CLI default."""
    import importlib.util
    import os
    import sys

    from distributed_training_tpu.config import LMConfig

    root = os.path.join(os.path.dirname(__file__), "..")

    def parser_default(relpath, attr="logits_dtype"):
        spec = importlib.util.spec_from_file_location(
            "cli_under_test_" + os.path.basename(relpath).replace(".", "_"),
            os.path.join(root, relpath))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(sys, "argv", [relpath])
        if hasattr(mod, "build_parser"):
            return getattr(mod.build_parser().parse_args([]), attr)
        return getattr(mod.add_argument(), attr)

    assert LMConfig().logits_dtype == "bf16"
    assert parser_default("gpt/jax_tpu/train.py") == LMConfig().logits_dtype
    assert parser_default("bench.py") == LMConfig().logits_dtype
