"""Data pipeline tests: shard index math, set_epoch shuffling, transforms
(SURVEY.md §4 'data-shard index math')."""

import numpy as np
import pytest

from distributed_training_tpu.data.cifar10 import synthetic_cifar10
from distributed_training_tpu.data.pipeline import ShardedDataLoader
from distributed_training_tpu.data import transforms


def _loader(n=64, gbs=16, pi=0, pc=1, **kw):
    x, y = synthetic_cifar10(n, train=True)
    defaults = dict(global_batch_size=gbs, shuffle=True, drop_last=True,
                    augment="none", train=True, seed=0,
                    process_index=pi, process_count=pc)
    defaults.update(kw)
    return ShardedDataLoader(x, y, **defaults)


def test_shards_partition_global_batch():
    """Across processes, per-process slices tile each global batch exactly."""
    n, gbs, pc = 64, 16, 4
    loaders = [_loader(n, gbs, pi=p, pc=pc) for p in range(pc)]
    for l in loaders:
        l.set_epoch(0)
    batches = [list(l) for l in loaders]
    x, y = synthetic_cifar10(n, train=True)
    seen = []
    for step in range(len(loaders[0])):
        labels = np.concatenate([batches[p][step]["label"] for p in range(pc)])
        assert len(labels) == gbs
        seen.append(labels)
    # With drop_last and n % gbs == 0, every example appears exactly once.
    all_labels = np.concatenate(seen)
    assert len(all_labels) == n


def test_set_epoch_reshuffles_deterministically():
    l = _loader()
    l.set_epoch(0)
    e0a = [b["label"].copy() for b in l]
    l.set_epoch(0)
    e0b = [b["label"].copy() for b in l]
    l.set_epoch(1)
    e1 = [b["label"].copy() for b in l]
    for a, b in zip(e0a, e0b):
        np.testing.assert_array_equal(a, b)  # same epoch → same order
    assert any(
        not np.array_equal(a, b) for a, b in zip(e0a, e1)
    ), "different epoch must reshuffle"


def test_no_shuffle_is_sequential():
    l = _loader(shuffle=False)
    x, y = synthetic_cifar10(64, train=True)
    first = next(iter(l))
    np.testing.assert_array_equal(first["label"], y[:16])


def test_drop_last_true_drops_ragged_batch():
    l = _loader(n=70, gbs=16)
    assert len(l) == 4
    assert sum(1 for _ in l) == 4


def test_drop_last_false_pads_with_mask():
    l = _loader(n=70, gbs=16, drop_last=False, shuffle=False, train=False)
    batches = list(l)
    assert len(batches) == 5
    last = batches[-1]
    assert last["image"].shape[0] == 16
    assert last["mask"].sum() == 70 - 64
    assert all(b["mask"].sum() == 16 for b in batches[:-1])


def test_global_batch_must_divide_by_process_count():
    with pytest.raises(ValueError):
        _loader(gbs=10, pc=4)


def test_pad_crop_flip_shapes_and_range():
    rng = np.random.RandomState(0)
    x = np.random.RandomState(1).randint(0, 256, (8, 32, 32, 3), dtype=np.uint8)
    out = transforms.pad_crop_flip(x, rng)
    assert out.shape == x.shape
    assert out.dtype == np.uint8


def test_pad_crop_identity_possible():
    """With pad=0 and no flip chance, crop must be the identity."""
    class FixedRng:
        def randint(self, lo, hi, size=None):
            return np.zeros(size, dtype=np.int64)
        def rand(self, n):
            return np.ones(n)  # >= 0.5 → no flip... (flips where < 0.5)
    x = np.arange(8 * 32 * 32 * 3, dtype=np.uint8).reshape(8, 32, 32, 3) % 255
    out = transforms.pad_crop_flip(x, FixedRng(), pad=0)
    np.testing.assert_array_equal(out, x)


def test_normalize_half_range():
    x = np.array([[[[0, 128, 255]]]], dtype=np.uint8)
    out = transforms.normalize_half(transforms.to_float(x))
    assert out.min() >= -1.0 and out.max() <= 1.0
    np.testing.assert_allclose(out[0, 0, 0, 0], -1.0)
    np.testing.assert_allclose(out[0, 0, 0, 2], 1.0)


def test_synthetic_cifar_learnable_structure():
    x, y = synthetic_cifar10(512, train=True)
    # Class-conditional means must be ordered — the property making the
    # synthetic set learnable for convergence smoke tests.
    means = [x[y == c].mean() for c in range(10) if (y == c).any()]
    assert all(b > a for a, b in zip(means, means[1:]))
