"""Pre-decoded cache loader (data/decoded_cache.py): the DALI-cache
analogue — decode once into a uint8 memmap, train at augment speed.

Round-2 host-pipeline work (VERDICT r1 #3): a single measured core JPEG-
decodes ~150 img/s at 224 px while the chip consumes ~2400; the cache moves
the decode out of the epoch loop (measured ~3400 img/s/core post-cache).
"""

import os

import numpy as np
import pytest

from distributed_training_tpu.data.decoded_cache import (
    DecodedCacheLoader,
    build_decoded_cache,
    _base_size,
)


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    """A tiny on-disk JPEG tree + its built cache."""
    pil = pytest.importorskip("PIL.Image")
    root = tmp_path_factory.mktemp("decoded")
    rng = np.random.RandomState(0)
    paths, labels = [], []
    for c in range(2):
        d = root / f"class{c}"
        d.mkdir()
        for i in range(8):
            p = str(d / f"im{i}.jpg")
            pil.fromarray(
                rng.randint(0, 255, (40 + 8 * c, 48, 3), dtype=np.uint8)
            ).save(p, quality=95)
            paths.append(p)
            labels.append(c)
    cache = build_decoded_cache(
        paths, np.asarray(labels, np.int32), str(root / "cache"),
        image_size=24, num_workers=2)
    return root, paths, np.asarray(labels, np.int32), cache


def test_cache_build_idempotent(tree):
    root, paths, labels, cache = tree
    mtime = os.path.getmtime(cache + ".npy")
    again = build_decoded_cache(paths, labels, cache, image_size=24)
    assert again == cache
    assert os.path.getmtime(cache + ".npy") == mtime  # not rebuilt


def test_cache_rebuilds_when_file_replaced_in_place(tmp_path):
    """Re-encoding a source image under the SAME filename (a regenerated /
    re-downloaded dataset) must invalidate the cache — the fingerprint
    includes per-file byte size, not just (basename, label)."""
    pil = pytest.importorskip("PIL.Image")
    rng = np.random.RandomState(7)
    paths = []
    for i in range(3):
        p = str(tmp_path / f"im{i}.jpg")
        pil.fromarray(rng.randint(0, 255, (40, 48, 3), dtype=np.uint8)).save(
            p, quality=95)
        paths.append(p)
    labels = np.zeros(3, np.int32)
    cache = str(tmp_path / "cache")
    build_decoded_cache(paths, labels, cache, image_size=24, num_workers=2)
    mtime = os.path.getmtime(cache + ".npy")
    # Rewrite one file in place: same name, different pixels/size.
    pil.fromarray(rng.randint(0, 255, (64, 64, 3), dtype=np.uint8)).save(
        paths[0], quality=60)
    build_decoded_cache(paths, labels, cache, image_size=24, num_workers=2)
    assert os.path.getmtime(cache + ".npy") != mtime  # rebuilt


def test_cache_layout(tree):
    _, paths, labels, cache = tree
    arr = np.load(cache + ".npy", mmap_mode="r")
    base = _base_size(24)
    assert arr.shape == (len(paths), base, base, 3)
    assert arr.dtype == np.uint8
    np.testing.assert_array_equal(np.load(cache + ".labels.npy"), labels)


def test_loader_yields_uint8_crops(tree):
    _, paths, labels, cache = tree
    loader = DecodedCacheLoader(
        cache, global_batch_size=8, augment="pad_crop_flip", train=True,
        process_index=0, process_count=1)
    loader.set_epoch(0)
    batches = list(loader)
    assert len(batches) == 2
    for b in batches:
        assert b["image"].dtype == np.uint8
        assert b["image"].shape == (8, 24, 24, 3)
        assert b["label"].dtype == np.int32
    # Deterministic per epoch; reshuffled across epochs.
    loader.set_epoch(0)
    again = list(loader)
    np.testing.assert_array_equal(batches[0]["image"], again[0]["image"])
    loader.set_epoch(1)
    other = list(loader)
    assert not np.array_equal(batches[0]["label"], other[0]["label"]) or \
        not np.array_equal(batches[0]["image"], other[0]["image"])


def test_eval_center_crop_matches_native_and_python(tree):
    """Native fused gather+crop must equal the pure-python fallback."""
    from distributed_training_tpu.ops.native import native

    _, paths, labels, cache = tree
    loader = DecodedCacheLoader(
        cache, global_batch_size=8, augment="none", train=False,
        shuffle=False, process_index=0, process_count=1)
    loader.set_epoch(0)
    native_batches = [b["image"].copy() for b in loader]
    if native.available():
        # Force the python path and compare.
        import distributed_training_tpu.ops.native.native as nat
        orig = nat.available
        nat.available = lambda: False
        try:
            loader.set_epoch(0)
            py_batches = [b["image"].copy() for b in loader]
        finally:
            nat.available = orig
        for a, b in zip(native_batches, py_batches):
            np.testing.assert_array_equal(a, b)


def test_iter_from_skips_at_index_level(tree):
    _, paths, labels, cache = tree
    loader = DecodedCacheLoader(
        cache, global_batch_size=4, augment="none", train=False,
        shuffle=True, process_index=0, process_count=1)
    loader.set_epoch(3)
    full = [b["label"].tolist() for b in loader]
    skipped = [b["label"].tolist() for b in loader.iter_from(2)]
    assert skipped == full[2:]


def test_image_size_larger_than_base_rejected(tree):
    _, paths, labels, cache = tree
    with pytest.raises(ValueError, match="rebuild the cache"):
        DecodedCacheLoader(cache, global_batch_size=4, image_size=64)


def test_uint8_batch_trains_end_to_end(tree, mesh):
    """A uint8 batch drives the jitted train step (device-side /255) and
    produces the same loss as the equivalent pre-normalized f32 batch."""
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_training_tpu.config import PrecisionConfig
    from distributed_training_tpu.models import get_model
    from distributed_training_tpu.train.precision import LossScaleState
    from distributed_training_tpu.train.step import make_train_step
    from distributed_training_tpu.train.train_state import init_train_state

    model = get_model("resnet_micro", num_classes=2, stem="cifar")
    state = init_train_state(
        model, jax.random.PRNGKey(0), (1, 24, 24, 3), optax.sgd(0.1),
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    u8 = np.random.RandomState(0).randint(
        0, 255, (8, 24, 24, 3), dtype=np.uint8)
    labels = np.arange(8, dtype=np.int32) % 2

    step_u8 = make_train_step(mesh, donate=False)
    _, m_u8 = step_u8(state, {"image": u8, "label": labels},
                      jax.random.PRNGKey(1))

    step_f32 = make_train_step(mesh, donate=False)
    f32 = u8.astype(np.float32) / 255.0
    _, m_f32 = step_f32(state, {"image": f32, "label": labels},
                        jax.random.PRNGKey(1))
    np.testing.assert_allclose(
        float(m_u8["loss"]), float(m_f32["loss"]), rtol=1e-6)

    # normalize_only affine parity: (2/255, -1) == Normalize(.5,.5) ∘ ToTensor
    step_norm = make_train_step(mesh, donate=False,
                                input_affine=(2.0 / 255.0, -1.0))
    _, m_norm_u8 = step_norm(state, {"image": u8, "label": labels},
                             jax.random.PRNGKey(1))
    normed = (f32 - 0.5) / 0.5
    _, m_norm_f32 = step_f32(state, {"image": normed, "label": labels},
                             jax.random.PRNGKey(1))
    np.testing.assert_allclose(
        float(m_norm_u8["loss"]), float(m_norm_f32["loss"]), rtol=1e-5)


def test_multi_worker_stream_identical(tree):
    """num_workers parallelizes ASSEMBLY only: the batch stream (order,
    crops, flips, padding) is byte-identical to the inline path — all
    randomness is drawn sequentially in the producer."""
    _, _, _, cache = tree

    def batches(workers):
        ld = DecodedCacheLoader(
            cache, global_batch_size=6, train=True, drop_last=False,
            augment="pad_crop_flip", process_index=0, process_count=1,
            num_workers=workers)
        ld.set_epoch(3)
        return list(ld)

    base = batches(0)
    multi = batches(3)
    assert len(base) == len(multi) > 0
    for a, b in zip(base, multi):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])
