"""The DDP-equivalence property (SURVEY.md §4).

A data-parallel step over N devices must equal a single-device step on the
batch-concatenated data: same gradients (psum/pmean of shard grads == grads
of the full batch, since CE-mean losses average), same params after update.
This pins down the collective math of both the GSPMD and the explicit
shard_map paths against an independently-computed reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_tpu.models import get_model
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.step import (
    cross_entropy_loss,
    make_shard_map_train_step,
    make_train_step,
)
from distributed_training_tpu.train.train_state import TrainState, init_train_state
from distributed_training_tpu.config import PrecisionConfig


def _make_state(axis_name=None, lr=1e-2):
    # SGD+momentum: the update is LINEAR in the gradients, so the sharded
    # and unsharded paths agree to reduction-order noise (~1e-6). Adam's
    # step-1 update is g/|g|-shaped and amplifies that noise to ~lr; the
    # Adam path is covered separately with an appropriate tolerance.
    model = get_model("resnet_micro", num_classes=10, axis_name=axis_name,
                      stem="cifar")
    tx = optax.sgd(lr, momentum=0.9)
    state = init_train_state(
        model, jax.random.PRNGKey(0), (8, 8, 8, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    return state


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "image": rng.rand(n, 8, 8, 3).astype(np.float32),
        "label": rng.randint(0, 10, n).astype(np.int32),
    }


def _single_device_reference(state, batch, rng):
    """Independent single-device step: plain jax.grad + tx.update."""

    def loss_fn(params):
        logits, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            batch["image"], train=True, mutable=["batch_stats"],
            rngs={"dropout": rng})
        return cross_entropy_loss(logits, batch["label"]), mutated

    grads, _ = jax.grad(loss_fn, has_aux=True)(state.params)
    updates, _ = state.tx.update(grads, state.opt_state, state.params)
    return optax.apply_updates(state.params, updates), grads


def _maxdiff(a, b):
    return max(
        float(jnp.max(jnp.abs(x - y)))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_gspmd_dp_step_matches_single_device(mesh):
    state = _make_state()
    batch = _batch()
    rng = jax.random.PRNGKey(42)
    ref_params, _ = _single_device_reference(state, batch, rng)

    step = make_train_step(mesh, zero_stage=0, donate=False)
    new_state, metrics = step(state, batch, rng)

    assert _maxdiff(new_state.params, ref_params) < 1e-5
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1


def test_shard_map_dp_step_matches_single_device(mesh):
    # SyncBN axis must match the pmean axis for exact equivalence.
    state = _make_state(axis_name="data")
    batch = _batch()
    rng = jax.random.PRNGKey(42)

    ref_state = _make_state()  # same init (seed-deterministic), no axis_name
    ref_params, _ = _single_device_reference(ref_state, batch, rng)

    with mesh:
        step = make_shard_map_train_step(mesh, donate=False)
        new_state, metrics = step(state, batch, rng)

    assert _maxdiff(new_state.params, ref_params) < 1e-5
    assert np.isfinite(float(metrics["loss"]))


def test_sync_batchnorm_stats_are_global(mesh):
    """BN running stats after a sharded step == stats of the full batch.

    This is the SyncBatchNorm property (SURVEY.md §7 hard parts): shard-local
    BN would produce different (and wrong) running means.
    """
    state = _make_state(axis_name="data")
    batch = _batch(n=16, seed=3)
    rng = jax.random.PRNGKey(0)

    ref_state = _make_state()
    _, mutated = ref_state.apply_fn(
        {"params": ref_state.params, "batch_stats": ref_state.batch_stats},
        batch["image"], train=True, mutable=["batch_stats"],
        rngs={"dropout": rng})
    ref_stats = mutated["batch_stats"]

    with mesh:
        step = make_shard_map_train_step(mesh, donate=False)
        new_state, _ = step(state, batch, rng)

    assert _maxdiff(new_state.batch_stats, ref_stats) < 1e-5


def test_adam_dp_step_matches_single_device(mesh):
    """Adam path: grads agree to ~1e-6 (verified separately), but Adam's
    first-step update is ±lr·(1-β1)/√(1-β2)-shaped, so sign flips on
    near-zero grads move params by O(lr). Tolerance reflects that bound,
    not a correctness gap: 4e-3 << 2·lr = 2e-2."""
    model = get_model("resnet_micro", num_classes=10, stem="cifar")
    tx = optax.adam(1e-2)
    state = init_train_state(
        model, jax.random.PRNGKey(0), (8, 8, 8, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    batch = _batch()
    rng = jax.random.PRNGKey(42)
    ref_params, _ = _single_device_reference(state, batch, rng)
    step = make_train_step(mesh, zero_stage=0, donate=False)
    new_state, _ = step(state, batch, rng)
    assert _maxdiff(new_state.params, ref_params) < 2e-2


def test_local_bn_differs_from_sync_bn_in_variance(mesh):
    """sync_batchnorm=False semantics: per-shard statistics.

    Shard means average to the global mean (equal shard sizes), so the
    running-mean EMAs agree; the running-*variance* EMAs must differ
    (E[shard var] < global var when shard means differ) — that gap IS the
    local-vs-sync distinction.
    """
    batch = _batch(n=16, seed=11)
    rng = jax.random.PRNGKey(0)

    with mesh:
        step = make_shard_map_train_step(mesh, donate=False)
        local_state, _ = step(_make_state(axis_name=None), batch, rng)
        sync_state, _ = step(_make_state(axis_name="data"), batch, rng)

    def stem(s, kind):
        # Only the STEM BN sees identical inputs under both modes; deeper
        # layers' inputs already differ (they are downstream of the first
        # normalization), so the clean local-vs-sync contrast lives here.
        [v] = [np.asarray(v) for k, v in
               jax.tree_util.tree_flatten_with_path(s.batch_stats)[0]
               if "bn_init" in jax.tree_util.keystr(k)
               and kind in jax.tree_util.keystr(k)]
        return v

    # Shard means average to the global mean → running means agree...
    np.testing.assert_allclose(
        stem(local_state, "mean"), stem(sync_state, "mean"), atol=1e-5)
    # ...but E[shard var] < global var: the variance EMAs must differ.
    var_gap = np.abs(
        stem(local_state, "var") - stem(sync_state, "var")).max()
    assert var_gap > 1e-6, "local BN must produce different variance stats"


def test_trainer_local_bn_path(tmp_path):
    from distributed_training_tpu import TrainConfig, Trainer
    from distributed_training_tpu.config import CheckpointConfig, DataConfig

    cfg = TrainConfig.from_plugin("torch_ddp").replace(
        model="resnet_micro", num_epochs=1, log_interval=4, sync_batchnorm=False,
        data=DataConfig(dataset="synthetic_cifar", batch_size=8,
                        max_steps_per_epoch=6),
        checkpoint=CheckpointConfig(directory=str(tmp_path), interval=0))
    trainer = Trainer(cfg)
    loader, _ = trainer.make_loaders()
    metrics = trainer.train_epoch(0, loader)
    assert metrics["loss"] < 2.3
    assert metrics["grads_finite"] == 1.0


def test_gspmd_and_shard_map_paths_agree(mesh):
    state_a = _make_state()
    state_b = _make_state(axis_name="data")
    batch = _batch(seed=7)
    rng = jax.random.PRNGKey(1)

    step_a = make_train_step(mesh, zero_stage=0, donate=False)
    new_a, _ = step_a(state_a, batch, rng)
    with mesh:
        step_b = make_shard_map_train_step(mesh, donate=False)
        new_b, _ = step_b(state_b, batch, rng)

    assert _maxdiff(new_a.params, new_b.params) < 1e-5
