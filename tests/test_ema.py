"""Parameter-EMA tests: the average lives in opt_state (checkpointed,
ZeRO-shardable, overflow-skip-covered for free)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import (
    DataConfig,
    OptimizerConfig,
    PrecisionConfig,
    TrainConfig,
)
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state, state_shardings
from distributed_training_tpu.train.optim import (
    EmaState,
    ema_batch_stats,
    ema_params,
    make_optimizer,
    with_ema,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.step import make_train_step
from distributed_training_tpu.train.train_state import init_train_state


class TestWithEma:
    def test_tracks_the_recurrence(self):
        tx = with_ema(optax.sgd(1.0), decay=0.5)
        params = {"w": jnp.asarray(0.0)}
        state = tx.init(params)
        np.testing.assert_allclose(float(ema_params(state)["w"]), 0.0)
        # grad 1 -> p1 = -1; ema = .5*0 + .5*(-1) = -.5
        u, state = tx.update({"w": jnp.asarray(1.0)}, state, params)
        params = optax.apply_updates(params, u)
        np.testing.assert_allclose(float(ema_params(state)["w"]), -0.5)
        # p2 = -2; ema = .5*(-.5) + .5*(-2) = -1.25
        u, state = tx.update({"w": jnp.asarray(1.0)}, state, params)
        params = optax.apply_updates(params, u)
        np.testing.assert_allclose(float(ema_params(state)["w"]), -1.25)

    def test_inner_updates_unchanged(self):
        """Wrapping must not alter what the inner optimizer produces."""
        g = {"w": jnp.asarray(0.7)}
        p = {"w": jnp.asarray(1.0)}
        plain = optax.adam(1e-2)
        wrapped = with_ema(optax.adam(1e-2), 0.99)
        u1, _ = plain.update(g, plain.init(p), p)
        u2, _ = wrapped.update(g, wrapped.init(p), p)
        np.testing.assert_allclose(
            float(u1["w"]), float(u2["w"]), rtol=1e-7)

    def test_ema_params_raises_without_ema(self):
        tx = optax.adam(1e-3)
        with pytest.raises(ValueError, match="no EMA"):
            ema_params(tx.init({"w": jnp.zeros(())}))

    def test_factory_wires_ema(self):
        tx = make_optimizer(OptimizerConfig(name="adam", ema_decay=0.9))
        state = tx.init({"w": jnp.ones((2,))})
        assert isinstance(state, EmaState)


class TestTrainStepIntegration:
    def _fit_state(self, mesh, ema_decay, dtype="fp32", zero_stage=0):
        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        tx = make_optimizer(OptimizerConfig(name="adam", ema_decay=ema_decay))
        state = init_train_state(
            model, jax.random.PRNGKey(0), (8, 8, 8, 3), tx,
            loss_scale=LossScaleState.create(PrecisionConfig(dtype=dtype)))
        state = place_state(state, state_shardings(state, mesh, zero_stage))
        step = make_train_step(mesh, donate=False, zero_stage=zero_stage)
        batch = {
            "image": jnp.asarray(
                np.random.RandomState(0).rand(8, 8, 8, 3), jnp.float32),
            "label": jnp.asarray(
                np.random.RandomState(0).randint(0, 10, 8), jnp.int32),
        }
        return step(state, batch, jax.random.PRNGKey(1))

    def test_step_advances_ema_toward_params(self, mesh):
        new_state, m = self._fit_state(mesh, ema_decay=0.5)
        assert np.isfinite(float(m["loss"]))
        ema = jax.device_get(ema_params(new_state.opt_state))
        params = jax.device_get(new_state.params)
        # After one step with decay .5, ema = (init + new)/2 — close to but
        # not equal to the live params.
        diffs = jax.tree.leaves(jax.tree.map(
            lambda e, p: float(np.abs(e - p).max()), ema, params))
        assert max(diffs) > 0

    def test_composes_with_zero_sharding(self, mesh):
        new_state, m = self._fit_state(mesh, ema_decay=0.9, zero_stage=1)
        assert np.isfinite(float(m["loss"]))
        assert isinstance(new_state.opt_state, EmaState)

    def test_trainer_eval_uses_ema(self, mesh):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="resnet_micro", num_epochs=1, eval_every=1, log_interval=4,
            optimizer=OptimizerConfig(name="adam", lr=0.5, ema_decay=0.999),
            data=DataConfig(dataset="synthetic_cifar", batch_size=4,
                            max_steps_per_epoch=2, prefetch=0),
        )
        tr = Trainer(cfg, mesh=mesh)
        acc_ema = tr.fit()["final_acc"]
        # With decay .999 and lr .5, the EMA stays ~at init while live
        # params moved: evaluating without EMA must differ.
        tr.cfg = cfg.replace(eval_with_ema=False)
        _, eval_loader = tr.make_loaders()
        acc_live = tr.evaluate(eval_loader)
        assert acc_ema is not None and acc_live is not None
        # Both are valid accuracies; the states they evaluate differ.
        ema = jax.device_get(ema_params(tr.state.opt_state))
        live = jax.device_get(tr.state.params)
        diff = max(jax.tree.leaves(jax.tree.map(
            lambda a, b: float(np.abs(a - b).max()), ema, live)))
        assert diff > 1e-4

    def test_bn_stats_averaged_alongside_params(self, mesh):
        """EMA eval must see averaged BN statistics, not live ones: the
        ema_batch_stats tree is seeded at create and advanced per step."""
        new_state, _ = self._fit_state(mesh, ema_decay=0.5)
        ema_bs = jax.device_get(ema_batch_stats(new_state.opt_state))
        live_bs = jax.device_get(new_state.batch_stats)
        assert jax.tree.leaves(ema_bs), "ema_batch_stats not seeded"
        # One step at decay .5: ema = (init + new)/2 — between init and live.
        diffs = jax.tree.leaves(jax.tree.map(
            lambda e, b: float(np.abs(e - b).max()), ema_bs, live_bs))
        assert max(diffs) > 0

    def test_eval_state_pairs_ema_params_with_ema_stats(self, mesh):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="resnet_micro", num_epochs=1, eval_every=0, log_interval=4,
            optimizer=OptimizerConfig(name="adam", lr=0.5, ema_decay=0.9),
            data=DataConfig(dataset="synthetic_cifar", batch_size=4,
                            max_steps_per_epoch=2, prefetch=0),
        )
        tr = Trainer(cfg, mesh=mesh)
        train_loader, _ = tr.make_loaders()
        tr.train_epoch(0, train_loader)
        es = tr._eval_state()
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(es.params)[0]),
            np.asarray(jax.tree.leaves(ema_params(tr.state.opt_state))[0]))
        np.testing.assert_array_equal(
            np.asarray(jax.tree.leaves(es.batch_stats)[0]),
            np.asarray(jax.tree.leaves(
                ema_batch_stats(tr.state.opt_state))[0]))

    def test_local_bn_shard_map_step_keeps_ema_stats_replicated(self, mesh):
        """sync_batchnorm=False + EMA: per-shard BN stats feed the EMA; the
        step must pmean the EMA tree so its output is truly replicated."""
        from distributed_training_tpu.train.step import (
            make_shard_map_train_step,
        )

        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        tx = make_optimizer(OptimizerConfig(name="adam", ema_decay=0.5))
        state = init_train_state(
            model, jax.random.PRNGKey(0), (8, 8, 8, 3), tx,
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = place_state(state, state_shardings(state, mesh, 0))
        step = make_shard_map_train_step(mesh, donate=False)
        # Per-shard-distinct images so local BN stats genuinely diverge.
        batch = {
            "image": jnp.asarray(
                np.random.RandomState(0).rand(8, 8, 8, 3) *
                np.arange(1, 9)[:, None, None, None], jnp.float32),
            "label": jnp.asarray(np.arange(8) % 10, jnp.int32),
        }
        new_state, m = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
        ema_bs = ema_batch_stats(new_state.opt_state)
        # Fully addressable + consistent across devices: fetching succeeds
        # and equals the mean of what each shard would hold.
        fetched = jax.device_get(ema_bs)
        assert all(np.isfinite(x).all() for x in jax.tree.leaves(fetched))

    def test_fp16_overflow_skip_covers_ema(self, mesh):
        """A rejected step must leave the EMA untouched."""
        from distributed_training_tpu.train.precision import LossScaleState

        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        tx = make_optimizer(OptimizerConfig(name="adam", ema_decay=0.5))
        state = init_train_state(
            model, jax.random.PRNGKey(0), (8, 8, 8, 3), tx,
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp16")))
        state = place_state(state, state_shardings(state, mesh, 0))
        step = make_train_step(mesh, donate=False)
        bad_batch = {
            "image": jnp.full((8, 8, 8, 3), jnp.inf, jnp.float32),
            "label": jnp.zeros((8,), jnp.int32),
        }
        ema_before = jax.device_get(ema_params(state.opt_state))
        new_state, m = step(state, bad_batch, jax.random.PRNGKey(1))
        assert float(m["grads_finite"]) == 0.0
        ema_after = jax.device_get(ema_params(new_state.opt_state))
        jax.tree.map(np.testing.assert_array_equal, ema_before, ema_after)
