"""Live telemetry plane tests (observability/exporter.py).

Load-bearing properties:

1. **Scrape-vs-dump parity** (the one-implementation satellite): a live
   ``/metrics`` scrape and ``flight_report.py --prometheus`` over a dump
   of the SAME run agree family-for-family — byte-for-byte, in fact,
   since both render through ``observability/prometheus.py``.
2. **Bitwise telemetry equality** (acceptance): the TTFT/TPOT histogram
   bucket counts a live scrape reports equal the end-of-run
   ``ServeTelemetry`` state exactly.
3. **Liveness semantics**: /healthz tracks the engine's
   serving→draining→drained phase and the trainers' clock phase;
   a port already in use fails construction loudly; close() releases
   the port; a broken snapshot provider returns 500 without killing the
   server.
4. **Live-run integration**: both a real 1-epoch LM train and an
   in-process serving run are scrapeable while alive, through the same
   ``ObservabilityConfig.metrics_port`` / ``Engine.flight_snapshot``
   surfaces the CLIs use.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import (
    CheckpointConfig,
    DataConfig,
    LMConfig,
    ObservabilityConfig,
    ServeConfig,
    TrainConfig,
)
from distributed_training_tpu.models import get_model
from distributed_training_tpu.observability.exporter import MetricsExporter
from distributed_training_tpu.observability.flight_recorder import (
    FlightRecorder,
)
from distributed_training_tpu.observability.prometheus import (
    families,
    prometheus_text,
    sample_value,
)
from distributed_training_tpu.serving import Engine


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return (resp.status, resp.headers.get("Content-Type", ""),
                resp.read().decode("utf-8"))


CANNED = {
    "format_version": 1,
    "reason": "scrape",
    "steps_recorded_total": 7,
    "step_time_stats": {"step_time_p50_ms": 3.5, "step_time_p95_ms": 9.0,
                        "step_time_max_ms": 12.0},
    "histograms": {"step_time_ms": {"bounds": [1.0, 10.0],
                                    "counts": [2, 3, 1],
                                    "count": 6, "sum": 31.0}},
}


class TestExporterUnit:
    def test_all_three_endpoints(self):
        exp = MetricsExporter(lambda: dict(CANNED), port=0,
                              phase_provider=lambda: "train").start()
        try:
            code, ctype, text = _get(exp.url("/metrics"))
            assert code == 200 and ctype.startswith("text/plain")
            fams = families(text)
            assert fams["flight_steps_recorded_total"] == "gauge"
            assert fams["flight_step_time_ms"] == "histogram"
            # Cumulative-le rendering of the canned counts [2, 3, 1].
            assert sample_value(text, 'flight_step_time_ms_bucket'
                                      '{le="1"}') == 2
            assert sample_value(text, 'flight_step_time_ms_bucket'
                                      '{le="+Inf"}') == 6
            assert sample_value(text, "flight_step_time_ms_count") == 6

            code, ctype, body = _get(exp.url("/healthz"))
            assert code == 200 and ctype.startswith("application/json")
            health = json.loads(body)
            assert health["status"] == "ok"
            assert health["phase"] == "train"
            assert health["scrapes"] == 1  # the /metrics GET above
            assert health["uptime_seconds"] >= 0

            code, ctype, body = _get(exp.url("/vars"))
            assert code == 200 and ctype.startswith("application/json")
            assert json.loads(body)["steps_recorded_total"] == 7
        finally:
            exp.close()

    def test_unknown_path_404(self):
        exp = MetricsExporter(lambda: dict(CANNED), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(exp.url("/nope"))
            assert ei.value.code == 404
            body = json.loads(ei.value.read().decode())
            assert "/metrics" in body["endpoints"]
        finally:
            exp.close()

    def test_port_in_use_raises_at_construction(self):
        first = MetricsExporter(lambda: {}, port=0).start()
        try:
            with pytest.raises(OSError):
                MetricsExporter(lambda: {}, port=first.port)
        finally:
            first.close()

    def test_close_releases_port_and_stops_serving(self):
        exp = MetricsExporter(lambda: dict(CANNED), port=0).start()
        port = exp.port
        assert _get(exp.url("/healthz"))[0] == 200
        exp.close()
        exp.close()  # idempotent
        with pytest.raises(OSError):
            _get(f"http://127.0.0.1:{port}/healthz", timeout=1.0)
        # The port is actually free again: a new exporter can bind it.
        again = MetricsExporter(lambda: {}, port=port).start()
        try:
            assert _get(again.url("/healthz"))[0] == 200
        finally:
            again.close()

    def test_broken_provider_returns_500_server_survives(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("torn snapshot")
            return dict(CANNED)

        exp = MetricsExporter(flaky, port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(exp.url("/metrics"))
            assert ei.value.code == 500
            assert "torn snapshot" in ei.value.read().decode()
            assert _get(exp.url("/metrics"))[0] == 200  # still alive
        finally:
            exp.close()

    def test_train_observability_recorder_off_minimal_snapshot(self):
        """metrics_port with the flight recorder disabled still serves:
        the minimal snapshot keeps /metrics and /vars parseable."""
        from distributed_training_tpu.observability.hooks import (
            TrainObservability,
        )

        obs = TrainObservability(ObservabilityConfig(
            flight_recorder=False, metrics_port=0,
            straggler_attribution=False))
        try:
            assert obs.exporter is not None
            code, _, text = _get(obs.exporter.url("/metrics"))
            assert code == 200
            assert "flight_steps_recorded_total 0" in text
            json.loads(_get(obs.exporter.url("/vars"))[2])  # strict JSON
        finally:
            obs.close()


# -- serving integration ------------------------------------------------------

VOCAB = 32
N_NEW = 5
MIXED_LENS = (2, 7, 13, 5, 9)  # mixed-length workload (acceptance)


@pytest.fixture(scope="module")
def served():
    """One engine run over a mixed-length workload with the exporter
    attached, kept ALIVE for the scrape tests (drained by the last
    test in TestServingScrape, closed at teardown)."""
    model = get_model("transformer_lm", num_classes=VOCAB, num_layers=1,
                      num_heads=2, hidden_dim=32, max_len=48)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    eng = Engine(model, params, ServeConfig(
        max_batch=2, max_new_tokens=N_NEW, prefill_bucket=4,
        flush_every=2))
    exp = MetricsExporter(eng.flight_snapshot, port=0,
                          phase_provider=lambda: eng.phase).start()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, VOCAB, size=n).astype(np.int32)
               for n in MIXED_LENS]
    for p in prompts:
        eng.submit(p)
    done = eng.run()
    assert len(done) == len(prompts)
    yield eng, exp
    exp.close()


class TestServingScrape:
    def test_live_scrape_ttft_tpot_bitwise_equals_telemetry(self, served):
        """Acceptance: a live /metrics scrape and the end-of-run
        telemetry report IDENTICAL TTFT/TPOT histogram bucket counts
        for the completed requests."""
        eng, exp = served
        _, _, text = _get(exp.url("/metrics"))
        for name, hist in (("serving_ttft_ms", eng.telemetry.ttft_hist),
                           ("serving_tpot_ms", eng.telemetry.tpot_hist)):
            cum = hist.cumulative()
            bounds = [f"{b:g}" for b in hist.bounds] + ["+Inf"]
            for le, want in zip(bounds, cum):
                got = sample_value(text, f'{name}_bucket{{le="{le}"}}')
                assert got == want, (name, le, got, want)
            assert sample_value(text, f"{name}_count") == hist.total
        # The SLA-line percentiles and the scraped gauges agree too
        # (same %g rendering of the same float).
        stats = eng.stats()
        for key in ("ttft_hist_p50_ms", "ttft_hist_p95_ms",
                    "ttft_hist_p99_ms", "tpot_hist_p99_ms"):
            assert sample_value(text, f"serving_{key}") == float(
                f"{stats[key]:g}")

    def test_scrape_does_not_mutate_telemetry(self, served):
        """A scrape observes; it must not add flush entries or touch
        counters (dump_flight does flush — flight_snapshot must not)."""
        eng, exp = served
        before = len(eng.telemetry.recorder.flushes)
        finished = eng.telemetry.requests_finished
        _get(exp.url("/metrics"))
        _get(exp.url("/vars"))
        assert len(eng.telemetry.recorder.flushes) == before
        assert eng.telemetry.requests_finished == finished

    def test_golden_parity_live_scrape_vs_flight_report(self, served,
                                                        tmp_path):
        """Satellite: one exposition implementation — the live scrape
        and flight_report.py --prometheus over a dump of the same run
        agree family-for-family (byte-identical here: both render via
        observability/prometheus.py and the engine is quiescent)."""
        from conftest import load_cli_module

        eng, exp = served
        _, _, scrape_text = _get(exp.url("/metrics"))
        path = str(tmp_path / "serve_flight.json")
        eng.dump_flight(path)
        report = load_cli_module("tools/flight_report.py")
        report_text = "\n".join(
            report.prometheus_lines(FlightRecorder.load(path))) + "\n"
        assert families(scrape_text) == families(report_text)
        assert scrape_text == report_text
        # And the same text the module-level helper would produce.
        assert scrape_text == prometheus_text(eng.flight_snapshot())

    def test_vars_is_strict_json_with_serving_section(self, served):
        eng, exp = served
        snap = json.loads(_get(exp.url("/vars"))[2])
        srv = snap["serving"]
        assert srv["requests_finished"] == len(MIXED_LENS)
        # The fixed SLA histograms, plus one ledger_<cause>_ms family
        # per latency-ledger cause that actually appeared in this run
        # (serving/ledger.py; a clean serve shows the three lifecycle
        # causes and nothing else).
        assert set(srv["histograms"]) == {
            "ttft_ms", "tpot_ms", "queue_wait_ms", "prefill_ms",
            "ledger_queue_wait_ms", "ledger_prefill_ms",
            "ledger_decode_ms"}
        assert srv["kv_reserved_vs_written"] > 1.0
        assert srv["ledger_conservation_violations"] == 0
        assert srv["ledger_requests"] == len(MIXED_LENS)

    def test_drained_engine_phase(self, served):
        """Engine-drained behavior: /healthz keeps answering 200 and
        names the phase, so an LB can distinguish alive-but-drained
        from dead. (Runs last: drain closes admission for good.)"""
        eng, exp = served
        health = json.loads(_get(exp.url("/healthz"))[2])
        assert health["phase"] == "idle"
        eng.drain()
        health = json.loads(_get(exp.url("/healthz"))[2])
        assert health["status"] == "ok"
        assert health["phase"] == "drained"


# -- trainer integration ------------------------------------------------------

class TestTrainerLiveScrape:
    def test_scrape_during_live_1_epoch_train(self, mesh, tmp_path):
        """A real 1-epoch LM train with metrics_port: the endpoint
        answers DURING fit() (scraper thread) and is closed by
        obs.close() afterwards."""
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm", num_epochs=1, log_interval=4,
            eval_every=0,
            data=DataConfig(batch_size=2, max_steps_per_epoch=40,
                            prefetch=0),
            lm=LMConfig(seq_len=16, vocab_size=32, num_layers=1,
                        num_heads=2, hidden_dim=32, max_len=32,
                        train_sequences=128, eval_sequences=16),
            checkpoint=CheckpointConfig(directory=str(tmp_path / "ckpt"),
                                        interval=0),
            observability=ObservabilityConfig(metrics_port=0),
        )
        trainer = LMTrainer(cfg, mesh=mesh)
        exp = trainer.obs.exporter
        assert exp is not None, "metrics_port should attach an exporter"
        port = exp.port

        got: dict = {}
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    _, _, text = _get(exp.url("/metrics"), timeout=2.0)
                    health = json.loads(
                        _get(exp.url("/healthz"), timeout=2.0)[2])
                except Exception:
                    time.sleep(0.005)
                    continue
                got["metrics"], got["health"] = text, health
                return

        th = threading.Thread(target=scraper, daemon=True)
        th.start()
        try:
            trainer.fit()
        finally:
            stop.set()
            th.join(timeout=30)
        assert "metrics" in got, "no successful scrape during the train"
        assert "flight_steps_recorded_total" in families(got["metrics"])
        assert got["health"]["status"] == "ok"
        assert got["health"]["phase"]  # step/log/data/... or "train"
        # close() (in fit's finally) released the port.
        with pytest.raises(OSError):
            _get(f"http://127.0.0.1:{port}/healthz", timeout=1.0)
