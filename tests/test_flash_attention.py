"""Pallas flash attention vs exact attention (interpret mode on CPU).

The kernel contract: blockwise online-softmax attention — forward and all
three custom-VJP gradients — must be numerically indistinguishable from the
materialized [T, T] softmax, causal and not, across block shapes that
exercise warmup/skip paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.models import get_model
from distributed_training_tpu.ops.flash_attention import flash_attention


def exact_attention(q, k, v, causal):
    s = jnp.einsum("...qd,...kd->...qk", q, k) / np.sqrt(q.shape[-1])
    if causal:
        t = q.shape[-2]
        s = jnp.where(jnp.triu(jnp.ones((t, t), bool), 1), -jnp.inf, s)
    p = jax.nn.softmax(s.astype(jnp.float32), -1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def _qkv(shape, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(*shape), jnp.float32) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,bq,bk", [(256, 128, 128), (256, 64, 128),
                                     (128, 128, 128), (192, 64, 64)])
def test_flash_matches_exact_forward(causal, t, bq, bk):
    q, k, v = _qkv((2, 3, t, 32))
    ref = exact_attention(q, k, v, causal)
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_exact(causal):
    q, k, v = _qkv((2, 2, 256, 32))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    ref = jax.grad(loss(lambda q, k, v: exact_attention(q, k, v, causal)),
                   argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=64, block_k=64,
        # Explicit bwd blocks: keep the dq/dkv kernels multi-block at this
        # T so the cross-block accumulation + causal skip stay covered.
        bwd_block_q=64, bwd_block_k=64)),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", ref, got):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-5, rtol=1e-4,
            err_msg=f"d{name} mismatch")


def test_flash_rejects_indivisible():
    q, k, v = _qkv((1, 1, 100, 32))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_lm_flash_impl_matches_exact():
    """TransformerLM(attn_impl='flash') == the exact model, fwd and grads."""
    kw = dict(num_classes=64, seq_axis=None, num_layers=2, num_heads=2,
              hidden_dim=32, max_len=128)
    exact_m = get_model("transformer_lm", attn_impl="exact", **kw)
    flash_m = get_model("transformer_lm", attn_impl="flash", **kw)
    variables = exact_m.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 128)), jnp.int32)

    ref = exact_m.apply(variables, tokens, train=False)
    got = flash_m.apply(variables, tokens, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

    def loss(m):
        return lambda p: jnp.sum(
            m.apply({"params": p}, tokens, train=False) ** 2)

    gr = jax.grad(loss(exact_m))(variables["params"])
    gg = jax.grad(loss(flash_m))(variables["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=2e-3, rtol=2e-3),
        gr, gg)


def test_flash_lse_matches_exact_logsumexp():
    """flash_attention_lse: out == exact attention and lse == the row
    logsumexp of the scaled (masked) scores, with lse's cotangent folding
    correctly into the q/k grads (the ring-hop merge depends on it)."""
    from distributed_training_tpu.ops.flash_attention import (
        flash_attention_lse,
    )

    for causal in (False, True):
        q, k, v = _qkv((2, 2, 128, 32), seed=causal)
        s = jnp.einsum("...qd,...kd->...qk", q, k) / np.sqrt(q.shape[-1])
        if causal:
            s = jnp.where(jnp.triu(jnp.ones((128, 128), bool), 1),
                          -jnp.inf, s)

        out, lse = flash_attention_lse(q, k, v, causal=causal,
                                       block_q=64, block_k=64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(exact_attention(q, k, v, causal)),
            atol=2e-6, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(lse),
            np.asarray(jax.scipy.special.logsumexp(s, axis=-1)),
            atol=1e-5, rtol=1e-5)

        # lse-cotangent path: a loss that reads BOTH outputs.
        def loss_flash(q, k, v):
            o, l = flash_attention_lse(q, k, v, causal=causal,
                                       block_q=64, block_k=64,
                                       bwd_block_q=64, bwd_block_k=64)
            return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))

        def loss_exact(q, k, v):
            s = jnp.einsum("...qd,...kd->...qk", q, k) / np.sqrt(q.shape[-1])
            if causal:
                t = q.shape[-2]
                s = jnp.where(jnp.triu(jnp.ones((t, t), bool), 1),
                              -jnp.inf, s)
            l = jax.scipy.special.logsumexp(s, axis=-1)
            return (jnp.sum(exact_attention(q, k, v, causal) ** 2)
                    + jnp.sum(jnp.sin(l)))

        ref = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", ref, got):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=5e-5, rtol=1e-4,
                err_msg=f"d{name} mismatch (causal={causal})")
