"""Fleet-wide distributed tracing + federated telemetry plane.

Three layers, no jax compute and no subprocesses (the full drill —
serve_net with ``--trace-dir`` and a mid-stream SIGKILL — is the CI
"Fleet trace drill" and the slow leg in tests/test_router.py):

- **tools/fleet_trace.py on synthetic files** — wall-origin rebase,
  pid-collision remap, hop-handshake clock refinement, the slack and
  failover checks, merged-output validity (``load_trace`` round-trip)
  and bitwise determinism across two identical runs.
- **merge_labeled_expositions** — the /fleet/metrics relabeling: one
  TYPE header per family, every sample tagged ``replica="..."``,
  histogram suffixes grouped under their parent family.
- **RouterFrontDoor federated plane over scripted HTTP replicas** —
  trace-id mint/propagation/echo (header + done frame), the door's
  conserved fleet ledger joined with the replica ledger off the
  terminal frame, /fleet/metrics//fleet/vars//fleet/replicas fan-out,
  and the breaker-open → deterministic ``stale`` marker contract
  (an open replica is never even contacted by a scrape).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributed_training_tpu.observability.prometheus import (
    merge_labeled_expositions,
)
from distributed_training_tpu.observability.trace import (
    TraceSession,
    load_trace,
)
from distributed_training_tpu.serving.ledger import (
    CAUSE_RELAY,
    CAUSE_ROUTE,
    LatencyLedger,
)
from distributed_training_tpu.serving.router import (
    HttpReplica,
    Router,
    RouterFrontDoor,
    generate_over_http,
)
from tools import fleet_trace


# -- synthetic trace files ----------------------------------------------------
def _span(name, ts, dur, pid, tid=1, **args):
    return {"name": name, "ph": "X", "ts": float(ts), "dur": float(dur),
            "pid": pid, "tid": tid, "args": args}


def _instant(name, ts, pid, tid=1, **args):
    return {"name": name, "ph": "i", "s": "t", "ts": float(ts),
            "pid": pid, "tid": tid, "args": args}


def _write_trace(path, *, pid, pname, origin, events):
    meta = {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "ts": 0.0, "args": {"name": pname}}
    obj = {"traceEvents": [meta] + events, "displayTimeUnit": "ms",
           "otherData": {"format": "chrome-trace-events",
                         "wall_time_origin": origin,
                         "dropped_events": 0}}
    path.write_text(json.dumps(obj))
    return str(path)


def _failover_fleet(tmp_path, *, r1_origin=1000.25, r1_recv_ts=10_000.0):
    """A coherent 3-process failover: the door relays hop 1 to
    replica-r0 (killed mid-stream), then hop 2 of the SAME trace id to
    replica-r1, which came up 250 ms later. All timestamps are
    microseconds relative to each file's own origin; wall-consistent
    by construction (hop 2's recv lands ~10 ms into r1's life =
    wall 1000.26, right after the door's send at wall 1000.255)."""
    tid = "req-000003"
    door = _write_trace(
        tmp_path / "door_pid100_trace.json", pid=100, pname="door",
        origin=1000.0, events=[
            _span("route", 500, 300, 100, trace=tid, seq=3),
            _instant("hop.send", 1000, 100, trace=tid, hop=1,
                     replica="r0"),
            _span("relay", 1000, 150_000, 100, trace=tid, hop=1,
                  died=True),
            _instant("failover_resume", 151_000, 100, trace=tid,
                     replica="r0"),
            _instant("hop.send", 255_000, 100, trace=tid, hop=2,
                     replica="r1"),
            _span("relay", 255_000, 80_000, 100, trace=tid, hop=2,
                  died=False),
        ])
    r0 = _write_trace(
        tmp_path / "replica-r0_pid200_trace.json", pid=200,
        pname="replica-r0", origin=1000.0, events=[
            _instant("hop.recv", 2000, 200, trace=tid, hop=1),
            _span("serve.decode", 2000, 120_000, 200, trace=tid),
        ])
    r1 = _write_trace(
        tmp_path / "replica-r1_pid300_trace.json", pid=300,
        pname="replica-r1", origin=r1_origin, events=[
            _instant("hop.recv", r1_recv_ts, 300, trace=tid, hop=2),
            _span("serve.decode", r1_recv_ts, 60_000, 300, trace=tid),
        ])
    return tid, [door, r0, r1]


class TestFleetTraceMerge:
    def test_merged_file_is_valid_and_bitwise_deterministic(
            self, tmp_path):
        _, paths = _failover_fleet(tmp_path)
        out1, out2 = tmp_path / "m1.json", tmp_path / "m2.json"
        assert fleet_trace.main([*paths, "-o", str(out1)]) == 0
        assert fleet_trace.main([*paths, "-o", str(out2)]) == 0
        assert out1.read_bytes() == out2.read_bytes()
        merged = load_trace(str(out1))  # structural validity
        other = merged["otherData"]
        assert other["merged_from"] == [
            "door_pid100_trace.json", "replica-r0_pid200_trace.json",
            "replica-r1_pid300_trace.json"]
        # Non-meta events are globally time-sorted after alignment.
        ts = [ev["ts"] for ev in merged["traceEvents"]
              if ev["ph"] != "M"]
        assert ts == sorted(ts)

    def test_dir_glob_matches_explicit_paths(self, tmp_path, capsys):
        _, paths = _failover_fleet(tmp_path)
        assert fleet_trace.main(
            ["--dir", str(tmp_path), "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["files"] == [
            "door_pid100_trace.json", "replica-r0_pid200_trace.json",
            "replica-r1_pid300_trace.json"]
        assert summary["events"] == 10

    def test_wall_origin_rebase_aligns_sessions(self, tmp_path, capsys):
        # replica-r1's session opened 0.25 s after the door's: its
        # events must shift by exactly that in the merged timeline.
        _, paths = _failover_fleet(tmp_path)
        out = tmp_path / "merged.json"
        assert fleet_trace.main([*paths, "--json",
                                 "-o", str(out)]) == 0
        summary = json.loads(capsys.readouterr().out)
        shift = load_trace(str(out))["otherData"]["shift_us"]
        assert shift["door_pid100_trace.json"] == 0.0
        assert shift["replica-r1_pid300_trace.json"] == \
            pytest.approx(250_000.0)
        # Hop 2: send at door-ts 255000, recv at r1-ts 10000 + 250000
        # shift = 260000 → 5 ms residual, causal and well under slack.
        assert summary["max_residual_ms"] == pytest.approx(5.0)
        assert summary["clock_skew_ms"] == {
            "door_pid100_trace.json": 0.0,
            "replica-r0_pid200_trace.json": 0.0,
            "replica-r1_pid300_trace.json": 0.0}

    def test_hop_refinement_repairs_backdated_clock(self, tmp_path,
                                                    capsys):
        # r1's recorded wall origin is 30 ms EARLY (clock skew): after
        # the coarse rebase its hop-2 recv lands before the door's
        # send. The causality pass shifts the file forward by exactly
        # the negative residual and reports it as clock skew.
        _, paths = _failover_fleet(tmp_path, r1_origin=1000.22)
        assert fleet_trace.main([*paths, "--json",
                                 "--slack-ms", "50"]) == 0
        summary = json.loads(capsys.readouterr().out)
        skew = summary["clock_skew_ms"]["replica-r1_pid300_trace.json"]
        assert skew == pytest.approx(25.0)  # 255ms send - 230ms recv
        # Hop 2's residual is repaired to exactly zero; hop 1 (r0,
        # honest clock) keeps its real 1 ms queueing delay.
        assert summary["max_residual_ms"] == pytest.approx(1.0)

    def test_slack_check_fails_on_excess_residual(self, tmp_path,
                                                  capsys):
        # Recv 105 ms after send (r1 origin pushed 100 ms later):
        # positive residuals are real queueing, never "repaired" — the
        # slack bound is how a drill catches a broken handshake.
        _, paths = _failover_fleet(tmp_path, r1_origin=1000.35)
        assert fleet_trace.main([*paths, "--slack-ms", "50"]) == 1
        assert "residual" in capsys.readouterr().err

    def test_pid_collision_gets_distinct_tracks(self, tmp_path):
        # OS pid reuse: the restarted replica came back with the SAME
        # pid. The merge must keep the incarnations on separate tracks.
        a = _write_trace(tmp_path / "replica-a_pid77_trace.json",
                         pid=77, pname="replica-a", origin=1.0,
                         events=[_span("s", 0, 10, 77, trace="t")])
        b = _write_trace(tmp_path / "replica-b_pid77_trace.json",
                         pid=77, pname="replica-b", origin=2.0,
                         events=[_span("s", 0, 10, 77, trace="t")])
        files = fleet_trace._load_files([a, b])
        fleet_trace._remap_pids(files)
        assert files[0]["pids"] == [77]
        assert files[1]["pids"] == [78]
        merged = fleet_trace.merge(files)
        assert {ev["pid"] for ev in merged["traceEvents"]} == {77, 78}

    def test_check_failover_demands_two_replica_pids(self, tmp_path,
                                                     capsys):
        tid, paths = _failover_fleet(tmp_path)
        assert fleet_trace.main([*paths, "--check-failover",
                                 "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["failover_traces"] == [
            {"trace": tid, "replica_pids": [200, 300]}]
        # Door + ONE replica only: no id spans two replica pids.
        assert fleet_trace.main([paths[0], paths[1],
                                 "--check-failover"]) == 1
        assert "failover" in capsys.readouterr().err

    def test_no_inputs_and_malformed_input_exit_2(self, tmp_path,
                                                  capsys):
        assert fleet_trace.main(["--dir", str(tmp_path / "empty")]) == 2
        bad = tmp_path / "bad_trace.json"
        bad.write_text(json.dumps({"events": []}))
        assert fleet_trace.main([str(bad)]) == 2
        err = capsys.readouterr().err
        assert "no trace files" in err and "traceEvents" in err

    def test_real_sessions_round_trip_through_merge(self, tmp_path):
        # End-to-end with REAL TraceSession files (the exact producer
        # the tool consumes): spans survive, ids attribute correctly.
        door = TraceSession(pid=1, process_name="door")
        t0 = door._t0
        door.instant("hop.send", track="relay", t=t0 + 0.001,
                     trace="req-000001", hop=1)
        rep = TraceSession(pid=2, process_name="replica-r0")
        rep.instant("hop.recv", track="serve", t=rep._t0 + 0.001,
                    trace="req-000001", hop=1)
        p1 = door.save(str(tmp_path / "door_pid1_trace.json"))
        p2 = rep.save(str(tmp_path / "replica-r0_pid2_trace.json"))
        assert fleet_trace.main(
            [p1, p2, "-o", str(tmp_path / "m.json"),
             "--slack-ms", "1000"]) == 0
        merged = load_trace(str(tmp_path / "m.json"))
        recv = [ev for ev in merged["traceEvents"]
                if ev["name"] == "hop.recv"]
        assert len(recv) == 1 and recv[0]["args"]["trace"] == \
            "req-000001"


# -- /fleet/metrics relabeling ------------------------------------------------
class TestMergeLabeledExpositions:
    def test_relabels_and_groups_families(self):
        a = ("# TYPE engine_tokens_total counter\n"
             "engine_tokens_total 7\n"
             "# TYPE queue_wait_ms histogram\n"
             'queue_wait_ms_bucket{le="1"} 2\n'
             "queue_wait_ms_sum 1.5\n"
             "queue_wait_ms_count 2\n")
        b = ("# TYPE engine_tokens_total counter\n"
             "engine_tokens_total 9\n")
        lines = merge_labeled_expositions(
            [('replica="r0"', a), ('replica="r1"', b)])
        # One TYPE header per family, both samples labeled under it.
        assert lines.count("# TYPE engine_tokens_total counter") == 1
        i0 = lines.index('engine_tokens_total{replica="r0"} 7')
        i1 = lines.index('engine_tokens_total{replica="r1"} 9')
        assert lines.index("# TYPE engine_tokens_total counter") \
            < i0 < i1
        # Histogram suffixes group under the parent family, and the
        # replica label lands FIRST, ahead of existing labels.
        assert 'queue_wait_ms_bucket{replica="r0",le="1"} 2' in lines
        assert 'queue_wait_ms_sum{replica="r0"} 1.5' in lines

    def test_ledger_seal_is_close(self):
        led = LatencyLedger(0.0)
        led.stamp(CAUSE_ROUTE, 0.010)
        led.stamp(CAUSE_RELAY, 0.050)
        led.seal(CAUSE_RELAY)
        assert led.closed and led.violations() == []
        assert led.lifetime_ms == pytest.approx(50.0)


# -- federated plane over scripted HTTP replicas ------------------------------
class _FakeReplicaServer:
    """A replica's HTTP surface with no engine behind it: scripted
    probe/healthz, an SSE /generate that echoes the fleet trace
    headers and ships a conserved ledger on the done frame, and
    static /metrics//vars bodies. Counts every scrape per path so the
    breaker-stale test can pin "an open replica is never contacted"."""

    def __init__(self):
        self.seen: list[dict] = []
        self.scrapes: dict[str, int] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, payload):
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if self.path == "/probe":
                    self._json({"hit_tokens": 0,
                                "queue_wait_p95_ms": 0.0,
                                "queue_depth": 0, "active_slots": 0,
                                "draining": False, "phase": "serving"})
                    return
                tid = self.headers.get("X-Graft-Trace")
                outer.seen.append(
                    {"trace": tid,
                     "hop": self.headers.get("X-Graft-Hop")})
                uid = f"uid-{len(outer.seen) - 1}"
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                if tid is not None:
                    self.send_header("X-Graft-Trace", tid)
                self.send_header("Connection", "close")
                self.end_headers()

                def frame(event, payload):
                    return (f"event: {event}\n"
                            f"data: {json.dumps(payload)}\n\n").encode()
                self.wfile.write(frame(
                    "tokens", {"uid": uid, "tokens": [7, 8, 9]}))
                self.wfile.write(frame("done", {
                    "uid": uid, "tokens": [7, 8, 9], "trace_id": tid,
                    "ledger": {"lifetime_ms": 0.5,
                               "causes_ms": {"decode": 0.5},
                               "conserved": True}}))

            def do_GET(self):
                outer.scrapes[self.path] = \
                    outer.scrapes.get(self.path, 0) + 1
                if self.path == "/healthz":
                    self._json({"phase": "serving",
                                "serve_loop_heartbeat": 1})
                elif self.path == "/metrics":
                    body = ("# TYPE engine_tokens_total counter\n"
                            "engine_tokens_total 7\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/vars":
                    self._json({"engine_tokens_total": 7})
                else:
                    self._json({"error": "not found"})

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._srv.server_address[1]}"

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


@pytest.fixture()
def fleet(tmp_path):
    reps = [_FakeReplicaServer() for _ in range(2)]
    router = Router(
        [HttpReplica(r.url, name=f"r{i}") for i, r in enumerate(reps)],
        breaker_threshold=1, breaker_cooldown_s=600.0)
    trace = TraceSession(pid=0, process_name="door")
    trace_path = str(tmp_path / "door_pid0_trace.json")
    door = RouterFrontDoor(router, port=0, trace=trace,
                           trace_path=trace_path).start()
    try:
        yield reps, router, door, trace_path
    finally:
        door.stop()
        for r in reps:
            r.stop()


def _get(url, timeout=10.0):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


class TestFederatedDoor:
    def test_trace_id_minted_propagated_and_echoed(self, fleet):
        reps, router, door, trace_path = fleet
        # No client id: the door mints req-<seq> from its own request
        # sequence (deterministic — never wall clock).
        out = generate_over_http(door.url("/generate"),
                                 {"prompt": [1, 2, 3], "stream": True})
        assert out["trace_id"] == "req-000001"
        assert out["trace_header"] == "req-000001"
        # Client-supplied id passes through untouched.
        out2 = generate_over_http(
            door.url("/generate"), {"prompt": [4, 5], "stream": True},
            trace_id="cli-0007")
        assert out2["trace_id"] == "cli-0007"
        assert out2["trace_header"] == "cli-0007"
        # Each replica hop carried the id + a hop ordinal.
        hops = [s for r in reps for s in r.seen]
        assert sorted(h["trace"] for h in hops) == \
            ["cli-0007", "req-000001"]
        assert all(h["hop"] == "1" for h in hops)

    def test_fleet_ledger_joins_and_conserves(self, fleet):
        reps, router, door, trace_path = fleet
        for i in range(3):
            generate_over_http(door.url("/generate"),
                               {"prompt": [1, 2, i], "stream": True})
        fs = door.fleet_snapshot()
        assert fs["fleet_ledger_requests"] == 3
        assert fs["fleet_ledger_conservation_violations"] == 0
        assert fs["fleet_replica_ledger_joined"] == 3
        assert fs["fleet_replica_ledger_absent"] == 0
        assert fs["fleet_cause_ms"][CAUSE_RELAY] > 0.0
        top = fs["fleet_ledger_top"]
        assert len(top) == 3 and all(e["conserved"] for e in top)
        assert top[0]["replica_lifetime_ms"] == pytest.approx(0.5)

    def test_fleet_endpoints_fan_out(self, fleet):
        reps, router, door, trace_path = fleet
        generate_over_http(door.url("/generate"),
                           {"prompt": [1], "stream": True})
        text = _get(door.url("/fleet/metrics")).decode()
        assert "fleet_ledger_requests 1" in text
        assert "fleet_ledger_conservation_violations 0" in text
        assert 'fleet_replica_stale{replica="r0"} 0' in text
        assert 'fleet_replica_stale{replica="r1"} 0' in text
        assert 'engine_tokens_total{replica="r0"} 7' in text
        assert 'engine_tokens_total{replica="r1"} 7' in text
        assert text.count("# TYPE engine_tokens_total counter") == 1
        assert 'router_replica_breaker_state{replica="r0"} 0' in text
        fv = json.loads(_get(door.url("/fleet/vars")))
        assert fv["replicas"]["r0"]["engine_tokens_total"] == 7
        assert fv["fleet"]["fleet_ledger_requests"] == 1
        assert fv["router"]["router_requests_routed"] == 1
        fr = json.loads(_get(door.url("/fleet/replicas")))
        assert [r["name"] for r in fr["replicas"]] == ["r0", "r1"]
        assert all(r["breaker_state_code"] == 0
                   for r in fr["replicas"])

    def test_breaker_open_replica_is_stale_not_contacted(self, fleet):
        reps, router, door, trace_path = fleet
        router.note_replica_failure(1)  # threshold 1 → open, 600s cool
        assert router.breaker_state(1) == "open"
        before = dict(reps[1].scrapes)
        fv = json.loads(_get(door.url("/fleet/vars")))
        assert fv["replicas"]["r1"] == {"stale": True,
                                       "reason": "breaker_open"}
        assert fv["replicas"]["r0"]["engine_tokens_total"] == 7
        text = _get(door.url("/fleet/metrics")).decode()
        assert 'fleet_replica_stale{replica="r1"} 1' in text
        assert 'engine_tokens_total{replica="r1"}' not in text
        assert 'router_replica_breaker_state{replica="r1"} 2' in text
        # The scrape never reached the open replica — the stale marker
        # is a ROUTER-SIDE fact (lint-pinned: no breaker mutation and
        # no probe from the do_GET fan-out either).
        assert reps[1].scrapes == before

    def test_door_trace_has_fleet_spans(self, fleet):
        reps, router, door, trace_path = fleet
        generate_over_http(door.url("/generate"),
                           {"prompt": [1, 2], "stream": True})
        door.stop()  # checkpoints the door trace
        obj = load_trace(trace_path)
        by_name = {}
        for ev in obj["traceEvents"]:
            by_name.setdefault(ev["name"], []).append(ev)
        (send,) = by_name["hop.send"]
        assert send["args"] == {"trace": "req-000001", "hop": 1,
                                "replica": send["args"]["replica"],
                                "resume": False}
        (relay,) = by_name["relay"]
        assert relay["args"]["trace"] == "req-000001"
        assert relay["args"]["died"] is False
        (route,) = by_name["route"]
        assert route["args"]["seq"] == 1
        (audit,) = by_name["fleet.audit"]
        assert audit["args"]["conserved"] is True
