"""Flight recorder: ring wraparound, percentile math, dump round-trip,
goodput accounting, and the WallClock's exclusive phase attribution.

Pure host-side logic (no devices) — the recorder's whole design is that
the hot path is one ``perf_counter`` ring write; these tests pin the
derived statistics that the anomaly/crash dumps and
``tools/flight_report.py`` rely on.
"""

import json

import numpy as np
import pytest

from distributed_training_tpu.observability.flight_recorder import (
    FlightRecorder,
    percentile,
)
from distributed_training_tpu.utils.profiling import WallClock


class TestRing:
    def test_wraparound_keeps_last_ring_size(self):
        r = FlightRecorder(ring_size=8)
        for s in range(1, 21):
            r.record_step(s, t=float(s))
        assert len(r) == 8
        assert [n for n, _ in r.steps] == list(range(13, 21))
        assert r._count == 20

    def test_partial_ring_in_order(self):
        r = FlightRecorder(ring_size=8)
        for s in range(1, 4):
            r.record_step(s, t=float(s))
        assert [n for n, _ in r.steps] == [1, 2, 3]

    def test_flush_ring_wraps_too(self):
        r = FlightRecorder(ring_size=4)
        for s in range(10):
            r.record_flush(s, {"loss": float(s)})
        assert [f["step"] for f in r.flushes] == [6, 7, 8, 9]

    def test_flush_drops_none_and_step_key(self):
        r = FlightRecorder(ring_size=4)
        r.record_flush(3, {"loss": 1.0, "accuracy": None, "step": 3})
        assert r.flushes == [{"step": 3, "loss": 1.0}]

    def test_ring_size_validated(self):
        with pytest.raises(ValueError, match="ring_size"):
            FlightRecorder(ring_size=1)


class TestPercentiles:
    def test_matches_numpy_linear(self):
        rng = np.random.RandomState(0)
        xs = rng.rand(37).tolist()
        for q in (0, 25, 50, 95, 100):
            assert percentile(xs, q) == pytest.approx(
                float(np.percentile(xs, q)), rel=1e-12)

    def test_single_value(self):
        assert percentile([4.2], 95) == 4.2

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            percentile([], 50)

    def test_synthetic_timeline_stats(self):
        # 9 steps at 10 ms, one 100 ms straggler: p50 pins the steady
        # state, max pins the straggler, p95 interpolates between them.
        r = FlightRecorder(ring_size=64)
        t = 0.0
        r.record_step(1, t=t)
        for s in range(2, 11):
            t += 0.1 if s == 10 else 0.01
            r.record_step(s, t=t)
        stats = r.step_time_stats()
        times = r.step_times_ms()
        assert len(times) == 9
        assert stats["step_time_p50_ms"] == pytest.approx(10.0, rel=1e-6)
        assert stats["step_time_max_ms"] == pytest.approx(100.0, rel=1e-6)
        assert stats["step_time_p95_ms"] == pytest.approx(
            float(np.percentile(times, 95)), rel=1e-9)

    def test_non_adjacent_steps_excluded(self):
        # A gap in step numbering (eval/ckpt between epochs) must not be
        # billed as a 5-second "step".
        r = FlightRecorder(ring_size=8)
        r.record_step(1, t=0.0)
        r.record_step(2, t=0.01)
        r.record_step(10, t=5.0)   # resumed after a gap
        r.record_step(11, t=5.01)
        times = r.step_times_ms()
        assert len(times) == 2
        assert max(times) == pytest.approx(10.0, rel=1e-6)

    def test_marked_epoch_gap_excluded(self):
        # Step numbers stay CONSECUTIVE across epochs, so the numbering
        # heuristic can't see the eval/ckpt pause — mark_gap (called by
        # the trainers at epoch start) excludes that one delta.
        r = FlightRecorder(ring_size=8)
        r.record_step(1, t=0.0)
        r.record_step(2, t=0.01)
        r.mark_gap()                 # epoch boundary: eval + checkpoint
        r.record_step(3, t=5.0)      # first step of the next epoch
        r.record_step(4, t=5.01)
        times = r.step_times_ms()
        assert len(times) == 2
        assert max(times) == pytest.approx(10.0, rel=1e-6)

    def test_too_few_steps_empty_stats(self):
        r = FlightRecorder()
        assert r.step_time_stats() == {}
        r.record_step(1, t=0.0)
        assert r.step_time_stats() == {}


class TestDump:
    def test_dump_load_round_trip(self, tmp_path):
        r = FlightRecorder(ring_size=16)
        for s in range(1, 6):
            r.record_step(s, t=s * 0.01)
        r.record_flush(5, {"loss": 1.25, "grad_norm": 3.0})
        r.record_anomaly(5, ["non-finite loss (nan)"])
        path = str(tmp_path / "sub" / "flight.json")  # dirs auto-created
        written = r.dump(path, reason="unit-test",
                         phase_totals={"step": 3.0, "data": 1.0})
        loaded = FlightRecorder.load(path)
        assert loaded == json.loads(json.dumps(written))  # JSON-stable
        assert loaded["reason"] == "unit-test"
        assert loaded["steps"] == [[s, s * 0.01] for s in range(1, 6)]
        assert loaded["flushes"][-1]["grad_norm"] == 3.0
        assert loaded["anomalies"][0]["reasons"] == ["non-finite loss (nan)"]
        assert loaded["wall_clock"]["goodput"] == pytest.approx(0.75)
        assert loaded["step_time_stats"]["step_time_p50_ms"] == pytest.approx(
            10.0, rel=1e-6)

    def test_non_finite_metrics_dump_strict_json(self, tmp_path):
        # The anomaly dump's star witness IS a NaN loss — it must survive
        # as a parseable token, not as invalid bare `NaN`/`Infinity`
        # (jq / JSON.parse reject those).
        r = FlightRecorder(ring_size=8)
        r.record_flush(1, {"loss": float("nan"), "grad_norm": float("inf")})
        path = str(tmp_path / "f.json")
        r.dump(path, reason="anomaly: non-finite loss")
        text = open(path).read()
        assert "NaN" not in text and "Infinity" not in text
        snap = json.loads(text)
        assert snap["flushes"][-1]["loss"] == "nan"
        assert snap["flushes"][-1]["grad_norm"] == "inf"

    def test_load_rejects_unknown_format(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"format_version": 999}))
        with pytest.raises(ValueError, match="format"):
            FlightRecorder.load(str(p))

    def test_goodput_fractions_partition(self):
        g = FlightRecorder.goodput(
            {"step": 6.0, "data": 2.0, "log": 1.0, "ckpt": 1.0})
        assert g["goodput"] == pytest.approx(0.6)
        assert sum(g["phase_fraction"].values()) == pytest.approx(1.0)
        assert FlightRecorder.goodput({}) == {}


class TestWallClock:
    def test_nested_phase_attribution_is_exclusive(self):
        clock = WallClock(enabled=True)
        with clock.phase("eval"):
            with clock.phase("data"):
                pass
        totals = clock.snapshot()
        # Exclusive attribution: eval + data partition the eval span, so
        # goodput fractions can sum to 1 (no double counting).
        assert set(totals) == {"eval", "data"}
        assert totals["eval"] >= 0 and totals["data"] >= 0

    def test_report_clears_but_snapshot_is_lifetime(self):
        clock = WallClock(enabled=True)
        with clock.phase("step"):
            pass
        first = clock.report()
        assert first["step"] > 0
        assert clock.report() == {}  # report() clears per epoch
        with clock.phase("step"):
            pass
        # snapshot accumulates across report() clears (whole-run goodput).
        assert clock.snapshot()["step"] >= first["step"]
        second = clock.report()["step"]
        assert clock.snapshot()["step"] == pytest.approx(
            first["step"] + second)

    def test_disabled_clock_is_free(self):
        clock = WallClock(enabled=False)
        with clock.phase("step"):
            pass
        assert clock.snapshot() == {} and clock.report() == {}


class TestFlightReportTool:
    def test_summarize_and_render(self, tmp_path):
        from conftest import load_cli_module

        mod = load_cli_module("tools/flight_report.py")
        r = FlightRecorder(ring_size=16)
        for s in range(1, 5):
            r.record_step(s, t=s * 0.02)
        r.record_flush(4, {"loss": 2.0, "mfu": 0.41,
                           "mem_peak_bytes": 2.0 * 2 ** 30})
        r.record_anomaly(4, ["grad-norm spike"])
        path = str(tmp_path / "f.json")
        r.dump(path, reason="anomaly",
               phase_totals={"step": 8.0, "data": 2.0})
        summary = mod.summarize(mod.FlightRecorder.load(path))
        assert summary["goodput"] == pytest.approx(0.8)
        assert summary["last_flush"]["mfu"] == 0.41
        text = mod.render(summary)
        assert "p50 20.00 ms" in text
        assert "goodput: 80.0%" in text
        assert "grad-norm spike" in text
        # CLI main round-trips --json
        import io
        import contextlib

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            assert mod.main([path, "--json"]) == 0
        assert json.loads(buf.getvalue())["steps_in_ring"] == 4

    def test_fleet_section_renders_and_tolerates_absence(self):
        """The fleet-ledger section (router door dumps) renders with
        .get-tolerant access; a pre-fleet dump without the key — and a
        partial section from an older door — must not crash."""
        from conftest import load_cli_module

        mod = load_cli_module("tools/flight_report.py")
        snap = {"reason": "test", "steps": [], "steps_recorded_total": 0,
                "fleet": {
                    "fleet_ledger_requests": 3,
                    "fleet_ledger_conservation_violations": 1,
                    "fleet_ledger_violation_last": "req-000002: drift",
                    "fleet_replica_ledger_joined": 2,
                    "fleet_replica_ledger_absent": 1,
                    "fleet_cause_ms": {"relay": 4.0, "route": 1.0},
                    "fleet_ledger_top": [
                        {"trace_id": "req-000002", "uid": 7,
                         "lifetime_ms": 5.0,
                         "replica_lifetime_ms": 4.5,
                         "causes_ms": {"relay": 4.0, "route": 1.0},
                         "conserved": False}]}}
        text = mod.render(mod.summarize(snap))
        assert "fleet ledger: 3 request(s) audited" in text
        assert "2 joined / 1 absent" in text
        assert "LAST VIOLATION: req-000002: drift" in text
        assert "req-000002 (uid 7): 5.0 ms door-side" in text
        assert "[NOT CONSERVED]" in text
        # Absent section: no fleet line at all, no crash.
        no_fleet = mod.render(mod.summarize(
            {"reason": "old", "steps": [], "steps_recorded_total": 0}))
        assert "fleet ledger" not in no_fleet
        # Partial section (older door, fewer counters): defaults render.
        partial = mod.render(mod.summarize(
            {"reason": "partial", "steps": [], "steps_recorded_total": 0,
             "fleet": {"fleet_ledger_requests": 1}}))
        assert ("fleet ledger: 1 request(s) audited cross-hop, "
                "0 conservation") in partial
