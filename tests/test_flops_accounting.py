"""Analytic FLOPs vs XLA's AOT cost analysis, and the MFU plumbing.

The analytic formulas (``observability/flops.py``) count matmul FLOPs
only (multiply-add = 2), the published MFU convention; XLA's
``cost_analysis()`` books the same matmuls plus elementwise arithmetic
(LayerNorm/BN adds, residuals, softmax normalization — transcendentals
are a separate counter). The cross-check therefore pins a RATIO BAND:
analytic must land just under XLA's number on matmul-dominated configs —
close enough to catch a wrong term (any conv/projection miscount is a
>2x move at these dims), strict enough that analytic never exceeds XLA
by more than rounding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.models import get_model
from distributed_training_tpu.observability.flops import (
    device_peak_flops,
    forward_flops,
    gpt_forward_flops,
    mfu,
    resnet_forward_flops,
    train_step_flops,
    vit_forward_flops,
    xla_cost_flops,
)


def _fwd_cost(model, *args, **apply_kwargs):
    return xla_cost_flops(
        lambda p, x: model.apply({"params": p}, x, **apply_kwargs),
        *args)


class TestAnalyticVsCostAnalysis:
    def test_tiny_gpt_forward_agrees(self):
        # Matmul-dominated dims; exact attention computes the full masked
        # T^2 score matrix, matching the full-T^2 charging convention.
        model = get_model("transformer_lm", num_classes=512, num_layers=2,
                          num_heads=4, hidden_dim=128, max_len=64)
        tokens = jnp.zeros((1, 64), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        xla = _fwd_cost(model, params, tokens, train=False)
        assert xla is not None
        analytic = gpt_forward_flops(
            num_layers=2, hidden_dim=128, seq_len=64, vocab_size=512,
            mlp_ratio=4, batch=1)
        ratio = analytic / xla
        assert 0.75 <= ratio <= 1.02, (analytic, xla, ratio)

    def test_tiny_resnet_forward_agrees(self):
        model = get_model("resnet18", num_classes=10, stem="cifar")
        x = jnp.zeros((1, 64, 64, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        xla = xla_cost_flops(
            lambda v, x: model.apply(v, x, train=False), variables, x)
        assert xla is not None
        analytic = resnet_forward_flops(
            "resnet18", image_size=64, num_classes=10, batch=1, stem="cifar")
        # Analytic sits slightly ABOVE XLA here: the published-convention
        # count charges every output position x kernel tap, while XLA's
        # cost analysis excludes the SAME-padding taps that read padding
        # (measured +4.3% per 3x3 conv at 32^2, growing as spatial dims
        # shrink). Band asymmetric around 1 accordingly.
        ratio = analytic / xla
        assert 0.95 <= ratio <= 1.20, (analytic, xla, ratio)

    def test_tiny_vit_forward_agrees(self):
        model = get_model("vit_b16", num_classes=10, patch_size=8,
                          hidden_size=64, num_layers=2, num_heads=4,
                          mlp_dim=128)
        x = jnp.zeros((1, 32, 32, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x, train=False)["params"]
        xla = _fwd_cost(model, params, x, train=False)
        assert xla is not None
        analytic = vit_forward_flops(
            image_size=32, patch_size=8, hidden_size=64, num_layers=2,
            mlp_dim=128, num_classes=10, batch=1)
        ratio = analytic / xla
        assert 0.70 <= ratio <= 1.02, (analytic, xla, ratio)


class TestFormulaProperties:
    def test_linear_in_batch_accum_awareness(self):
        # The trainers pass the EFFECTIVE batch (micro x accum x world):
        # doubling it doubles step FLOPs — accumulation-aware MFU needs
        # exactly this linearity.
        one = gpt_forward_flops(num_layers=2, hidden_dim=64, seq_len=32,
                                vocab_size=128, batch=1)
        eight = gpt_forward_flops(num_layers=2, hidden_dim=64, seq_len=32,
                                  vocab_size=128, batch=8)
        assert eight == pytest.approx(8 * one)
        assert resnet_forward_flops(
            "resnet_micro", image_size=32, num_classes=10, batch=4,
            stem="cifar") == pytest.approx(4 * resnet_forward_flops(
                "resnet_micro", image_size=32, num_classes=10, batch=1,
                stem="cifar"))

    def test_step_is_three_forwards(self):
        assert train_step_flops(10.0) == 30.0
        assert train_step_flops(None) is None

    def test_instance_dispatch_matches_name_formulas(self):
        lm = get_model("transformer_lm", num_classes=256, num_layers=3,
                       num_heads=2, hidden_dim=64, max_len=128)
        assert forward_flops(lm, seq_len=128, batch=2) == pytest.approx(
            gpt_forward_flops(num_layers=3, hidden_dim=64, seq_len=128,
                              vocab_size=256, batch=2))
        rn = get_model("resnet50", num_classes=1000)
        assert forward_flops(rn, image_size=224) == pytest.approx(
            resnet_forward_flops("resnet50", image_size=224,
                                 num_classes=1000))
        # ResNet-50's textbook count is ~4.1 GMACs/image; this module
        # (like XLA and peak-FLOPs specs) charges 2 FLOPs per
        # multiply-add, so the anchor is ~8.2e9 — a sanity check that the
        # architecture walk is right, not just internally consistent.
        assert 7.5e9 < forward_flops(rn, image_size=224) < 8.8e9

    def test_moe_lm_reports_none(self):
        moe = get_model("transformer_lm", num_classes=256, num_layers=2,
                        num_heads=2, hidden_dim=64, max_len=64,
                        moe_num_experts=4, moe_every=1)
        assert forward_flops(moe, seq_len=64) is None

    def test_missing_dims_raise(self):
        lm = get_model("transformer_lm", num_classes=256, num_layers=1,
                       num_heads=2, hidden_dim=64, max_len=64)
        with pytest.raises(ValueError, match="seq_len"):
            forward_flops(lm)


class TestMfu:
    def test_peak_env_override(self, monkeypatch):
        monkeypatch.setenv("OBS_PEAK_FLOPS", "1e12")
        assert device_peak_flops() == 1e12

    def test_cpu_peak_unknown(self, monkeypatch):
        monkeypatch.delenv("OBS_PEAK_FLOPS", raising=False)
        # The virtual test devices are CPU: no peak, so MFU is honestly
        # absent rather than a guessed number.
        assert device_peak_flops(jax.devices()[0]) is None

    def test_known_kind_table(self):
        class FakeDev:
            device_kind = "TPU v5 lite"

        assert device_peak_flops(FakeDev()) == 197e12

    def test_mfu_math(self):
        assert mfu(100e12, 2, 250e12) == pytest.approx(0.2)
        assert mfu(100e12, 2, None) is None


class TestStepCostAnalysis:
    def test_lm_step_lower_hook_cost_analysis(self, mesh):
        """The AOT ``.lower`` hook the factories expose feeds the same
        cross-check at the STEP level: one fwd+bwd+Adam program books
        more than the model forward alone, in the right ballpark of 3x
        forward + optimizer elementwise."""
        import optax

        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.train.lm_step import (
            make_lm_batch,
            make_tp_lm_train_step,
        )
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import (
            init_train_state,
        )

        model = get_model("transformer_lm", num_classes=512, num_layers=2,
                          num_heads=4, hidden_dim=128, max_len=64)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (1, 8), optax.sgd(0.1),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)
        step = make_tp_lm_train_step(mesh, model=model)
        toks = np.zeros((8, 65), np.int32)
        batch = jax.device_put(
            {k: jnp.asarray(v) for k, v in make_lm_batch(toks).items()},
            step.batch_shardings)
        compiled = step.lower(state, batch, jax.random.PRNGKey(0)).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        # The partitioned program's cost analysis is PER DEVICE (the
        # batch is sharded 8 ways over the mesh); scale back to global.
        xla = float(ca["flops"]) * mesh.devices.size
        fwd = gpt_forward_flops(num_layers=2, hidden_dim=128, seq_len=64,
                                vocab_size=512, batch=8)
        # Step >= ~3x forward (XLA adds optimizer/elementwise work); and
        # the analytic step number stays within 2x of what XLA booked.
        assert xla > 2.4 * fwd, (xla, fwd)
        assert train_step_flops(fwd) == pytest.approx(3 * fwd)
        assert train_step_flops(fwd) / xla > 0.5
