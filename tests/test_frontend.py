"""Network front door, frontend half (serving/frontend.py).

The headline pin: SSE-streamed completions over HTTP are **bitwise
identical** to the batch engine's output for the same seeded workload —
greedy AND sampled — because tokens are a pure function of
``(seed, uid, position)`` and the sequential client preserves uid
order. Plus: per-token streaming framing, journal-backed exactly-once
delivery via the ack cursor, the read-only routing probe, drain/reopen
admin flow over HTTP, and the cache-aware seat-ordering satellite
(bitwise-neutral when the prefix cache is off).

Everything here runs one tiny CPU model in-process; the multi-replica
subprocess drills live in tests/test_router.py.
"""

import json
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.serving import Engine, RequestQueue
from distributed_training_tpu.serving.frontend import ServingFrontend
from distributed_training_tpu.serving.router import (
    generate_over_http,
    sse_events,
)

VOCAB = 31
MAX_LEN = 64
PS = 4


@pytest.fixture(scope="module")
def lm():
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=1, num_heads=2,
        hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


def make_engine(lm, **kw):
    model, params = lm
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("kv_page_size", PS)
    kw.setdefault("prefill_chunk", 4)
    return Engine(model, params, ServeConfig(**kw))


PROMPTS = [((np.arange(1, 9 + i, dtype=np.int32) * (2 + i)) % VOCAB)
           for i in range(5)]


def _serve_batch(eng, prompts):
    """The batch CLI path: submit in order, run each to completion —
    the reference stream the HTTP pin compares against."""
    out = {}
    for p in prompts:
        r = eng.submit(p)
        for f in eng.run():
            out[f.uid] = f
    return [out[u] for u in sorted(out)]


def _serve_http(frontend, prompts, *, stream=True):
    """The network path: same prompts, same order, one at a time."""
    results = []
    for p in prompts:
        results.append(generate_over_http(
            frontend.url("/generate"),
            {"prompt": [int(t) for t in p], "stream": stream},
            timeout_s=60.0))
    return results


def _post(url, payload, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


class TestStreamEqualsBatch:
    def test_sse_bitwise_equals_batch_greedy(self, lm):
        batch = _serve_batch(make_engine(lm, prefix_cache=True), PROMPTS)
        fe = ServingFrontend(make_engine(lm, prefix_cache=True)).start()
        try:
            net = _serve_http(fe, PROMPTS)
        finally:
            fe.stop()
        assert [r["tokens"] for r in net] == \
            [[int(t) for t in f.tokens] for f in batch]
        # The stream IS the completion: per-token events concatenate to
        # exactly the done payload (no token lost, none duplicated).
        for r in net:
            assert r["streamed_tokens"] == r["tokens"]

    def test_sse_bitwise_equals_batch_sampled(self, lm):
        kw = dict(temperature=0.7, seed=11)
        batch = _serve_batch(make_engine(lm, **kw), PROMPTS)
        fe = ServingFrontend(make_engine(lm, **kw)).start()
        try:
            net = _serve_http(fe, PROMPTS)
        finally:
            fe.stop()
        assert [r["tokens"] for r in net] == \
            [[int(t) for t in f.tokens] for f in batch]
        for r in net:
            assert r["streamed_tokens"] == r["tokens"]

    def test_unary_mode_matches_streamed(self, lm):
        fe = ServingFrontend(make_engine(lm)).start()
        try:
            streamed = _serve_http(fe, PROMPTS[:2], stream=True)
            unary = _serve_http(fe, PROMPTS[:2], stream=False)
        finally:
            fe.stop()
        assert [r["tokens"] for r in unary] == \
            [r["tokens"] for r in streamed]

    def test_sse_framing_is_event_per_iteration(self, lm):
        """Raw SSE check: tokens arrive as typed events ending in one
        'done' carrying the full completion."""
        fe = ServingFrontend(make_engine(lm)).start()
        try:
            req = urllib.request.Request(
                fe.url("/generate"),
                data=json.dumps({"prompt": [3, 5, 7],
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60.0) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/event-stream")
                events = list(sse_events(resp))
        finally:
            fe.stop()
        names = [e for e, _ in events]
        assert names[-1] == "done"
        assert set(names[:-1]) == {"tokens"}
        streamed = [t for e, d in events if e == "tokens"
                    for t in d["tokens"]]
        assert streamed == events[-1][1]["tokens"]


class TestExactlyOnce:
    def test_delivered_stream_acks_the_journal(self, lm, tmp_path):
        jdir = str(tmp_path / "j1")
        eng = make_engine(lm, journal_dir=jdir)
        eng.recover()
        fe = ServingFrontend(eng).start()
        try:
            _serve_http(fe, PROMPTS[:2])
        finally:
            fe.stop()
            eng.journal.shutdown()
        # Delivery acked the cursor: a recovery replays NOTHING.
        eng2 = make_engine(lm, journal_dir=jdir)
        report = eng2.recover()
        assert report["redelivered"] == []
        eng2.journal.shutdown()

    def test_unacked_completion_redelivers(self, lm, tmp_path):
        """The contrast pin: same workload WITHOUT the frontend's ack
        (a client that never got its stream) must redeliver."""
        jdir = str(tmp_path / "j2")
        eng = make_engine(lm, journal_dir=jdir)
        eng.recover()
        eng.submit(PROMPTS[0])
        list(eng.run())
        eng.journal.shutdown()
        eng2 = make_engine(lm, journal_dir=jdir)
        report = eng2.recover()
        assert len(report["redelivered"]) == 1
        eng2.journal.shutdown()


class TestProbeAndAdmin:
    def test_probe_reports_residency_read_only(self, lm):
        eng = make_engine(lm, prefix_cache=True)
        fe = ServingFrontend(eng).start()
        try:
            prompt = [int(t) for t in PROMPTS[0]]
            _serve_http(fe, [PROMPTS[0]])
            st, cold = _post(fe.url("/probe"), {"prompt": [9, 9, 9, 9]})
            assert st == 200 and cold["hit_tokens"] == 0
            st, warm = _post(fe.url("/probe"), {"prompt": prompt})
            assert st == 200 and warm["hit_tokens"] > 0
            # Read-only: probing twice is idempotent (no recency or
            # refcount movement observable through the probe itself).
            st, warm2 = _post(fe.url("/probe"), {"prompt": prompt})
            assert warm2["hit_tokens"] == warm["hit_tokens"]
            assert warm["phase"] in ("idle", "serving")
            assert "queue_wait_p95_ms" in warm
        finally:
            fe.stop()

    def test_drain_deploy_reopen_over_http(self, lm):
        eng = make_engine(lm)
        fe = ServingFrontend(eng).start()
        try:
            _serve_http(fe, [PROMPTS[0]])
            st, _ = _post(fe.url("/admin/drain"), {})
            assert st == 200
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                hz = json.loads(_get(fe.url("/healthz")))
                if hz["phase"] == "drained":
                    break
                time.sleep(0.02)
            assert hz["phase"] == "drained"
            # Admission is closed: a submit is refused, not queued.
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(fe.url("/generate"),
                      {"prompt": [1, 2, 3], "stream": False},
                      timeout=30.0)
            assert ei.value.code == 503
            # No-op redeploy at the drained boundary bumps the epoch.
            epoch0 = int(hz["weights_epoch"])
            st, _ = _post(fe.url("/admin/deploy"), {})
            assert st == 202
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                hz = json.loads(_get(fe.url("/healthz")))
                if int(hz["weights_epoch"]) > epoch0:
                    break
                time.sleep(0.02)
            assert int(hz["weights_epoch"]) == epoch0 + 1
            st, _ = _post(fe.url("/admin/reopen"), {})
            assert st == 200
            out = _serve_http(fe, [PROMPTS[1]])
            assert out[0]["tokens"]
        finally:
            fe.stop()

    def test_healthz_and_metrics_delegate_to_exporter(self, lm):
        fe = ServingFrontend(make_engine(lm)).start()
        try:
            hz = json.loads(_get(fe.url("/healthz")))
            assert hz["status"] == "ok" and "weights_epoch" in hz
            text = _get(fe.url("/metrics")).decode()
            assert "# TYPE" in text
        finally:
            fe.stop()

    def test_bad_requests_are_4xx_not_500(self, lm):
        fe = ServingFrontend(make_engine(lm)).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(fe.url("/generate"), {"stream": False})
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(fe.url("/nope"), {})
            assert ei.value.code == 404
        finally:
            fe.stop()


class TestChunkedBodies:
    """Satellite: chunked Transfer-Encoding request bodies; 411 is
    reserved for a body with NEITHER framing."""

    def test_read_body_parses_chunked_framing(self):
        import io

        from distributed_training_tpu.serving.httpbody import read_body

        wire = (b"5;ext=1\r\nhello\r\n"        # extension stripped
                b"6\r\n world\r\n"
                b"0\r\nTrailer: x\r\n\r\n")    # trailers consumed
        headers = {"Transfer-Encoding": "chunked"}
        assert read_body(headers, io.BytesIO(wire)) == b"hello world"

    def test_read_body_rejects_malformed_and_oversize(self):
        import io

        from distributed_training_tpu.serving.httpbody import (
            NoBodyLength,
            read_body,
        )

        chunked = {"Transfer-Encoding": "chunked"}
        with pytest.raises(ValueError):
            read_body(chunked, io.BytesIO(b"zz\r\nhi\r\n0\r\n\r\n"))
        with pytest.raises(ValueError):  # missing CRLF after data
            read_body(chunked, io.BytesIO(b"2\r\nhiXX0\r\n\r\n"))
        with pytest.raises(ValueError):  # chunk bigger than the cap
            read_body(chunked, io.BytesIO(b"5\r\nhello\r\n0\r\n\r\n"),
                      max_bytes=3)
        with pytest.raises(NoBodyLength):
            read_body({}, io.BytesIO(b""))

    def test_chunked_post_equals_content_length_post(self, lm):
        import http.client

        fe = ServingFrontend(make_engine(lm)).start()
        try:
            plain = generate_over_http(
                fe.url("/generate"),
                {"prompt": [3, 5, 7], "stream": False}, timeout_s=60.0)
            body = json.dumps({"prompt": [3, 5, 7],
                               "stream": False}).encode()
            conn = http.client.HTTPConnection(
                "127.0.0.1", fe.port, timeout=60.0)
            try:
                conn.request(
                    "POST", "/generate",
                    body=iter([body[:7], body[7:]]),
                    headers={"Content-Type": "application/json"},
                    encode_chunked=True)
                resp = conn.getresponse()
                assert resp.status == 200
                chunked = json.loads(resp.read())
            finally:
                conn.close()
            assert chunked["tokens"] == plain["tokens"]
        finally:
            fe.stop()

    def test_411_only_when_neither_framing_present(self, lm):
        import socket

        fe = ServingFrontend(make_engine(lm)).start()
        try:
            s = socket.create_connection(("127.0.0.1", fe.port),
                                         timeout=10.0)
            try:
                s.sendall(b"POST /generate HTTP/1.1\r\n"
                          b"Host: t\r\n\r\n")
                status = s.recv(4096).split(b"\r\n", 1)[0]
            finally:
                s.close()
            assert b"411" in status
            # Malformed chunked framing is a 400, NOT a 411: a length
            # WAS declared, it just didn't parse.
            s = socket.create_connection(("127.0.0.1", fe.port),
                                         timeout=10.0)
            try:
                s.sendall(b"POST /generate HTTP/1.1\r\nHost: t\r\n"
                          b"Transfer-Encoding: chunked\r\n\r\n"
                          b"zz\r\nhi\r\n0\r\n\r\n")
                status = s.recv(4096).split(b"\r\n", 1)[0]
            finally:
                s.close()
            assert b"400" in status
        finally:
            fe.stop()


class TestCancelOnDisconnect:
    def test_client_hangup_cancels_and_frees_pages(self, lm):
        """A dead SSE socket must CANCEL the in-flight request — evict
        it, free its pages, close its ledger under 'cancelled' — not
        let it decode its full budget for nobody."""
        import socket

        eng = make_engine(lm, max_new_tokens=24, prefix_cache=True)
        fe = ServingFrontend(eng).start()
        try:
            body = json.dumps({"prompt": [3, 5, 7],
                               "stream": True}).encode()
            s = socket.create_connection(("127.0.0.1", fe.port),
                                         timeout=30.0)
            s.sendall(b"POST /generate HTTP/1.1\r\nHost: t\r\n"
                      b"Content-Type: application/json\r\n"
                      b"Content-Length: %d\r\n\r\n" % len(body) + body)
            # Read until the first tokens frame lands, then hang up
            # mid-stream with ~20 tokens of budget left.
            buf = b""
            while b"event: tokens" not in buf or b"\n\n" not in buf:
                buf += s.recv(4096)
            s.close()
            deadline = time.monotonic() + 30.0
            cancelled = 0
            while time.monotonic() < deadline:
                stats = json.loads(_get(fe.url("/vars")))["serving"]
                cancelled = stats.get("requests_cancelled", 0)
                if cancelled:
                    break
                time.sleep(0.05)
            assert cancelled == 1
            assert stats.get("ledger_cancelled_ms_total", 0.0) > 0.0
            # Pages came back: the leak audit is green once idle, and
            # the replica still serves the next client.
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                st, probe = _post(fe.url("/probe"), {})
                if not probe["queue_depth"] and not probe["active_slots"]:
                    break
                time.sleep(0.05)
            st, verdict = _post(fe.url("/admin/check_balanced"), {})
            assert st == 200 and verdict["balanced"], verdict
            out = _serve_http(fe, [PROMPTS[0]])
            assert out[0]["tokens"]
        finally:
            fe.stop()


class TestResumeFailover:
    def test_journal_tail_resume_redelivers_exactly_the_tail(
            self, lm, tmp_path):
        """A finished-unacked journal entry answers a resume cursor
        with the UNDELIVERED tail — and a done event carrying the full
        array, so the client's head+tail concatenation checks out."""
        jdir = str(tmp_path / "jr")
        eng = make_engine(lm, journal_dir=jdir)
        eng.recover()
        # Finish a request WITHOUT delivering it (the journal's
        # finished-unacked state — exactly what a dead relay leaves).
        r = eng.submit(PROMPTS[0])
        (fin,) = list(eng.run())
        full = [int(t) for t in fin.tokens]
        fe = ServingFrontend(eng).start()
        try:
            req = urllib.request.Request(
                fe.url("/generate"),
                data=json.dumps({
                    "prompt": [int(t) for t in PROMPTS[0]],
                    "stream": True,
                    "resume": {"uid": r.uid, "delivered": 2}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60.0) as resp:
                events = list(sse_events(resp))
            tail = [t for e, d in events if e == "tokens"
                    for t in d["tokens"]]
            done = [d for e, d in events if e == "done"][0]
            assert tail == full[2:]
            assert done["tokens"] == full
            hz = json.loads(_get(fe.url("/healthz")))
            assert hz["requests_resumed"] == 1
        finally:
            fe.stop()
            eng.journal.shutdown()
        # The tail delivery ACKED: recovery replays nothing.
        eng2 = make_engine(lm, journal_dir=jdir)
        assert eng2.recover()["redelivered"] == []
        eng2.journal.shutdown()

    def test_unknown_uid_falls_through_to_fresh_submit_with_skip(
            self, lm):
        """Resume against a replica that never saw the uid (the
        cross-replica failover path): fresh submit, first K tokens
        suppressed — greedy decoding makes the regenerated stream
        bitwise the original, so the splice is seamless."""
        ref_eng = make_engine(lm)
        ref_eng.submit(PROMPTS[1])
        (fin,) = list(ref_eng.run())
        full = [int(t) for t in fin.tokens]
        fe = ServingFrontend(make_engine(lm)).start()
        try:
            req = urllib.request.Request(
                fe.url("/generate"),
                data=json.dumps({
                    "prompt": [int(t) for t in PROMPTS[1]],
                    "stream": True,
                    "resume": {"uid": 777, "delivered": 3}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60.0) as resp:
                events = list(sse_events(resp))
            tail = [t for e, d in events if e == "tokens"
                    for t in d["tokens"]]
            done = [d for e, d in events if e == "done"][0]
            assert tail == full[3:]   # the head is NOT re-sent
            assert done["tokens"] == full
        finally:
            fe.stop()

    def test_bad_resume_cursor_is_400(self, lm):
        fe = ServingFrontend(make_engine(lm)).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(fe.url("/generate"),
                      {"prompt": [1, 2], "stream": False,
                       "resume": {"uid": "not-an-int"}})
            assert ei.value.code == 400
        finally:
            fe.stop()

    def test_engine_stream_attach_reports_progress(self, lm):
        """Engine-level attach (the live re-attach half of resume):
        returns the tokens emitted so far and arms the stream cursor
        so the listener delivers the rest exactly once."""
        eng = make_engine(lm, max_new_tokens=6)
        r = eng.submit(PROMPTS[2])
        eng.step()  # seat + first chunk
        landed = eng.stream_attach(r.uid)
        assert landed is not None
        got = list(landed)
        eng.set_token_listener(
            lambda uid, toks, fin: got.extend(int(t) for t in toks))
        fins = []
        while not eng.idle:
            fins.extend(eng.step())
        (fin,) = fins
        assert got == [int(t) for t in fin.tokens]
        assert eng.stream_attach(999) is None
        eng.set_token_listener(None)


class TestSeatOrdering:
    """Satellite: cache-aware seat ordering inside the queue."""

    def test_probe_breaks_equal_service_ties(self):
        q = RequestQueue(budget=32)
        ra = q.submit([1, 2, 3], 4, tenant="a", arrival_t=0.0)
        rb = q.submit([4, 5, 6], 4, tenant="b", arrival_t=0.0)
        # No probe: equal service → alphabetical tenant tie-break.
        assert q.next_candidate() is ra
        # Probe says tenant b's head is resident → b seats first.
        probe = (lambda e: 8 if e.tenant == "b" else 0)
        assert q.next_candidate(prefix_probe=probe) is rb
        # Equal residency degenerates to the no-probe order.
        flat = (lambda e: 8)
        assert q.next_candidate(prefix_probe=flat) is ra

    def test_probe_never_reorders_within_a_tenant(self):
        q = RequestQueue(budget=32)
        first = q.submit([1, 2, 3], 4, tenant="a", arrival_t=0.0)
        q.submit([7, 8, 9], 4, tenant="a", arrival_t=0.0)
        # Even when the probe would prefer the SECOND entry, only the
        # tenant's FIFO head is a candidate.
        probe = (lambda e: 16 if e.uid != first.uid else 0)
        assert q.next_candidate(prefix_probe=probe) is first

    def test_probe_never_crosses_fairness_ranks(self):
        q = RequestQueue(budget=32)
        a1 = q.submit([1, 2, 3], 4, tenant="a", arrival_t=0.0)
        b1 = q.submit([4, 5, 6], 4, tenant="b", arrival_t=0.0)
        # Seat a's head: tenant a accrues weighted service.
        assert q.take(a1)
        q.submit([1, 2, 3], 4, tenant="a", arrival_t=0.0)
        # A huge resident prefix on a's next entry must NOT outrank
        # b's lower accumulated service.
        probe = (lambda e: 999 if e.tenant == "a" else 0)
        assert q.next_candidate(prefix_probe=probe) is b1

    def test_cache_off_is_bitwise_neutral(self, lm):
        """With the prefix cache off the engine never passes a probe,
        so the admission schedule — and therefore every token — is
        bitwise the pre-round-22 ordering (two fresh engines agree,
        and the multi-tenant interleave matches the no-probe key)."""
        runs = []
        for _ in range(2):
            eng = make_engine(lm, prefix_cache=False, max_batch=2)
            uids = []
            for i, p in enumerate(PROMPTS):
                eng.submit(p, tenant="ab"[i % 2])
            for f in eng.run():
                uids.append((f.uid, [int(t) for t in f.tokens]))
            runs.append(uids)
        assert runs[0] == runs[1]
