"""Pallas fused Adam vs optax reference (SURVEY.md §4: 'optimizer math vs
optax references'). Runs in interpret mode on the CPU mesh; the same kernel
compiles on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import OptimizerConfig
from distributed_training_tpu.ops.fused_adam import (
    fused_adam,
    fused_adam_kernel_update,
)
from distributed_training_tpu.train.optim import make_optimizer


def test_kernel_matches_optax_adam_single_tensor():
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(300, 7).astype(np.float32))  # non-tile-aligned
    g = jnp.asarray(rng.randn(300, 7).astype(np.float32))
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)

    ref_tx = optax.adam(1e-3)
    ref_state = ref_tx.init(p)
    updates, _ = ref_tx.update(g, ref_state, p)
    ref_p = optax.apply_updates(p, updates)

    new_p, new_m, new_v = fused_adam_kernel_update(
        p, g, m, v, jnp.float32(1e-3), jnp.int32(1), interpret=True)
    np.testing.assert_allclose(np.asarray(new_p), np.asarray(ref_p),
                               atol=1e-6, rtol=1e-6)


def test_transformation_matches_optax_over_steps():
    rng = np.random.RandomState(1)
    params = {
        "w": jnp.asarray(rng.randn(64, 33).astype(np.float32)),
        "b": jnp.asarray(rng.randn(10).astype(np.float32)),
    }
    ref_tx = optax.adam(3e-3, b1=0.8, b2=0.95)
    fus_tx = fused_adam(3e-3, b1=0.8, b2=0.95, interpret=True)
    ref_state = ref_tx.init(params)
    fus_state = fus_tx.init(params)
    ref_p, fus_p = params, params

    for step in range(4):
        g = jax.tree.map(
            lambda x: jnp.asarray(
                rng.randn(*x.shape).astype(np.float32)), params)
        ru, ref_state = ref_tx.update(g, ref_state, ref_p)
        ref_p = optax.apply_updates(ref_p, ru)
        fu, fus_state = fus_tx.update(g, fus_state, fus_p)
        fus_p = optax.apply_updates(fus_p, fu)

    for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(fus_p)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-5, rtol=1e-5)


def test_schedule_callable_supported():
    sched = optax.linear_schedule(0.0, 1e-2, 10)
    tx = fused_adam(sched, interpret=True)
    p = {"w": jnp.ones((8, 8))}
    state = tx.init(p)
    g = {"w": jnp.ones((8, 8))}
    # step 1: lr = sched(1) = 1e-3
    updates, state = tx.update(g, state, p)
    assert int(state.count) == 1
    assert float(jnp.abs(jax.tree.leaves(updates)[0]).max()) > 0


def test_make_optimizer_hybrid_adam_path():
    tx = make_optimizer(OptimizerConfig(name="hybrid_adam", lr=1e-3,
                                        weight_decay=1e-4,
                                        grad_clip_norm=1.0))
    p = {"w": jnp.ones((16, 16))}
    state = tx.init(p)
    g = {"w": jnp.full((16, 16), 2.0)}
    updates, state = tx.update(g, state, p)
    new_p = optax.apply_updates(p, updates)
    assert np.isfinite(np.asarray(jax.tree.leaves(new_p)[0])).all()
    # clip(1.0) scales the grad well below 2.0; update magnitude ≈ lr.
    assert float(jnp.abs(jax.tree.leaves(updates)[0]).max()) < 5e-3


@pytest.mark.parametrize("shape", [(1,), (8, 128), (8 * 128 * 32,),
                                   (5, 3, 2)])
def test_kernel_handles_any_shape(shape):
    rng = np.random.RandomState(2)
    p = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    z = jnp.zeros_like(p)
    new_p, new_m, new_v = fused_adam_kernel_update(
        p, g, z, z, jnp.float32(1e-3), jnp.int32(1), interpret=True)
    assert new_p.shape == shape
    assert np.isfinite(np.asarray(new_p)).all()
