"""Generation tests: KV-cache decode correctness + sampling transforms.

The load-bearing property is *cache equivalence*: decode-mode forwards
(chunked prefill + one-token steps against the KV cache) must produce the
same logits as the ordinary full-sequence causal forward. Everything else
(sampling filters, EOS handling) is unit-tested directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.inference import (
    Generator,
    SampleConfig,
    apply_top_k,
    apply_top_p,
    sample_token,
)
from distributed_training_tpu.models import get_model

VOCAB = 61  # deliberately not a power of two


@pytest.fixture(scope="module")
def lm():
    # head_bias=True: several tests force an argmax by construction by
    # adding a large lm_head bias (the model default is bias-less since
    # round 5, GPT-2 parity).
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=2, num_heads=2,
        hidden_dim=32, max_len=64, head_bias=True)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params


def full_logits(model, params, tokens):
    return model.apply({"params": params}, tokens, train=False)


class TestCacheEquivalence:
    def test_prefill_matches_full_forward(self, lm):
        model, params = lm
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, VOCAB)
        ref = full_logits(model, params, tokens)
        positions = jnp.broadcast_to(jnp.arange(16), (2, 16))
        got, _ = model.apply(
            {"params": params}, tokens, positions=positions,
            train=False, decode=True, mutable=["cache"])
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_stepwise_decode_matches_full_forward(self, lm):
        """Prefill 10 tokens, then 6 single-token steps == one 16-forward."""
        model, params = lm
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, VOCAB)
        ref = full_logits(model, params, tokens)

        positions = jnp.broadcast_to(jnp.arange(10), (2, 10))
        logits, vars_out = model.apply(
            {"params": params}, tokens[:, :10], positions=positions,
            train=False, decode=True, mutable=["cache"])
        np.testing.assert_allclose(logits, ref[:, :10], rtol=2e-5, atol=2e-5)
        cache = vars_out["cache"]
        for t in range(10, 16):
            pos = jnp.full((2, 1), t, jnp.int32)
            logits, vars_out = model.apply(
                {"params": params, "cache": cache}, tokens[:, t:t + 1],
                positions=pos, train=False, decode=True, mutable=["cache"])
            cache = vars_out["cache"]
            np.testing.assert_allclose(
                logits[:, 0], ref[:, t], rtol=2e-5, atol=2e-5)

    def test_greedy_generation_matches_naive_rollout(self, lm):
        """Cached greedy decode == re-running the full forward every step."""
        model, params = lm
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, VOCAB)
        gen = Generator(model, params, SampleConfig(
            max_new_tokens=8, temperature=0.0))
        got = gen(prompt)

        seq = prompt
        for _ in range(8):
            logits = full_logits(model, params, seq)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, np.asarray(seq[:, 5:]))


class TestGenerator:
    def test_1d_prompt_and_shapes(self, lm):
        model, params = lm
        out = Generator(model, params, SampleConfig(max_new_tokens=4))(
            np.array([1, 2, 3]))
        assert out.shape == (1, 4)
        assert out.dtype == np.int32
        assert ((0 <= out) & (out < VOCAB)).all()

    def test_cache_overflow_rejected(self, lm):
        model, params = lm
        gen = Generator(model, params, SampleConfig(max_new_tokens=60))
        with pytest.raises(ValueError, match="exceeds the KV cache"):
            gen(np.zeros((1, 10), np.int32))

    def test_seq_axis_model_rejected(self):
        model = get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis="sequence",
            num_layers=1, num_heads=2, hidden_dim=16, max_len=32)
        with pytest.raises(ValueError, match="seq_axis=None"):
            Generator(model, {}, SampleConfig())

    def test_eos_pads_tail(self, lm):
        """Force EOS as the argmax by construction: bias the lm_head."""
        model, params = lm
        eos = 7
        biased = jax.tree.map(lambda x: x, params)  # shallow copy
        head = dict(biased["lm_head"])
        head["bias"] = head["bias"].at[eos].add(1e4)
        biased = dict(biased)
        biased["lm_head"] = head
        gen = Generator(model, biased, SampleConfig(
            max_new_tokens=6, temperature=0.0, eos_id=eos, pad_id=0))
        out = gen(np.array([[1, 2]]))
        # First emission is EOS (it is the argmax everywhere); rest is pad.
        assert out[0, 0] == eos
        assert (out[0, 1:] == 0).all()

    def test_moe_model_decode_matches_full_forward(self):
        """MoE FFNs run position-wise in decode; cache equivalence holds."""
        model = get_model(
            "transformer_lm", num_classes=VOCAB, num_layers=2, num_heads=2,
            hidden_dim=32, max_len=32, moe_num_experts=4, moe_top_k=2)
        tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, VOCAB)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        ref = model.apply({"params": params}, tokens, train=False)
        positions = jnp.broadcast_to(jnp.arange(8), (2, 8))
        got, _ = model.apply(
            {"params": params}, tokens, positions=positions,
            train=False, decode=True, mutable=["cache"])
        # MoE capacity dispatch sees different token sets per call shape, so
        # only the dense-block positions are bit-comparable; loose tolerance
        # still pins the wiring (garbage cache → order-of-magnitude error).
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_decode_past_cache_end_is_loud(self, lm):
        """Steps beyond max_len must NaN-poison, not silently clamp."""
        model, params = lm
        tokens = jax.random.randint(
            jax.random.PRNGKey(8), (1, model.max_len), 0, VOCAB)
        positions = jnp.broadcast_to(
            jnp.arange(model.max_len), (1, model.max_len))
        _, vars_out = model.apply(
            {"params": params}, tokens, positions=positions,
            train=False, decode=True, mutable=["cache"])
        logits, _ = model.apply(
            {"params": params, "cache": vars_out["cache"]},
            tokens[:, :1], positions=jnp.full((1, 1), model.max_len),
            train=False, decode=True, mutable=["cache"])
        assert np.isnan(np.asarray(logits)).all()

    def test_chunk_straddling_cache_end_is_loud(self, lm):
        """A multi-token chunk that overflows poisons the WHOLE call: the
        clamped write corrupts history, so even in-bounds rows are wrong."""
        model, params = lm
        n = model.max_len - 2
        tokens = jax.random.randint(jax.random.PRNGKey(9), (1, n), 0, VOCAB)
        _, vars_out = model.apply(
            {"params": params}, tokens,
            positions=jnp.broadcast_to(jnp.arange(n), (1, n)),
            train=False, decode=True, mutable=["cache"])
        chunk = jax.random.randint(jax.random.PRNGKey(10), (1, 4), 0, VOCAB)
        logits, _ = model.apply(
            {"params": params, "cache": vars_out["cache"]}, chunk,
            positions=jnp.broadcast_to(n + jnp.arange(4), (1, 4)),
            train=False, decode=True, mutable=["cache"])
        assert np.isnan(np.asarray(logits)).all()

    def test_cache_len_beyond_pos_table_rejected(self, lm):
        model, params = lm
        big = model.clone(cache_len=model.max_len + 8)
        with pytest.raises(ValueError, match="exceeds the positional table"):
            big.apply({"params": params}, jnp.zeros((1, 1), jnp.int32),
                      positions=jnp.zeros((1, 1), jnp.int32),
                      train=False, decode=True, mutable=["cache"])

    def test_single_new_token(self, lm):
        """max_new_tokens=1 is the scan-length-0 edge of the decode loop."""
        model, params = lm
        gen = Generator(model, params, SampleConfig(
            max_new_tokens=1, temperature=0.0))
        prompt = np.array([[1, 2, 3]])
        out = gen(prompt)
        ref = jnp.argmax(full_logits(model, params, jnp.asarray(prompt))[:, -1],
                         axis=-1)
        np.testing.assert_array_equal(out[:, 0], np.asarray(ref))

    def test_default_rng_varies_per_call(self, lm):
        model, params = lm
        gen = Generator(model, params, SampleConfig(
            max_new_tokens=6, temperature=1.0))
        a = gen(np.array([[1, 2, 3]]))
        b = gen(np.array([[1, 2, 3]]))
        assert (a != b).any()

    def test_sampled_generation_deterministic_under_rng(self, lm):
        model, params = lm
        gen = Generator(model, params, SampleConfig(
            max_new_tokens=6, temperature=1.0, top_k=10))
        a = gen(np.array([[1, 2, 3]]), rng=jax.random.PRNGKey(5))
        b = gen(np.array([[1, 2, 3]]), rng=jax.random.PRNGKey(5))
        c = gen(np.array([[1, 2, 3]]), rng=jax.random.PRNGKey(6))
        np.testing.assert_array_equal(a, b)
        assert (a != c).any()  # 61^6 sequences; collision ≈ impossible


class TestSamplingTransforms:
    def test_top_k_keeps_k(self):
        logits = jnp.asarray([[1.0, 3.0, 2.0, 0.0, -1.0]])
        out = apply_top_k(logits, 2)
        np.testing.assert_array_equal(
            np.isfinite(np.asarray(out))[0], [False, True, True, False, False])

    def test_top_k_ties_keep_at_least_k(self):
        logits = jnp.asarray([[2.0, 2.0, 2.0, 0.0]])
        assert int(np.isfinite(np.asarray(apply_top_k(logits, 2))).sum()) >= 2

    def test_top_p_nucleus(self):
        # probs ≈ [0.643, 0.237, 0.087, 0.032] — p=0.8 keeps the first two
        # (exclusive cumsum at rank2 = 0.88 >= 0.8).
        logits = jnp.log(jnp.asarray([[0.643, 0.237, 0.087, 0.032]]))
        out = apply_top_p(logits, 0.8)
        np.testing.assert_array_equal(
            np.isfinite(np.asarray(out))[0], [True, True, False, False])

    def test_top_p_always_keeps_argmax(self):
        logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]])
        out = apply_top_p(logits, 0.01)
        finite = np.isfinite(np.asarray(out))[0]
        assert finite[0] and finite.sum() == 1

    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.0, 5.0, 1.0], [9.0, 0.0, 1.0]])
        out = sample_token(
            jax.random.PRNGKey(0), logits, SampleConfig(temperature=0.0))
        np.testing.assert_array_equal(np.asarray(out), [1, 0])

    def test_filtered_sampling_stays_in_support(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(4, 32), jnp.float32)
        cfg = SampleConfig(temperature=0.7, top_k=4)
        allowed = np.argsort(np.asarray(logits), axis=-1)[:, -4:]
        for seed in range(5):
            toks = np.asarray(
                sample_token(jax.random.PRNGKey(seed), logits, cfg))
            for b in range(4):
                assert toks[b] in allowed[b]

    def test_invalid_args_rejected(self):
        logits = jnp.zeros((1, 4))
        with pytest.raises(ValueError):
            apply_top_k(logits, 0)
        with pytest.raises(ValueError):
            apply_top_p(logits, 0.0)
        with pytest.raises(ValueError):
            apply_top_p(logits, 1.5)
        with pytest.raises(ValueError, match="temperature"):
            SampleConfig(temperature=-1.0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            SampleConfig(max_new_tokens=0)


class TestGenerateCliEmaRestore:
    def test_ema_checkpoint_restores_and_samples(self, tmp_path, monkeypatch,
                                                 capsys):
        """An --ema-decay training run saves an EmaState-wrapped opt_state;
        generate.py must mirror the flag so the restore template matches,
        and --use-ema must sample from the EMA average (ADVICE r1)."""
        from conftest import load_cli_module

        from distributed_training_tpu import checkpoint as ckpt_lib
        from distributed_training_tpu.config import (
            OptimizerConfig,
            PrecisionConfig,
            SchedulerConfig,
        )
        from distributed_training_tpu.train.optim import make_optimizer
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import init_train_state

        model = get_model("transformer_lm", num_classes=256, num_layers=1,
                          num_heads=2, hidden_dim=32, max_len=64)
        tx = make_optimizer(OptimizerConfig(ema_decay=0.9),
                            SchedulerConfig(), world_size=1)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (1, 8), tx,
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)
        ckpt_lib.save_checkpoint(str(tmp_path), 0, state)

        gen_cli = load_cli_module("gpt/jax_tpu/generate.py")
        monkeypatch.setattr("sys.argv", [
            "generate.py", "-c", str(tmp_path), "--prompt", "ab",
            "--num-layers", "1", "--num-heads", "2", "--hidden-dim", "32",
            "--max-len", "64", "--max-new-tokens", "4",
            "--temperature", "0", "--ema-decay", "0.9", "--use-ema"])
        assert gen_cli.main() == 0
        out = capsys.readouterr().out
        assert "restored epoch 0" in out
        assert "EMA parameter average" in out

    def test_use_ema_without_decay_refuses(self, tmp_path, monkeypatch):
        from conftest import load_cli_module

        gen_cli = load_cli_module("gpt/jax_tpu/generate.py")
        monkeypatch.setattr("sys.argv", [
            "generate.py", "-c", str(tmp_path), "--use-ema"])
        with pytest.raises(SystemExit, match="ema-decay"):
            gen_cli.main()
