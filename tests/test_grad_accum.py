"""Gradient-accumulation tests (DeepSpeed ``gradient_accumulation_steps``).

Core property: accumulating A microbatches and applying one update on the
averaged gradient is mathematically identical to one update on the full
effective batch — exactly checkable on BN-free models (BatchNorm computes
per-microbatch statistics by design, matching torch semantics, so ResNet is
checked for EMA-threading behavior rather than bit equality).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import (
    PrecisionConfig,
    TrainConfig,
    from_ds_config,
)
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state, state_shardings
from distributed_training_tpu.train.lm_step import (
    make_lm_batch,
    make_tp_lm_train_step,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.step import make_train_step, microbatches
from distributed_training_tpu.train.train_state import init_train_state


def _image_state(mesh, model_name="vit_b16", **kw):
    model = get_model(model_name, num_classes=10, **kw)
    tx = optax.adam(1e-3)
    state = init_train_state(
        model, jax.random.PRNGKey(0), (8, 16, 16, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    return place_state(state, state_shardings(state, mesh, 0))


def _image_batch(n):
    rng = np.random.RandomState(0)
    return {
        "image": jnp.asarray(rng.rand(n, 16, 16, 3), jnp.float32),
        "label": jnp.asarray(rng.randint(0, 10, n), jnp.int32),
    }


class TestMicrobatches:
    def test_reshape(self, mesh):
        batch = _image_batch(16)
        mb = microbatches(batch, 4)
        assert mb["image"].shape == (4, 4, 16, 16, 3)
        assert mb["label"].shape == (4, 4)
        np.testing.assert_array_equal(
            np.asarray(mb["image"]).reshape(16, 16, 16, 3),
            np.asarray(batch["image"]))

    def test_indivisible_rejected(self, mesh):
        with pytest.raises(ValueError, match="not divisible"):
            microbatches(_image_batch(10), 4)


class TestImageAccumEquivalence:
    def test_accum_matches_single_batch(self, mesh):
        """ViT (BN-free): accum=4 over 32 == one step of 32, elementwise."""
        kw = dict(hidden_size=32, num_layers=1, num_heads=2, mlp_dim=64,
                  patch_size=8, dropout_rate=0.0)
        batch = _image_batch(32)
        rng = jax.random.PRNGKey(7)

        ref_state = _image_state(mesh, **kw)
        ref_step = make_train_step(mesh, donate=False)
        ref_state, ref_metrics = ref_step(ref_state, batch, rng)

        acc_state = _image_state(mesh, **kw)
        acc_step = make_train_step(mesh, donate=False, grad_accum_steps=4)
        acc_state, acc_metrics = acc_step(acc_state, batch, rng)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            jax.device_get(ref_state.params), jax.device_get(acc_state.params))
        np.testing.assert_allclose(
            float(acc_metrics["loss"]), float(ref_metrics["loss"]),
            rtol=1e-5)
        np.testing.assert_allclose(
            float(acc_metrics["accuracy"]), float(ref_metrics["accuracy"]),
            rtol=1e-6)

    def test_resnet_bn_stats_thread_through_microbatches(self, mesh):
        """BN EMA must tick once per microbatch (torch grad-accum semantics):
        accum=2 applies momentum twice, differing from the single-batch EMA."""
        batch = _image_batch(16)
        rng = jax.random.PRNGKey(3)

        one_state = _image_state(mesh, model_name="resnet_micro", stem="cifar")
        one_step = make_train_step(mesh, donate=False)
        one_state, _ = one_step(one_state, batch, rng)

        acc_state = _image_state(mesh, model_name="resnet_micro", stem="cifar")
        acc_step = make_train_step(mesh, donate=False, grad_accum_steps=2)
        acc_state, m = acc_step(acc_state, batch, rng)

        assert np.isfinite(float(m["loss"]))
        # Stats updated (changed from init)...
        init_stats = jax.device_get(
            _image_state(mesh, model_name="resnet_micro", stem="cifar").batch_stats)
        got = jax.device_get(acc_state.batch_stats)
        changed = jax.tree.leaves(
            jax.tree.map(lambda a, b: float(np.abs(a - b).max()), init_stats, got))
        assert max(changed) > 0
        # ...and by a double EMA tick, not the single-batch one.
        single = jax.device_get(one_state.batch_stats)
        diff = jax.tree.leaves(
            jax.tree.map(lambda a, b: float(np.abs(a - b).max()), single, got))
        assert max(diff) > 0

    def test_fp16_loss_scaling_composes(self, mesh):
        """Scaled grads sum/unscale correctly; scale stays finite-stepped."""
        kw = dict(hidden_size=32, num_layers=1, num_heads=2, mlp_dim=64,
                  patch_size=8, dropout_rate=0.0)
        model = get_model("vit_b16", num_classes=10, **kw)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (8, 16, 16, 3), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp16")))
        state = place_state(state, state_shardings(state, mesh, 0))
        step = make_train_step(mesh, donate=False, grad_accum_steps=2)
        state, m = step(state, _image_batch(16), jax.random.PRNGKey(1))
        assert float(m["grads_finite"]) == 1.0
        assert np.isfinite(float(m["loss"]))


class TestLMAccumEquivalence:
    def test_tp_step_accum_matches_single_batch(self, mesh):
        model = get_model(
            "transformer_lm", num_classes=32, seq_axis=None,
            num_layers=2, num_heads=2, hidden_dim=32, max_len=64)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (8, 17)), jnp.int32)
        batch = make_lm_batch(tokens)
        rng = jax.random.PRNGKey(5)

        tx = optax.adam(1e-3)

        def mk_state():
            return init_train_state(
                model, jax.random.PRNGKey(0), (2, 8), tx,
                loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
                input_dtype=jnp.int32)

        ref_step = make_tp_lm_train_step(mesh, model=model, donate=False)
        state = mk_state()
        ref_state = place_state(state, ref_step.state_shardings(state))
        ref_state, ref_m = ref_step(ref_state, batch, rng)

        acc_step = make_tp_lm_train_step(
            mesh, model=model, donate=False, grad_accum_steps=4)
        state = mk_state()
        acc_state = place_state(state, acc_step.state_shardings(state))
        acc_state, acc_m = acc_step(acc_state, batch, rng)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            jax.device_get(ref_state.params), jax.device_get(acc_state.params))
        np.testing.assert_allclose(
            float(acc_m["perplexity"]), float(ref_m["perplexity"]), rtol=1e-5)


class TestConfigPlumbing:
    def test_ds_config_ingests_accum(self):
        cfg = from_ds_config({"gradient_accumulation_steps": 8})
        assert cfg.gradient_accumulation_steps == 8

    def test_default_is_one(self):
        assert TrainConfig().gradient_accumulation_steps == 1

    def test_effective_batch_derives_accum_ds_style(self):
        """train_batch_size = micro × world × 4 → accum derived as 4."""
        from distributed_training_tpu.config import (
            DataConfig,
            effective_batch_sizes,
        )

        cfg = TrainConfig(
            data=DataConfig(batch_size=16, global_batch_size=512))
        train_gbs, eval_gbs, accum = effective_batch_sizes(cfg, world=8)
        assert (train_gbs, eval_gbs, accum) == (512, 128, 4)

    def test_effective_batch_explicit_accum_validated(self):
        from distributed_training_tpu.config import (
            DataConfig,
            effective_batch_sizes,
        )

        cfg = TrainConfig(
            gradient_accumulation_steps=5,
            data=DataConfig(batch_size=16, global_batch_size=512))
        with pytest.raises(ValueError, match="not divisible"):
            effective_batch_sizes(cfg, world=8)

    def test_allow_derive_false_keeps_one_step(self):
        """Steps that can't accumulate (shard_map local BN, seq/pipe LM)
        keep the whole global batch as one step instead of erroring."""
        from distributed_training_tpu.config import (
            DataConfig,
            effective_batch_sizes,
        )

        cfg = TrainConfig(
            data=DataConfig(batch_size=16, global_batch_size=512))
        assert effective_batch_sizes(cfg, 8, allow_derive=False) == (
            512, 512, 1)

    def test_effective_batch_non_multiple_global_wins(self):
        """The reference's ds_config (train_batch_size=96, default micro):
        a non-multiple global batch overrides with accum 1."""
        from distributed_training_tpu.config import (
            DataConfig,
            effective_batch_sizes,
        )

        cfg = TrainConfig(data=DataConfig(batch_size=100, global_batch_size=96))
        assert effective_batch_sizes(cfg, world=8) == (96, 96, 1)

    def test_trainer_scales_loader_batch(self, mesh):
        from distributed_training_tpu.config import DataConfig
        from distributed_training_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="resnet_micro",
            gradient_accumulation_steps=2,
            data=DataConfig(dataset="synthetic_cifar", batch_size=4),
        )
        tr = Trainer(cfg, mesh=mesh)
        train_loader, eval_loader = tr.make_loaders()
        # 4/device × 8 devices × accum 2 = 64 effective; eval stays micro.
        assert train_loader.global_batch_size == 64
        assert eval_loader.global_batch_size == 32

    def test_lm_trainer_eval_loader_stays_micro(self, mesh):
        from distributed_training_tpu.config import DataConfig, LMConfig
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TrainConfig(
            model="transformer_lm",
            gradient_accumulation_steps=4,
            data=DataConfig(batch_size=4),
            lm=LMConfig(seq_len=16, vocab_size=32, num_layers=1, num_heads=2,
                        hidden_dim=16, max_len=32, eval_sequences=64),
        )
        tr = LMTrainer(cfg, mesh=mesh)
        train_loader, eval_loader = tr.make_loaders()
        assert train_loader.global_batch_size == 128
        # Micro-sized eval: 64 eval sequences still yield batches (the
        # accum-scaled 128 would have yielded zero and raised).
        assert eval_loader.global_batch_size == 32
        assert len(eval_loader) > 0

    def test_local_bn_accepts_accum(self, mesh):
        # Round 2: the shard_map local-BN step accumulates (shard-local
        # microbatch scan + one pmean); the old rejection is gone.
        from distributed_training_tpu.config import DataConfig
        from distributed_training_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="resnet_micro",
            sync_batchnorm=False,
            gradient_accumulation_steps=2,
            data=DataConfig(dataset="synthetic_cifar", batch_size=4),
        )
        trainer = Trainer(cfg, mesh=mesh)
        assert trainer.grad_accum == 2


class TestShardMapAccumEquivalence:
    def test_local_bn_accum_matches_single_shot(self, mesh):
        """Round-2 composition: the explicit shard_map (local-BN) step
        accumulates too — shard-local microbatch scan, ONE pmean, one
        update. accum=2 on the effective batch == single-shot, checked
        strictly on a BN-free model (ViT): BatchNorm computes
        per-microbatch statistics by design (torch semantics), so a BN
        model's losses legitimately differ — its accum path is covered by
        test_trainer_local_bn_accum below."""
        from distributed_training_tpu.train.step import (
            make_shard_map_train_step,
        )

        def state():
            model = get_model("vit_b16", num_classes=10, hidden_size=32,
                              num_layers=1, num_heads=2, mlp_dim=64,
                              patch_size=8, dropout_rate=0.0)
            tx = optax.sgd(1e-2, momentum=0.9)
            s = init_train_state(
                model, jax.random.PRNGKey(0), (2, 16, 16, 3), tx,
                loss_scale=LossScaleState.create(
                    PrecisionConfig(dtype="fp32")))
            return place_state(s, state_shardings(s, mesh, 0))

        batch = _image_batch(32)
        rng = jax.random.PRNGKey(7)

        one = make_shard_map_train_step(mesh, donate=False)
        acc = make_shard_map_train_step(mesh, donate=False,
                                        grad_accum_steps=2)
        s1, m1 = one(state(), batch, rng)
        s2, m2 = acc(state(), batch, rng)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6),
            jax.device_get(s1.params), jax.device_get(s2.params))
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-6)

    def test_trainer_local_bn_accum(self, tmp_path):
        """Trainer accepts accumulation on the local-BN path now."""
        from distributed_training_tpu import Trainer
        from distributed_training_tpu.config import DataConfig

        cfg = TrainConfig.from_plugin("torch_ddp").replace(
            model="resnet_micro", num_epochs=1, log_interval=2,
            eval_every=0, sync_batchnorm=False,
            gradient_accumulation_steps=2,
            data=DataConfig(dataset="synthetic_cifar", batch_size=4,
                            max_steps_per_epoch=3))
        trainer = Trainer(cfg)
        train_loader, _ = trainer.make_loaders()
        metrics = trainer.train_epoch(0, train_loader)
        assert metrics["grads_finite"] == 1.0
        assert np.isfinite(metrics["loss"])


class TestPipelineAccum:
    """PP × gradient accumulation (round 5): DeepSpeed's pipeline engine
    equates accumulation with microbatching, so the trainer maps
    ``gradient_accumulation_steps`` onto the schedule's microbatch count
    (num_microbatches × accum, each microbatch keeping its shape) instead
    of refusing."""

    def _cfg(self, batch_size, microbatches, accum):
        from distributed_training_tpu.config import (
            DataConfig,
            LMConfig,
            MeshSpec,
        )

        return TrainConfig(
            model="transformer_lm", num_epochs=1,
            gradient_accumulation_steps=accum,
            mesh=MeshSpec(data=-1, pipe=2),
            data=DataConfig(batch_size=batch_size, max_steps_per_epoch=2),
            lm=LMConfig(seq_len=16, vocab_size=32, num_layers=2,
                        num_heads=2, hidden_dim=16, max_len=32,
                        num_microbatches=microbatches,
                        train_sequences=64, eval_sequences=32),
        )

    def test_accum_equals_explicit_microbatches(self, devices):
        """accum=2 × microbatches=2 builds the same schedule as
        accum=1 × microbatches=4 at the same effective batch, and one
        train step produces identical params on identical data — the
        effective-batch math pin."""
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        ta = LMTrainer(self._cfg(batch_size=4, microbatches=2, accum=2))
        tb = LMTrainer(self._cfg(batch_size=8, microbatches=4, accum=1))
        assert ta._pp_microbatches == tb._pp_microbatches == 4
        assert ta.train_gbs == tb.train_gbs  # micro × accum × world

        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 32, (ta.train_gbs, 17)),
            jnp.int32)
        batch = make_lm_batch(toks)
        rng = jax.random.PRNGKey(7)
        sa, ma = ta.train_step(ta.state, batch, rng)
        sb, mb = tb.train_step(tb.state, batch, rng)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6),
            jax.device_get(sa.params), jax.device_get(sb.params))
        np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                                   rtol=1e-6)

    def test_pp_accum_indivisible_batch_refused_at_init(self, devices):
        """batch_size must divide by microbatches × accum — eval runs
        micro-sized batches through the SAME scaled schedule, so an
        indivisible config would train a full epoch then crash in eval
        (caught by review, round 5)."""
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        with pytest.raises(ValueError, match="microbatch count"):
            LMTrainer(self._cfg(batch_size=4, microbatches=2, accum=4))

    def test_pp_accum_fit(self, devices):
        """End-to-end: a PP run with gradient_accumulation_steps trains."""
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        result = LMTrainer(self._cfg(4, 2, 2)).fit()
        assert np.isfinite(result["final_perplexity"])
