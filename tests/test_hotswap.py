"""Live weight hot-swap tests (serving/hotswap.py + engine barrier).

Load-bearing properties, in order of importance:

1. **Determinism**: two engines fed the same requests with the swap
   forced at the same iteration produce bitwise-identical outputs —
   the swap is a pure params substitution at a boundary, nothing else
   moves. With no swap armed, the greedy oracle (sequential Generator
   equivalence) is untouched.
2. **Refusal safety**: a torn/corrupt candidate is quarantined and the
   engine keeps serving its old weights (typed ``SwapError`` +
   ``swaps_rejected``); I/O faults mid-staging and tree mismatches are
   rejected the same way. An UNCOMMITTED dir is invisible (it may be a
   save still in flight — quarantining it would destroy good bytes).
3. **Attribution**: the barrier pause lands in ``swap_blocked_s``, is
   compensated out of in-flight requests' TPOT, and its iteration delta
   is gap-excluded from the decode step-time percentiles — pinned the
   way ``admission_blocked_s`` is.
4. **Resource hygiene**: a swap under 2×+ page-pool oversubscription
   leaves the allocator balanced (no leak, no stranded commitment).

The fixtures share one tiny compiled model; swaps never retrace (same
shapes/dtypes), so the per-test cost is host logic, not XLA.
"""

import json
import os
import time
import urllib.request

import jax
import numpy as np
import pytest

from distributed_training_tpu import checkpoint as ckpt_lib
from distributed_training_tpu.config import ChaosConfig, ServeConfig
from distributed_training_tpu.inference import Generator, SampleConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.resilience import chaos as chaos_lib
from distributed_training_tpu.resilience.chaos import (
    ChaosMonkey,
    corrupt_committed_checkpoint,
    tear_checkpoint,
)
from distributed_training_tpu.serving import (
    Engine,
    HotSwapper,
    SwapError,
    committed_epochs,
)

VOCAB = 61
MAX_LEN = 64
N_NEW = 6
PROMPT_LENS = [3, 5, 9, 5]


@pytest.fixture(scope="module")
def lm():
    model = get_model("transformer_lm", num_classes=VOCAB, num_layers=2,
                      num_heads=2, hidden_dim=32, max_len=MAX_LEN)
    p1 = model.init(jax.random.PRNGKey(0),
                    np.zeros((2, 16), np.int32))["params"]
    p2 = model.init(jax.random.PRNGKey(1),
                    np.zeros((2, 16), np.int32))["params"]
    return model, p1, p2


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(1)
    return [rng.randint(0, VOCAB, size=l).astype(np.int32)
            for l in PROMPT_LENS]


def _run(model, params, prompts, *, swap_at=None, swap_params=None,
         swap_epoch=7, **cfg_kw):
    """Drive one engine over ``prompts``, optionally arming a swap
    before iteration ``swap_at``; returns (engine, {uid: tokens})."""
    eng = Engine(model, params, ServeConfig(
        max_batch=2, max_new_tokens=N_NEW, **cfg_kw))
    for p in prompts:
        eng.submit(p)
    done, it = [], 0
    while not eng.idle:
        if swap_at is not None and it == swap_at:
            eng.arm_swap(swap_params, epoch=swap_epoch)
        done.extend(eng.step())
        it += 1
    assert len(done) == len(prompts)
    return eng, {f.uid: f for f in done}


class TestSwapDeterminism:
    def test_swap_at_iteration_k_bitwise_across_runs(self, lm, prompts):
        """Acceptance: same requests + swap forced at the same iteration
        ⇒ bitwise-identical outputs on both runs — and the swap really
        changed the weights (outputs differ from the no-swap run)."""
        model, p1, p2 = lm
        _, base = _run(model, p1, prompts)
        ea, a = _run(model, p1, prompts, swap_at=3, swap_params=p2)
        _, b = _run(model, p1, prompts, swap_at=3, swap_params=p2)
        for uid in a:
            np.testing.assert_array_equal(a[uid].tokens, b[uid].tokens)
        assert any((a[u].tokens != base[u].tokens).any() for u in a), \
            "swap to different weights changed no output token"
        stats = ea.stats()
        assert stats["swaps_completed"] == 1
        assert stats["weights_epoch"] == 7
        assert stats["swaps_rejected"] == 0

    def test_no_swap_greedy_oracle_untouched(self, lm, prompts, tmp_path):
        """A watcher attached to an empty directory (polling mid-run)
        must not perturb a single token: greedy stays identical to the
        sequential Generator."""
        model, p1, _ = lm
        eng = Engine(model, p1, ServeConfig(max_batch=2,
                                            max_new_tokens=N_NEW))
        swapper = HotSwapper(eng, str(tmp_path / "empty"),
                             lambda e: None, printer=lambda m: None)
        for p in prompts:
            eng.submit(p)
        done = []
        while not eng.idle:
            assert swapper.poll_once() is None
            done.extend(eng.step())
        by_uid = {f.uid: f for f in done}
        gen = Generator(model, p1, SampleConfig(max_new_tokens=N_NEW,
                                                temperature=0.0))
        for uid, p in enumerate(prompts):
            np.testing.assert_array_equal(by_uid[uid].tokens, gen(p)[0])
        assert eng.stats()["swaps_completed"] == 0
        assert eng.weights_epoch == -1

    def test_swap_under_pool_oversubscription_leak_free(self, lm,
                                                        prompts):
        """Swap mid-flight with the pool at 2×+ oversubscription (3
        pages serve one request's commitment at a time): every request
        completes, tokens are deterministic across two runs, and the
        allocator drains balanced — no page leak, no stranded
        commitment."""
        model, p1, p2 = lm
        ea, a = _run(model, p1, prompts * 2, swap_at=4, swap_params=p2,
                     kv_pages=3)
        eb, b = _run(model, p1, prompts * 2, swap_at=4, swap_params=p2,
                     kv_pages=3)
        for uid in a:
            np.testing.assert_array_equal(a[uid].tokens, b[uid].tokens)
            assert a[uid].tokens.size == N_NEW
        ea.pool.check_balanced()
        eb.pool.check_balanced()
        assert ea.stats()["swaps_completed"] == 1


class TestRefusalSafety:
    def test_torn_candidate_quarantined_engine_unharmed(self, lm,
                                                        prompts,
                                                        tmp_path):
        """Tear-after-commit: the candidate carries a COMMITTED marker
        but fails the checksum pass — the watcher quarantines it, the
        engine keeps serving the old weights, and the rejection is a
        typed SwapError counted in swaps_rejected."""
        model, p1, p2 = lm
        watch = str(tmp_path / "ckpt")
        eng = Engine(model, p1, ServeConfig(max_batch=2,
                                            max_new_tokens=N_NEW))
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        corrupt_committed_checkpoint(os.path.join(watch, "epoch_1"))
        assert swapper.poll_once() is None
        with pytest.raises(SwapError, match="verification"):
            # the quarantine already happened; re-dropping the same
            # fault re-raises through raise_on_error for the caller
            ckpt_lib.save_checkpoint(
                watch, 2, {"x": np.arange(64, dtype=np.float32)})
            corrupt_committed_checkpoint(os.path.join(watch, "epoch_2"))
            swapper.poll_once(raise_on_error=True)
        assert os.path.isdir(os.path.join(watch, "epoch_1.corrupt"))
        assert os.path.isdir(os.path.join(watch, "epoch_2.corrupt"))
        err = eng.last_swap_error
        assert isinstance(err, SwapError) and err.stage == "verify"
        assert err.epoch == 2
        stats = eng.stats()
        assert stats["swaps_rejected"] == 2
        assert stats["swaps_completed"] == 0
        assert eng.weights_epoch == -1
        # The engine still serves (old weights) after the refusals.
        _, by_uid = _run(model, p1, prompts[:1])
        eng.submit(prompts[0])
        done = eng.run()
        np.testing.assert_array_equal(done[0].tokens, by_uid[0].tokens)

    def test_quarantined_epoch_redropped_good_deploys(self, lm,
                                                      tmp_path):
        """A quarantine is a verdict on BYTES, not on the epoch number:
        after a torn epoch_1 is renamed to epoch_1.corrupt, a fresh
        valid epoch_1 dropped later is a new candidate and deploys —
        the blacklist only pins epochs whose bad dir is still visible
        (quarantine disabled or the rename failed)."""
        model, p1, p2 = lm
        watch = str(tmp_path / "ckpt")
        eng = Engine(model, p1, ServeConfig(max_batch=1,
                                            max_new_tokens=2))
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        corrupt_committed_checkpoint(os.path.join(watch, "epoch_1"))
        assert swapper.poll_once() is None
        assert os.path.isdir(os.path.join(watch, "epoch_1.corrupt"))
        # The re-drop (e.g. the trainer re-saving the epoch after the
        # first copy bit-rotted in transit).
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        assert swapper.poll_once() == 1
        eng.submit(np.arange(3, dtype=np.int32))
        eng.run()
        assert eng.weights_epoch == 1
        assert eng.stats()["swaps_rejected"] == 1

    def test_uncommitted_candidate_invisible_not_quarantined(self, lm,
                                                             tmp_path):
        """A torn UNCOMMITTED dir is a save that may still be flushing:
        the swap plane must neither deploy nor quarantine it (the
        trainer-side fallback owns dead saves)."""
        model, p1, p2 = lm
        watch = str(tmp_path / "ckpt")
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        tear_checkpoint(os.path.join(watch, "epoch_1"))
        assert committed_epochs(watch) == []
        eng = Engine(model, p1, ServeConfig(max_batch=1))
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        assert swapper.poll_once() is None
        assert eng.stats()["swaps_rejected"] == 0
        assert os.path.isdir(os.path.join(watch, "epoch_1"))

    def test_staging_io_fault_rejected_then_next_poll_succeeds(
            self, lm, tmp_path):
        """Chaos staging-read fault (swap_error_rate=1): the attempt is
        rejected with stage='stage' and the engine keeps its weights;
        the fault is one-shot, so the next poll deploys the epoch."""
        model, p1, p2 = lm
        watch = str(tmp_path / "ckpt")
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        eng = Engine(model, p1, ServeConfig(max_batch=1,
                                            max_new_tokens=2))
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        monkey = ChaosMonkey(ChaosConfig(swap_error_rate=1.0))
        chaos_lib.install(monkey)
        try:
            assert swapper.poll_once() is None
            assert eng.last_swap_error.stage == "stage"
            assert eng.stats()["swaps_rejected"] == 1
            assert eng.weights_epoch == -1
            assert monkey.counters["io_faults"] == 1
            # One-shot: the retry (next poll) stages clean. The failed
            # attempt must not have blacklisted a healthy save.
            assert swapper.poll_once() == 1
        finally:
            chaos_lib.uninstall()
        eng.submit(np.arange(3, dtype=np.int32))
        eng.run()
        assert eng.weights_epoch == 1

    def test_tree_mismatch_rejected_at_validate(self, lm, tmp_path):
        """A restored tree that doesn't match the serving model's
        abstract tree (here: wrong depth) dies at the validate stage —
        never reaching the compiled programs."""
        model, p1, _ = lm
        other = get_model("transformer_lm", num_classes=VOCAB,
                          num_layers=1, num_heads=2, hidden_dim=32,
                          max_len=MAX_LEN)
        bad = other.init(jax.random.PRNGKey(0),
                         np.zeros((2, 16), np.int32))["params"]
        watch = str(tmp_path / "ckpt")
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        eng = Engine(model, p1, ServeConfig(max_batch=1))
        swapper = HotSwapper(eng, watch, lambda e: bad,
                             printer=lambda m: None)
        with pytest.raises(SwapError, match="parameter tree") as exc:
            swapper.poll_once(raise_on_error=True)
        assert exc.value.stage == "validate"
        assert eng.stats()["swaps_rejected"] == 1
        assert eng.weights_epoch == -1
        # The rejected dir stays on disk (not quarantined — the bytes
        # verified clean, they just don't fit THIS model) and is pinned
        # by marker identity: the unchanged dir is skipped silently...
        assert swapper.poll_once() is None
        assert eng.stats()["swaps_rejected"] == 1
        # ...but an in-place re-save (fresh COMMITTED marker, now
        # restoring a matching tree) is a NEW candidate and deploys.
        p2 = lm[2]
        swapper.restore_fn = lambda e: p2
        marker = os.path.join(watch, "epoch_1", "COMMITTED")
        st = os.stat(marker)
        os.utime(marker, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        assert swapper.poll_once() == 1

    def test_rollback_rearms_previous_weights(self, lm, prompts):
        """After a swap, rollback() re-arms the predecessor: outputs
        return to the original weights' tokens. With no completed swap
        there is nothing to re-arm — typed stage='rollback'."""
        model, p1, p2 = lm
        fresh = Engine(model, p1, ServeConfig(max_batch=1))
        with pytest.raises(SwapError, match="roll back") as exc:
            fresh.rollback()
        assert exc.value.stage == "rollback"

        _, base = _run(model, p1, prompts[:2])
        eng, _ = _run(model, p1, prompts[:2], swap_at=2, swap_params=p2)
        assert eng.weights_epoch == 7
        assert eng.rollback() == -1
        for i, p in enumerate(prompts[:2]):
            eng.submit(p)
        done = {f.uid - len(prompts[:2]): f for f in eng.run()}
        assert eng.weights_epoch == -1
        for i in range(2):
            np.testing.assert_array_equal(done[i].tokens, base[i].tokens)
        assert eng.stats()["swaps_completed"] == 2  # swap + rollback

    def test_swap_error_typing(self):
        err = SwapError("boom", stage="verify", epoch=3)
        assert isinstance(err, RuntimeError)
        assert err.stage == "verify" and err.epoch == 3
        assert SwapError("x").stage == "swap"
        from distributed_training_tpu.resilience import (
            SwapError as FromResilience,
        )
        assert FromResilience is SwapError


class TestSwapPauseAccounting:
    def test_pause_lands_in_swap_blocked_not_tpot_or_step_times(
            self, lm, prompts, monkeypatch):
        """The satellite pin, admission_blocked_s-style: an artificially
        slow barrier (300 ms install) must (a) land in swap_blocked_s,
        (b) be compensated out of in-flight requests' TPOT, and (c) be
        gap-excluded from the decode step-time series — the delta of
        the swap iteration contributes no step-time sample."""
        model, p1, p2 = lm
        pause = 0.3
        orig = Engine._install_params

        def slow_install(self, params):
            time.sleep(pause)
            orig(self, params)

        monkeypatch.setattr(Engine, "_install_params", slow_install)
        swap_at = 3
        eng = Engine(model, p1, ServeConfig(max_batch=2,
                                            max_new_tokens=N_NEW))
        # Warm both compiled programs OFF the measured window — a cold
        # engine's XLA compiles land inside token intervals and would
        # drown the pause this test attributes.
        eng.submit(np.arange(2, dtype=np.int32), max_new_tokens=2)
        eng.run()
        eng.reset_stats()
        for p in prompts:
            eng.submit(p)
        done, it = [], 0
        while not eng.idle:
            if it == swap_at:
                eng.arm_swap(p2, epoch=7)
            done.extend(eng.step())
            it += 1
        by_uid = {f.uid: f for f in done}
        stats = eng.stats()
        assert stats["swap_blocked_s"] >= pause
        # TPOT compensation: every multi-token request's decode span
        # (tpot × intervals) excludes the pause entirely.
        for f in by_uid.values():
            assert f.tpot_ms is not None
            assert f.tpot_ms * (f.tokens.size - 1) < pause * 1e3
        # Step-time exclusion: the delta attributed to the swap
        # iteration is gap-marked out of the recorder's series.
        deltas = dict(eng.telemetry.recorder.step_deltas_ms())
        assert swap_at not in deltas, (
            "swap-iteration delta leaked into step-time percentiles")
        assert swap_at + 1 in deltas  # neighbors still counted

    def test_phase_and_healthz_reflect_swap(self, lm):
        """The drive-by satellite: phase gains 'swapping', and /healthz
        carries weights_epoch + swap counters (the rollout driver's
        confirmation surface)."""
        from distributed_training_tpu.observability.exporter import (
            attach_engine,
        )

        model, p1, p2 = lm
        eng = Engine(model, p1, ServeConfig(max_batch=1,
                                            max_new_tokens=2))
        exporter = attach_engine(eng, 0, printer=lambda m: None)
        try:
            def healthz():
                with urllib.request.urlopen(exporter.url("/healthz"),
                                            timeout=10) as resp:
                    return json.loads(resp.read())

            h = healthz()
            assert h["phase"] == "idle"
            assert h["weights_epoch"] == -1
            assert h["swaps_completed"] == 0
            eng.arm_swap(p2, epoch=5)
            assert eng.phase == "swapping"
            assert healthz()["phase"] == "swapping"
            eng.submit(np.arange(3, dtype=np.int32))
            eng.run()
            h = healthz()
            assert h["phase"] == "idle"
            assert h["weights_epoch"] == 5
            assert h["swaps_completed"] == 1
        finally:
            exporter.close()

    def test_trace_carries_swap_marks_and_staging_span(self, lm,
                                                       tmp_path):
        """Swap observability on the timeline: armed/applied/rejected
        instants on the engine track, the staging pipeline as a span on
        its own 'hotswap' track."""
        from distributed_training_tpu.observability.trace import (
            TraceSession,
        )

        model, p1, p2 = lm
        trace = TraceSession()
        eng = Engine(model, p1, ServeConfig(max_batch=1,
                                            max_new_tokens=2),
                     trace=trace)
        watch = str(tmp_path / "ckpt")
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        assert swapper.poll_once() == 1
        eng.submit(np.arange(3, dtype=np.int32))
        eng.run()
        eng.note_swap_rejected(SwapError("x", stage="verify", epoch=2))
        names = [e["name"] for e in trace.to_json()["traceEvents"]]
        for want in ("swap.stage", "swap.armed", "swap.applied",
                     "swap.rejected"):
            assert want in names, (want, names)


class TestWatcherLifecycle:
    def test_background_thread_trigger_and_close(self, lm, tmp_path):
        """The serve.py wiring shape: a long-interval watcher thread,
        woken early by trigger() (the SIGHUP path), deploys a freshly
        committed epoch; close() joins the thread."""
        model, p1, p2 = lm
        watch = str(tmp_path / "ckpt")
        eng = Engine(model, p1, ServeConfig(max_batch=1,
                                            max_new_tokens=2))
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        swapper.start(interval_s=60.0)
        deadline = time.time() + 20
        while swapper.counters["polls"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert swapper.counters["polls"] >= 1, "watcher never polled"
        ckpt_lib.save_checkpoint(watch, 1,
                                 {"x": np.arange(64, dtype=np.float32)})
        swapper.trigger()
        deadline = time.time() + 20
        while swapper.counters["armed"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert swapper.counters["armed"] == 1, "trigger() never woke it"
        swapper.close()
        assert eng.phase == "swapping"  # armed, awaiting the barrier
        eng.submit(np.arange(3, dtype=np.int32))
        eng.run()
        assert eng.weights_epoch == 1

    def test_request_rollback_serviced_on_watcher_thread(self, lm,
                                                         tmp_path):
        """The SIGUSR1 path: request_rollback() only sets events (a
        signal handler must not take the engine's swap lock — the
        serving loop holds it around the barrier on the same thread);
        the WATCHER thread performs the rollback on its next wake."""
        model, p1, p2 = lm
        watch = str(tmp_path / "ckpt")  # stays empty: polls find nothing
        eng, _ = _run(model, p1, [np.arange(3, dtype=np.int32)],
                      swap_at=0, swap_params=p2)
        assert eng.weights_epoch == 7
        swapper = HotSwapper(eng, watch, lambda e: p2,
                             printer=lambda m: None)
        swapper.start(interval_s=60.0)
        swapper.request_rollback()
        deadline = time.time() + 20
        while eng.phase != "swapping" and time.time() < deadline:
            time.sleep(0.01)
        swapper.close()
        assert eng.phase == "swapping", "rollback never serviced"
        eng.submit(np.arange(3, dtype=np.int32))
        eng.run()
        assert eng.weights_epoch == -1  # back on the original weights

    def test_restore_fn_reuses_template_without_rebuild(self, tmp_path):
        """The build_lm_and_restorer closure IS the staging read: a
        checkpoint saved from a differently-valued state restores
        through restore_fn bitwise, with no model rebuild."""
        from distributed_training_tpu.config import (
            OptimizerConfig,
            PrecisionConfig,
            SchedulerConfig,
        )
        from distributed_training_tpu.inference.restore import (
            build_lm_and_restorer,
        )
        from distributed_training_tpu.train.optim import make_optimizer
        from distributed_training_tpu.train.precision import (
            LossScaleState,
            Policy,
        )
        from distributed_training_tpu.train.train_state import (
            init_train_state,
        )

        ckdir = str(tmp_path / "ck")
        kw = dict(vocab_size=VOCAB, num_layers=1, num_heads=2,
                  hidden_dim=32, max_len=MAX_LEN, checkpoint=ckdir,
                  printer=lambda m: None)
        model, params, epoch, restore_fn = build_lm_and_restorer(**kw)
        assert epoch == -1  # nothing saved yet

        # Save a state with shifted params (the "newly trained" epoch),
        # built exactly the way the restorer's template was.
        tx = make_optimizer(OptimizerConfig(), SchedulerConfig(),
                            world_size=1)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (1, 8), tx,
            loss_scale=LossScaleState.create(PrecisionConfig()),
            input_dtype=jax.numpy.int32)
        shifted = jax.tree.map(lambda a: a + 1.0, state.params)
        state = state.replace(params=shifted)
        ckpt_lib.save_checkpoint(ckdir, 0, state)

        got = restore_fn(0)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), got, shifted)

    def test_serve_bench_swap_mode_sla_line(self, monkeypatch, capsys):
        """tools/serve_bench.py --swap-at-request: the SLA line carries
        the swap counters the bench gate consumes (exactly one
        completed swap, zero rejected, the bumped weights epoch)."""
        from conftest import load_cli_module

        bench = load_cli_module("tools/serve_bench.py")
        monkeypatch.setattr("sys.argv", [
            "serve_bench.py", "--requests", "6", "--rate", "500",
            "--max-batch", "2", "--num-layers", "1", "--num-heads", "2",
            "--hidden-dim", "32", "--model-max-len", "64",
            "--prompt-len", "6", "--max-new-tokens", "4",
            "--swap-at-request", "3"])
        assert bench.main() == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        stats = json.loads(line)
        assert stats["swaps_completed"] == 1
        assert stats["swaps_rejected"] == 0
        assert stats["swap_blocked_s"] >= 0.0
        assert stats["weights_epoch"] == 0  # -1 (random init) + 1
        assert stats["requests_finished"] == 6
