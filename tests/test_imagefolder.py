"""ImageFolder dataset tests: scan, lazy sharded decode, trainer wiring."""

import os

import numpy as np
import pytest
from PIL import Image

from distributed_training_tpu.data.imagefolder import (
    ImageFolderLoader,
    scan_imagefolder,
)


def make_tree(root, classes=("cat", "dog"), per_class=6, size=(40, 30)):
    rng = np.random.RandomState(0)
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 255, (size[1], size[0], 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i}.png"))
    return root


class TestScan:
    def test_layout_and_labels(self, tmp_path):
        make_tree(str(tmp_path))
        paths, labels, classes = scan_imagefolder(str(tmp_path))
        assert classes == ["cat", "dog"]  # sorted
        assert len(paths) == 12
        assert (labels[:6] == 0).all() and (labels[6:] == 1).all()

    def test_non_image_files_skipped(self, tmp_path):
        make_tree(str(tmp_path), per_class=2)
        open(tmp_path / "cat" / "notes.txt", "w").write("x")
        paths, _, _ = scan_imagefolder(str(tmp_path))
        assert len(paths) == 4

    def test_missing_root_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            scan_imagefolder(str(tmp_path / "nope"))

    def test_empty_root_raises(self, tmp_path):
        os.makedirs(tmp_path / "empty_cls")
        with pytest.raises(ValueError, match="no images"):
            scan_imagefolder(str(tmp_path))


class TestLoader:
    def _loader(self, tmp_path, **kw):
        make_tree(str(tmp_path))
        paths, labels, _ = scan_imagefolder(str(tmp_path))
        defaults = dict(global_batch_size=4, image_size=16, seed=1,
                        process_index=0, process_count=1, num_workers=2)
        defaults.update(kw)
        return ImageFolderLoader(paths, labels, **defaults)

    def test_shapes_and_range(self, tmp_path):
        loader = self._loader(tmp_path)
        batch = next(iter(loader))
        assert batch["image"].shape == (4, 16, 16, 3)
        assert batch["image"].dtype == np.float32
        assert 0.0 <= batch["image"].min() and batch["image"].max() <= 1.0
        assert batch["label"].shape == (4,)

    def test_epoch_reshuffle_and_determinism(self, tmp_path):
        loader = self._loader(tmp_path)
        loader.set_epoch(0)
        a = [b["label"].tolist() for b in loader]
        loader.set_epoch(0)
        b = [b["label"].tolist() for b in loader]
        assert a == b  # same epoch -> same order
        loader.set_epoch(1)
        c = [b["label"].tolist() for b in loader]
        assert a != c  # new epoch -> reshuffled

    def test_process_sharding_partitions_batch(self, tmp_path):
        full = self._loader(tmp_path, shuffle=False)
        p0 = self._loader(tmp_path, shuffle=False,
                          process_index=0, process_count=2)
        p1 = self._loader(tmp_path, shuffle=False,
                          process_index=1, process_count=2)
        f, a, b = (next(iter(x)) for x in (full, p0, p1))
        np.testing.assert_array_equal(
            f["label"], np.concatenate([a["label"], b["label"]]))
        np.testing.assert_allclose(
            f["image"], np.concatenate([a["image"], b["image"]]))

    def test_eval_crop_is_deterministic(self, tmp_path):
        loader = self._loader(tmp_path, train=False, shuffle=False)
        a = next(iter(loader))["image"]
        b = next(iter(loader))["image"]
        np.testing.assert_array_equal(a, b)

    def test_train_crops_vary_across_epochs(self, tmp_path):
        loader = self._loader(tmp_path, shuffle=False)
        loader.set_epoch(0)
        a = next(iter(loader))["image"]
        loader.set_epoch(1)
        b = next(iter(loader))["image"]
        assert not np.array_equal(a, b)

    def test_normalize_only_mode_is_deterministic_and_centered(self, tmp_path):
        """DS-parity augment: no random crop/flip, values in [-1, 1]."""
        a_loader = self._loader(tmp_path, augment="normalize_only",
                                shuffle=False)
        a_loader.set_epoch(0)
        a = next(iter(a_loader))["image"]
        a_loader.set_epoch(1)
        b = next(iter(a_loader))["image"]
        np.testing.assert_array_equal(a, b)  # no train-time randomness
        assert a.min() < 0 <= 1.0 >= a.max() and a.min() >= -1.0

    def test_unknown_augment_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="augment"):
            self._loader(tmp_path, augment="mixup")

    def test_ragged_final_batch_masked(self, tmp_path):
        loader = self._loader(tmp_path, global_batch_size=5, drop_last=False,
                              shuffle=False)
        batches = list(loader)
        assert len(batches) == 3  # ceil(12 / 5)
        last = batches[-1]
        np.testing.assert_array_equal(last["mask"], [1, 1, 0, 0, 0])
        assert last["image"].shape == (5, 16, 16, 3)


class TestTrainerWiring:
    def test_imagefolder_end_to_end(self, mesh, tmp_path):
        from distributed_training_tpu.config import DataConfig, TrainConfig
        from distributed_training_tpu.train.trainer import Trainer

        make_tree(str(tmp_path / "train"), per_class=8)
        make_tree(str(tmp_path / "val"), per_class=2)
        cfg = TrainConfig(
            model="resnet_micro",
            num_epochs=1,
            log_interval=1,
            eval_every=1,
            data=DataConfig(
                dataset="imagefolder", data_path=str(tmp_path),
                batch_size=1, image_size=16, num_classes=2,
                num_workers=2, prefetch=1),
            checkpoint=__import__(
                "distributed_training_tpu.config",
                fromlist=["CheckpointConfig"]).CheckpointConfig(interval=0),
        )
        tr = Trainer(cfg, mesh=mesh)
        result = tr.fit()
        assert result["final_acc"] is not None
        assert np.isfinite(result["last_metrics"]["loss"])

    def test_class_count_mismatch_raises(self, mesh, tmp_path):
        from distributed_training_tpu.config import DataConfig, TrainConfig
        from distributed_training_tpu.train.trainer import Trainer

        make_tree(str(tmp_path / "train"), per_class=2)
        make_tree(str(tmp_path / "val"), per_class=1)
        cfg = TrainConfig(
            model="resnet_micro", num_epochs=1,
            data=DataConfig(dataset="imagefolder", data_path=str(tmp_path),
                            batch_size=1, image_size=16, num_classes=10),
        )
        with pytest.raises(ValueError, match="num_classes"):
            Trainer(cfg, mesh=mesh).make_loaders()
