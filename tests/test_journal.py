"""Crash-durable serving: write-ahead request journal + lossless
restart recovery (serving/journal.py, Engine.recover()).

Load-bearing properties, in order of importance:

1. **Lossless crash recovery** (the tentpole): kill the engine with
   requests in flight, restart on the same journal — finished results
   re-deliver from the log exactly once, unfinished requests re-seat
   through the round-16 resume path, and every completed output is
   BITWISE identical to the uninterrupted single-slot oracle (greedy
   and sampled, paged and legacy, speculation on and off). Tokens past
   the last durable flush are recomputed by the same
   ``fold_in(rng, position)`` induction, not lost.
2. **Durable-format robustness**: length-prefixed crc-framed records;
   a torn tail (truncation, bit flip, garbage append) truncates at the
   last good record and quarantines the severed bytes — never a crash;
   segment rotation compacts finished-and-acked requests so the
   journal's footprint tracks in-flight state, not history.
3. **Replay idempotence + the client cursor**: recovering twice yields
   the same state; redelivery repeats until the CLIENT acks (a
   recovery attempt that died before its consumer took delivery loses
   nothing), and after the ack nothing redelivers again.
4. **Deadlines survive restart**: arrival/first-token clocks are
   wall-anchored in the journal, so downtime keeps billing — a request
   whose deadline expired while the engine was dead completes
   ``timeout`` (``preempted_timeout`` if the journal shows a
   preemption) at replay instead of resurrecting.

Engines compile real XLA programs, so the model is tiny and the
crash-matrix is trimmed to cover every axis value rather than the full
product (the CI crash-recovery drill exercises the real ``kill -9``
path through serve_bench subprocesses).
"""

import dataclasses
import json
import os
import struct
import time
import zlib

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.serving import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_PREEMPT_TIMEOUT,
    FINISH_TIMEOUT,
    ActiveSequence,
    Engine,
    FinishedRequest,
    JournalCorruptError,
    Request,
    RequestJournal,
)

VOCAB = 31
MAX_LEN = 48
# The ServeConfig-default RNG/sampling/weights fingerprint (what an
# Engine with default sampling and no checkpoint writes); unit tests
# that hand-craft journals reuse it so a real engine can recover them.
DEFAULT_FP = {"seed": 0, "temperature": 0.0, "top_k": None,
              "top_p": None, "eos_id": None, "pad_id": 0,
              "quantize_weights": False, "kv_dtype": None,
              "weights_epoch": -1}


@pytest.fixture(scope="module")
def lm():
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=1, num_heads=2,
        hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(3)
    return [rng.randint(0, VOCAB, size=l).astype(np.int32)
            for l in (5, 7, 3, 6)]


def _solo_outputs(model, params, reqs, **cfg_kw):
    """Uninterrupted oracle: serve ``reqs`` one at a time on a single
    slot (uid parity with the crash run is what the bitwise comparison
    requires — the RNG stream is fold_in(seed, uid))."""
    eng = Engine(model, params, ServeConfig(max_batch=1, **cfg_kw))
    out = {}
    for prompt, max_new in reqs:
        req = eng.submit(prompt, max_new_tokens=max_new)
        for fin in eng.run():
            out[fin.uid] = fin.tokens.tolist()
        assert req.uid in out
    return out


def _mk_req(uid, prompt_len=4, mnt=8, arrival_t=None, **kw):
    return Request(
        uid=uid, prompt=np.arange(1, prompt_len + 1, dtype=np.int32),
        max_new_tokens=mnt,
        arrival_t=time.perf_counter() if arrival_t is None else arrival_t,
        **kw)


def _frames(path):
    """(offset, payload) per well-formed record in a segment file."""
    data = open(path, "rb").read()
    out, off = [], 0
    while off + 8 <= len(data):
        ln, crc = struct.unpack_from("<II", data, off)
        payload = data[off + 8:off + 8 + ln]
        if len(payload) < ln or zlib.crc32(payload) != crc:
            break
        out.append((off, payload))
        off += 8 + ln
    return out


def _segment(path):
    segs = [os.path.join(path, n) for n in sorted(os.listdir(path))
            if n.startswith("wal-") and n.endswith(".log")]
    assert len(segs) == 1, segs
    return segs[0]


class TestJournalUnit:
    def _journal(self, d, **kw):
        kw.setdefault("fingerprint", DEFAULT_FP)
        j = RequestJournal(str(d), **kw)
        j.recover()
        return j

    def test_roundtrip_and_ack_drop(self, tmp_path):
        j = self._journal(tmp_path)
        a, b = _mk_req(0, priority=0), _mk_req(1, prompt_len=3)
        j.log_admit(a)
        j.log_admit(b)
        seq = ActiveSequence(request=a, slot=0)
        for i, tok in enumerate((7, 8, 9)):
            seq.note_token(tok, time.perf_counter())
        j.note_tokens(seq)
        fin = FinishedRequest.from_active(seq, FINISH_LENGTH)
        j.note_finish(fin)
        j.ack(0)
        j.shutdown()

        j2 = self._journal(tmp_path)
        state = j2.recover()
        # 0 finished AND acked -> dropped entirely; 1 still pending.
        assert sorted(state.requests) == [1]
        assert state.max_uid == 1  # acked uids never get reused
        e = state.requests[1]
        assert e.prompt == [1, 2, 3]
        assert e.tokens == [] and not e.finished
        j2.shutdown()

    def test_token_batches_are_idempotent_by_base(self, tmp_path):
        j = self._journal(tmp_path)
        req = _mk_req(0)
        j.log_admit(req)
        seq = ActiveSequence(request=req, slot=0)
        seq.note_token(4, time.perf_counter())
        j.note_tokens(seq)
        seq.note_token(5, time.perf_counter())
        seq.note_token(6, time.perf_counter())
        j.note_tokens(seq)
        j.note_tokens(seq)  # no-op: nothing new
        j.shutdown()
        state = self._journal(tmp_path).recover()
        assert state.requests[0].tokens == [4, 5, 6]

    def test_unrecovered_append_raises_typed(self, tmp_path):
        j = RequestJournal(str(tmp_path), fingerprint=DEFAULT_FP)
        with pytest.raises(JournalCorruptError) as ei:
            j.log_admit(_mk_req(0))
        assert ei.value.reason == "unrecovered"

    def test_shutdown_refuses_appends(self, tmp_path):
        """An append after shutdown() must refuse loudly — a silently
        pending-forever admission would break 'accepted ⇒ durable'."""
        j = self._journal(tmp_path)
        j.shutdown()
        with pytest.raises(JournalCorruptError) as ei:
            j.log_admit(_mk_req(0))
        assert ei.value.reason == "closed"

    def test_weights_epoch_tail_fingerprint(self, tmp_path):
        """The LAST cfg record wins: a hot-swap journals its new
        weights_epoch, and a restart serving different weights than the
        journal's tail is refused typed (recomputing 'lost' tokens
        under the wrong model would silently break the bitwise
        contract); a restart on the swapped weights recovers."""
        j = self._journal(tmp_path)
        j.log_admit(_mk_req(0))
        j.update_fingerprint(weights_epoch=2)  # a hot-swap landed
        j.shutdown()
        j2 = RequestJournal(str(tmp_path), fingerprint=DEFAULT_FP)
        with pytest.raises(JournalCorruptError) as ei:
            j2.recover()
        assert ei.value.reason == "fingerprint"
        j3 = RequestJournal(
            str(tmp_path),
            fingerprint={**DEFAULT_FP, "weights_epoch": 2})
        state = j3.recover()
        assert sorted(state.requests) == [0]
        j3.shutdown()

    def test_fingerprint_mismatch_refuses_replay(self, tmp_path):
        j = self._journal(tmp_path)
        j.log_admit(_mk_req(0))
        j.shutdown()
        j2 = RequestJournal(str(tmp_path),
                            fingerprint={**DEFAULT_FP, "seed": 1})
        with pytest.raises(JournalCorruptError) as ei:
            j2.recover()
        assert ei.value.reason == "fingerprint"

    def test_torn_tail_truncated_and_quarantined(self, tmp_path):
        j = self._journal(tmp_path)
        for uid in range(3):
            j.log_admit(_mk_req(uid))
        j.shutdown()
        seg = _segment(tmp_path)
        with open(seg, "ab") as fh:
            fh.write(b"\xff" * 37)  # a crash mid-append
        j2 = RequestJournal(str(tmp_path), fingerprint=DEFAULT_FP)
        state = j2.recover()
        j2.shutdown()
        assert sorted(state.requests) == [0, 1, 2]
        assert state.torn_bytes == 37
        corrupt = [n for n in os.listdir(tmp_path) if ".corrupt" in n]
        assert len(corrupt) == 1
        # The quarantine holds the severed bytes; the next recovery is
        # clean (the tail was truncated at the last good record and the
        # survivors compacted forward).
        state2 = self._journal(tmp_path).recover()
        assert state2.torn_bytes == 0
        assert sorted(state2.requests) == [0, 1, 2]

    def test_crc_flip_kills_only_the_tail(self, tmp_path):
        j = self._journal(tmp_path)
        for uid in range(3):
            j.log_admit(_mk_req(uid))
        j.shutdown()
        seg = _segment(tmp_path)
        frames = _frames(seg)
        last_off, last_payload = frames[-1]
        assert b'"u":2' in last_payload
        with open(seg, "r+b") as fh:
            fh.seek(last_off + 8)  # first payload byte of last record
            byte = fh.read(1)
            fh.seek(last_off + 8)
            fh.write(bytes([byte[0] ^ 0xFF]))
        j2 = RequestJournal(str(tmp_path), fingerprint=DEFAULT_FP)
        state = j2.recover()
        j2.shutdown()
        assert sorted(state.requests) == [0, 1]  # the flipped admit died
        assert state.torn_bytes > 0

    def test_truncation_mid_record(self, tmp_path):
        j = self._journal(tmp_path)
        for uid in range(3):
            j.log_admit(_mk_req(uid))
        j.shutdown()
        seg = _segment(tmp_path)
        with open(seg, "r+b") as fh:
            fh.seek(0, 2)
            fh.truncate(fh.tell() - 3)
        state = self._journal(tmp_path).recover()
        assert sorted(state.requests) == [0, 1]

    def test_rotation_bounds_journal_size(self, tmp_path):
        """Satellite: a preempt-storm-shaped churn (admit, tokens,
        preempt, re-tokens, finish, ack per request) must stay under a
        pinned size bound — finished-and-acked requests compact away,
        so the footprint tracks in-flight state, not history."""
        seg_bytes = 4096
        j = self._journal(tmp_path, segment_bytes=seg_bytes,
                          fsync="none")
        t = time.perf_counter()
        for uid in range(300):
            req = _mk_req(uid)
            j.log_admit(req)
            seq = ActiveSequence(request=req, slot=0)
            for tok in range(4):
                seq.note_token(tok, t)
            j.note_tokens(seq)
            j.note_preempt(seq)
            for tok in range(4, 8):
                seq.note_token(tok, t)
            j.note_tokens(seq)
            j.note_finish(FinishedRequest.from_active(seq, FINISH_LENGTH))
            j.ack(uid)
        # One unfinished straggler must SURVIVE every compaction.
        j.log_admit(_mk_req(300, prompt_len=6))
        j.persist()
        j.shutdown()
        total = sum(os.path.getsize(os.path.join(tmp_path, n))
                    for n in os.listdir(tmp_path))
        assert j.segments_rotated > 0
        assert total < 4 * seg_bytes, total
        state = self._journal(tmp_path).recover()
        assert sorted(state.requests) == [300]
        assert state.max_uid == 300

    def test_write_fault_retains_and_retries_batch(self, tmp_path):
        """A transient disk fault must lose NOTHING and must not end
        durability: the failed batch returns to the queue head and the
        next persist lands it (replay idempotence absorbs any
        half-written prefix)."""
        j = self._journal(tmp_path)
        j.pause()  # deterministic: we drive persist() by hand
        j.log_note({"cursor": 7}, flush=False)
        seg_fd, j._fd = j._fd, None
        os.close(seg_fd)
        seg = _segment(tmp_path)
        j._fd = os.open(os.devnull, os.O_WRONLY)
        os.close(j._fd)  # a dead fd: the next write raises EBADF
        with pytest.raises(OSError):
            j.persist()
        assert j.write_errors == 1
        j._fd = os.open(seg, os.O_WRONLY | os.O_APPEND)
        j.persist()  # the retried batch lands
        j.shutdown()
        state = self._journal(tmp_path).recover()
        assert state.notes.get("cursor") == 7

    def test_double_recovery_is_idempotent(self, tmp_path):
        j = self._journal(tmp_path)
        req = _mk_req(0)
        j.log_admit(req)
        seq = ActiveSequence(request=req, slot=0)
        seq.note_token(9, time.perf_counter())
        j.note_tokens(seq)
        j.note_preempt(seq)
        j.shutdown()
        a = self._journal(tmp_path).recover()
        b = self._journal(tmp_path).recover()
        assert sorted(a.requests) == sorted(b.requests) == [0]
        for s in (a, b):
            e = s.requests[0]
            assert e.tokens == [9] and e.preempts == 1

    def test_notes_last_write_wins_and_survive_compaction(self, tmp_path):
        j = self._journal(tmp_path, segment_bytes=4096, fsync="none")
        for i in range(200):
            j.log_note({"submitted": i + 1})
        j.shutdown()
        state = self._journal(tmp_path).recover()
        assert state.notes == {"submitted": 200}

    def test_deadline_offsets_roundtrip(self, tmp_path):
        j = self._journal(tmp_path)
        now = time.perf_counter()
        j.log_admit(_mk_req(0, arrival_t=now, ttft_deadline_t=now + 1.5,
                            deadline_t=now + 30.0))
        j.shutdown()
        e = self._journal(tmp_path).recover().requests[0]
        assert e.ttft_rel_s == pytest.approx(1.5)
        assert e.deadline_rel_s == pytest.approx(30.0)


# Every axis value (paged/legacy, spec 0/2) under both greedy and
# sampled temperatures, without the full product. The legacy-cache
# combos ride the slow mark (round-8 tier-1 budget note): the resume
# path they share is already tier-1-pinned by test_preemption, and the
# paged combos + the CI crash drill carry the per-push recovery claim.
CRASH_CASES = [
    ({"prefill_chunk": 4}, 0.0),
    ({"prefill_chunk": 4, "spec_k": 2}, 0.8),
    pytest.param({"kv_page_size": None, "prefill_bucket": 8}, 0.0,
                 marks=pytest.mark.slow),
    pytest.param({"kv_page_size": None, "prefill_bucket": 8,
                  "spec_k": 2, "max_len": 40}, 0.8,
                 marks=pytest.mark.slow),
]


class TestCrashRecovery:
    @pytest.mark.parametrize("cfg_kw,temp", CRASH_CASES)
    def test_crash_resume_bitwise(self, lm, prompts, tmp_path, cfg_kw,
                                  temp):
        """THE invariant: kill an engine with requests in flight — one
        past its last durable flush — restart on the journal, and every
        output (redelivered + recomputed) equals the uninterrupted
        single-slot oracle bitwise."""
        model, params = lm
        cfg = ServeConfig(max_batch=2, max_new_tokens=8,
                          temperature=temp, journal_dir=str(tmp_path),
                          **cfg_kw)
        eng = Engine(model, params, cfg)
        eng.recover()
        uids = [eng.submit(p, max_new_tokens=8).uid
                for p in prompts[:3]]
        done = {}
        for _ in range(6):
            for f in eng.step():
                done[f.uid] = f.tokens.tolist()
        # Everything so far is durable; the NEXT iterations' tokens
        # (and possibly a finish) are enqueued but never persisted —
        # the tail a kill -9 loses and recovery must recompute.
        eng.journal.pause()
        for _ in range(3):
            for f in eng.step():
                done[f.uid] = f.tokens.tolist()
        eng.journal.crash()

        eng2 = Engine(model, params, cfg)
        rep = eng2.recover()
        out = {f.uid: f.tokens.tolist()
               for f in rep["redelivered"] + rep["completed_at_replay"]}
        for f in eng2.drain():
            out[f.uid] = f.tokens.tolist()
        if eng2.paged:
            eng2.pool.check_balanced()
        solo = _solo_outputs(model, params, [(p, 8) for p in prompts[:3]],
                             temperature=temp, **cfg_kw)
        assert sorted(out) == uids
        for uid in uids:
            assert out[uid] == solo[uid], uid
        stats = eng2.stats()
        assert stats["requests_recovered"] == 3
        assert stats["tokens_recomputed_on_recovery"] > 0
        assert stats["journal_records_written"] > 0
        eng2.journal.shutdown()

    def test_crash_while_preempted_recovers_with_attribution(
            self, lm, prompts, tmp_path):
        """A crash while a preempted sequence sits requeued: recovery
        rebuilds the resumption (emitted tokens + preempt count) and
        the continued outputs stay bitwise; the preemption attribution
        survives the restart."""
        model, params = lm
        cfg = ServeConfig(max_batch=1, max_new_tokens=8, num_tiers=2,
                          prefill_chunk=4, journal_dir=str(tmp_path))
        eng = Engine(model, params, cfg)
        eng.recover()
        low = eng.submit(prompts[0], priority=1, max_new_tokens=8)
        for _ in range(3):
            eng.step()
        assert len(eng.scheduler.sequence(0).tokens) >= 1
        high = eng.submit(prompts[1], priority=0, max_new_tokens=4)
        eng.step()  # the preemption pass: low requeues mid-flight
        assert eng.stats()["requests_preempted"] == 1
        eng.journal.persist()
        eng.journal.crash()

        eng2 = Engine(model, params, cfg)
        rep = eng2.recover()
        assert rep["resumed"] == 2
        # The requeued victim restores into its tier as a resumption
        # carrying its emitted tokens AND its preempt count (the
        # high-tier head, mid-prefill at the crash, restores fresh).
        entry = eng2.queue._tiers[1][0]
        assert isinstance(entry, ActiveSequence)
        assert entry.request.uid == low.uid and entry.preempts == 1
        out = {f.uid: f.tokens.tolist() for f in eng2.drain()}
        eng2.pool.check_balanced()
        solo = _solo_outputs(model, params,
                             [(prompts[0], 8), (prompts[1], 4)],
                             prefill_chunk=4)
        assert out[low.uid] == solo[low.uid]
        assert out[high.uid] == solo[high.uid]
        eng2.journal.shutdown()

    def test_redelivery_repeats_until_acked_then_stops(
            self, lm, prompts, tmp_path):
        """The client cursor (replay idempotence): a finished result
        redelivers on EVERY recovery until the consumer acks — a
        recovery attempt that died before its consumer took delivery
        loses nothing — and after the ack it never redelivers again.
        Double replay of the same journal is a state no-op throughout."""
        model, params = lm
        cfg = ServeConfig(max_batch=2, max_new_tokens=6,
                          prefill_chunk=4, journal_dir=str(tmp_path))
        eng = Engine(model, params, cfg)
        eng.recover()
        for p in prompts[:2]:
            eng.submit(p, max_new_tokens=6)
        finished = {f.uid: f.tokens.tolist() for f in eng.run()}
        assert len(finished) == 2
        eng.journal.crash()  # finishes durable (writer ran), no acks

        def recover_once(ack):
            e = Engine(model, params, cfg)
            rep = e.recover()
            assert rep["resumed"] == 0 and not rep["completed_at_replay"]
            redelivered = {f.uid: f.tokens.tolist()
                           for f in rep["redelivered"]}
            if ack:
                e.journal.ack(list(redelivered))
            e.journal.shutdown()
            return redelivered

        # Two un-acked recoveries redeliver identically (kill -9 mid
        # replay converges); the acked one is final.
        assert recover_once(ack=False) == finished
        assert recover_once(ack=True) == finished
        assert recover_once(ack=False) == {}

    def test_finish_condition_met_in_journal_completes_at_replay(
            self, lm, tmp_path):
        """Crash between the last emit and the finish record's flush:
        the journaled stream already satisfies EOS/budget, so replay
        completes the request with the right reason instead of
        re-seating a sequence that has nothing left to decode."""
        model, params = lm
        j = RequestJournal(str(tmp_path), fingerprint=DEFAULT_FP)
        j.recover()
        t = time.perf_counter()
        length = _mk_req(0, mnt=3)
        j.log_admit(length)
        seq = ActiveSequence(request=length, slot=0)
        for tok in (4, 5, 6):  # budget reached, finish never flushed
            seq.note_token(tok, t)
        j.note_tokens(seq)
        eos_req = _mk_req(1, mnt=8)
        j.log_admit(eos_req)
        seq2 = ActiveSequence(request=eos_req, slot=0)
        seq2.note_token(2, t)  # == eos_id below
        j.note_tokens(seq2)
        j.shutdown()

        eng = Engine(model, params, ServeConfig(
            max_batch=1, eos_id=2, journal_dir=str(tmp_path)))
        with pytest.raises(JournalCorruptError):
            eng.recover()  # eos_id changes the fingerprint: refused
        eng = Engine(model, params, ServeConfig(
            max_batch=1, journal_dir=str(tmp_path)))
        rep = eng.recover()
        reasons = {f.uid: f.finish_reason
                   for f in rep["completed_at_replay"]}
        assert reasons[0] == FINISH_LENGTH
        assert rep["resumed"] == 1  # no eos configured: 1 keeps going
        done = {f.uid: f for f in eng.drain()}
        assert done[1].tokens.size == 8
        eng.journal.shutdown()
        # Same journal under an engine whose fingerprint MATCHES an
        # eos config: hand-craft the eos fingerprint to prove the eos
        # branch too.
        j3 = RequestJournal(str(tmp_path / "eos"),
                            fingerprint={**DEFAULT_FP, "eos_id": 2})
        j3.recover()
        j3.log_admit(eos_req)
        j3.note_tokens(seq2)
        j3.shutdown()
        eng3 = Engine(model, params, ServeConfig(
            max_batch=1, eos_id=2, journal_dir=str(tmp_path / "eos")))
        rep3 = eng3.recover()
        assert [f.finish_reason for f in rep3["completed_at_replay"]] \
            == [FINISH_EOS]
        eng3.journal.shutdown()

    def test_deadline_expired_during_downtime(self, lm, tmp_path):
        """Satellite: deadline clocks keep running across downtime. A
        request whose total deadline passed while the engine was dead
        completes ``timeout`` at replay — ``preempted_timeout`` when
        the journal shows a preemption (partial tokens kept) — and one
        whose deadline still has slack resumes with the remaining
        budget mapped into the new process's clock."""
        model, params = lm
        j = RequestJournal(str(tmp_path), fingerprint=DEFAULT_FP)
        j.recover()
        t = time.perf_counter()
        # "Admitted 10 s ago", 1 s total deadline, preempted after one
        # token: expired 9 s of downtime ago.
        preempted = _mk_req(0, arrival_t=t - 10.0, deadline_t=t - 9.0)
        j.log_admit(preempted)
        seq = ActiveSequence(request=preempted, slot=0)
        seq.note_token(5, t - 9.5)
        j.note_tokens(seq)
        j.note_preempt(seq)
        # Fresh request past its TTFT deadline, never served.
        fresh = _mk_req(1, arrival_t=t - 10.0, ttft_deadline_t=t - 9.0)
        j.log_admit(fresh)
        # Still-live request: 1 h of total deadline left.
        alive = _mk_req(2, arrival_t=t - 10.0, deadline_t=t + 3600.0)
        j.log_admit(alive)
        j.shutdown()

        eng = Engine(model, params, ServeConfig(
            max_batch=1, journal_dir=str(tmp_path)))
        rep = eng.recover()
        fins = {f.uid: f for f in rep["completed_at_replay"]}
        assert fins[0].finish_reason == FINISH_PREEMPT_TIMEOUT
        assert fins[0].tokens.tolist() == [5]  # partial tokens kept
        assert fins[1].finish_reason == FINISH_TIMEOUT
        assert fins[1].tokens.size == 0
        assert rep["resumed"] == 1
        entry = eng.queue.peek()
        remaining = entry.deadline_t - time.perf_counter()
        assert 3500.0 < remaining < 3600.0  # 10 s of downtime billed
        stats = eng.stats()
        assert stats["requests_recovered"] == 3
        assert stats["requests_preempt_timed_out"] == 1
        assert stats["requests_timed_out"] == 1
        eng.journal.shutdown()

    def test_submit_withdraws_when_journal_append_fails(self, lm,
                                                        prompts,
                                                        tmp_path):
        """Acceptance is journal-backed: when the durable admission
        record cannot be written, submit() must raise AND leave the
        queue empty — an accepted-but-unjournaled request would decode
        anyway and duplicate the caller's retry."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, journal_dir=str(tmp_path)))
        eng.recover()
        eng.journal.shutdown()  # appends now refuse typed
        with pytest.raises(JournalCorruptError):
            eng.submit(prompts[2], max_new_tokens=8)
        assert len(eng.queue) == 0 and eng.idle

    def test_phase_counters_and_reset_preservation(self, lm, prompts,
                                                   tmp_path,
                                                   monkeypatch):
        """/healthz evidence: phase reads 'recovering' during replay,
        health() carries the journal counters, and reset_stats (the
        bench warm-up reset) preserves the recovery evidence."""
        model, params = lm
        cfg = ServeConfig(max_batch=1, max_new_tokens=4,
                          prefill_chunk=4, journal_dir=str(tmp_path))
        eng = Engine(model, params, cfg)
        eng.recover()
        eng.submit(prompts[2], max_new_tokens=4)
        eng.step()
        eng.journal.persist()
        eng.journal.crash()

        eng2 = Engine(model, params, cfg)
        seen = {}
        orig = eng2.journal.recover

        def spy():
            seen["phase"] = eng2.phase
            return orig()

        monkeypatch.setattr(eng2.journal, "recover", spy)
        assert eng2.phase != "recovering"
        eng2.recover()
        assert seen["phase"] == "recovering"
        assert eng2.phase != "recovering"
        health = eng2.health()
        for key in ("requests_recovered", "journal_records_written",
                    "journal_fsyncs"):
            assert key in health, key
        assert health["requests_recovered"] == 1
        eng2.reset_stats()
        assert eng2.stats()["requests_recovered"] == 1
        eng2.journal.shutdown()


class TestServeBenchJournalCli:
    def test_journal_run_then_idempotent_restart(self, monkeypatch,
                                                 capsys, tmp_path):
        """serve_bench with --journal-dir: the SLA line carries the
        journal keys with zero recovery on a clean run; restarting on
        the same journal after a clean (fully acked) run recovers
        nothing, submits nothing (the submission cursor says the
        scenario is done), and delivers nothing twice."""
        from conftest import load_cli_module

        bench = load_cli_module("tools/serve_bench.py")
        jd = str(tmp_path / "j")
        comp = str(tmp_path / "completions.json")
        argv = ["serve_bench.py", "--requests", "6", "--rate", "400",
                "--max-batch", "2", "--num-layers", "1",
                "--num-heads", "2", "--hidden-dim", "32",
                "--model-max-len", "64", "--prompt-len", "8",
                "--max-new-tokens", "8", "--prefill-chunk", "8",
                "--virtual-dt", "2", "--journal-dir", jd,
                "--completions-out", comp]
        monkeypatch.setattr("sys.argv", argv)
        assert bench.main() == 0
        stats = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert stats["requests_finished"] == 6
        assert stats["requests_recovered"] == 0
        assert stats["tokens_recomputed_on_recovery"] == 0
        assert stats["journal_records_written"] > 0
        first = {c["uid"]: c for c in json.load(open(comp))}
        assert len(first) == 6

        monkeypatch.setattr("sys.argv", argv)
        assert bench.main() == 0
        stats2 = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert stats2["requests_finished"] == 0
        assert stats2["requests_recovered"] == 0
        assert json.load(open(comp)) == []
