"""Per-request latency ledger: conserved millisecond attribution.

Load-bearing properties, in order of importance:

1. **Conservation** (the invariant): every finished request's
   ``(cause, start, end)`` intervals partition its wall lifetime —
   ``sum(intervals) == finish_t − arrival_t`` within
   ``ledger.EPSILON_S`` — under EVERY composition the engine supports:
   greedy/sampled × paged/legacy × speculation on/off × preemption ×
   hot-swap × crash recovery, and for queue-side completions (timeout,
   shed) that never reached a slot.
2. **TTFT decomposition**: for an unpreempted, unrecovered request,
   ``queue_wait + prefill (+ journal_admit) == TTFT`` exactly — the
   ledger's totals reproduce the independently measured SLA number.
3. **Deterministic token attribution**: the per-cause token counters
   are pure functions of each request's token stream
   (``ledger_tokens_decode == tokens_emitted``,
   ``ledger_tokens_recompute`` mirrors the preempt/recovery recompute
   counters) — the zero-drift evidence the bench gate holds.
4. **Audit enforcement**: a tampered or unclosed ledger is COUNTED
   (``ledger_conservation_violations``) — the invariant is checked
   in-engine at every completion, not post-hoc.
5. **Window-reset semantics** (round-17 precedent extended): the
   per-cause LIFETIME histograms and the violation audit survive
   ``Engine.reset_stats``; the windowed token counters start fresh.

Engines compile real XLA programs, so the model is tiny and the tier-1
matrix covers every axis value pairwise; the full 8-way product runs
under ``-m slow`` (the CI ledger drill exercises the big
preempt-storm × swap × spec composition through serve_bench).
"""

import time

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.serving import (
    FINISH_TIMEOUT,
    Engine,
    FinishedRequest,
    LatencyLedger,
    QueueFullError,
    ServeTelemetry,
)
from distributed_training_tpu.serving.ledger import (
    CAUSE_DECODE,
    CAUSE_JOURNAL_ADMIT,
    CAUSE_PREEMPT_REQUEUE,
    CAUSE_PREFILL,
    CAUSE_QUEUE_WAIT,
    CAUSE_RECOMPUTE,
    CAUSE_RECOVERY,
    CAUSE_SWAP_BARRIER,
    EPSILON_S,
    LEDGER_CAUSES,
    TOKEN_CAUSES,
)

VOCAB = 31
MAX_LEN = 48


@pytest.fixture(scope="module")
def lm():
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=1, num_heads=2,
        hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def lm_params2(lm):
    model, _ = lm
    return model.init(jax.random.PRNGKey(1),
                      np.zeros((1, 8), np.int32))["params"]


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(3)
    return [rng.randint(0, VOCAB, size=l).astype(np.int32)
            for l in (5, 7, 3, 6)]


def _audit(fins, engine=None):
    """Every finished request's ledger closed and conserved; zero
    engine-side violations."""
    assert fins
    for f in fins:
        led = f.ledger
        assert led is not None and led.closed, f"uid {f.uid}: no ledger"
        v = led.violations(ttft_ms=f.ttft_ms)
        assert not v, f"uid {f.uid} ({f.finish_reason}): {v}"
    if engine is not None:
        st = engine.stats()
        assert st["ledger_conservation_violations"] == 0, st


def _ttft_split(fins):
    """Property 2: queue_wait + prefill (+ journal_admit) == TTFT for
    every request untouched by preemption/recovery."""
    checked = 0
    for f in fins:
        if f.ttft_ms is None:
            continue
        totals = f.ledger.totals_ms()
        if any(totals.get(c) for c in (CAUSE_PREEMPT_REQUEUE,
                                       CAUSE_RECOMPUTE, CAUSE_RECOVERY)):
            continue
        split = (totals.get(CAUSE_QUEUE_WAIT, 0.0)
                 + totals.get(CAUSE_PREFILL, 0.0)
                 + totals.get(CAUSE_JOURNAL_ADMIT, 0.0)
                 + totals.get(CAUSE_SWAP_BARRIER, 0.0))
        assert abs(split - f.ttft_ms) <= EPSILON_S * 1e3 * 4, (
            f.uid, split, f.ttft_ms, totals)
        checked += 1
    assert checked > 0


class TestLedgerUnit:
    def test_stamp_coalesce_clamp_and_totals(self):
        led = LatencyLedger(10.0)
        led.stamp("queue_wait", 11.0)
        led.stamp("prefill", 11.5)
        led.stamp("prefill", 12.0)      # coalesces with the previous
        led.stamp("decode", 11.0)       # clock glitch: clamps, 0-width
        led.stamp("decode", 13.0)
        assert [iv[0] for iv in led.intervals] == [
            "queue_wait", "prefill", "decode"]
        assert led.total_s("prefill") == pytest.approx(1.0)
        led.add_tokens("decode", 3)
        led.add_tokens("decode", 2)
        assert led.tokens == {"decode": 5}
        led.close("decode", 13.25)
        assert led.closed and led.finish_t == pytest.approx(13.25)
        assert not led.violations()
        assert led.lifetime_ms == pytest.approx(3250.0)
        d = led.to_dict()
        assert d["conserved"] and len(d["intervals"]) == 3

    def test_admit_handoff_materializes_on_engine_stamp(self):
        """The journal_admit span is a producer-thread HANDOFF (one
        attribute store); the interval itself is appended by the next
        engine-side stamp — and if the engine raced ahead (seated the
        request before the fsync returned), the span clamps away
        without ever breaking conservation."""
        led = LatencyLedger(0.0)
        led.note_admit_done(0.004)
        led.stamp(CAUSE_QUEUE_WAIT, 0.010)  # seat materializes both
        assert [iv[0] for iv in led.intervals] == [
            CAUSE_JOURNAL_ADMIT, CAUSE_QUEUE_WAIT]
        assert led.total_s(CAUSE_JOURNAL_ADMIT) == pytest.approx(0.004)
        led.close(CAUSE_DECODE, 0.020)
        assert not led.violations()
        # Raced: the engine seated BEFORE the admit write returned —
        # the admission span clamps away entirely, even when the
        # admit-done instant lands AFTER the seat (billing the post-
        # seat span to journal_admit would mislabel in-slot work).
        for admit_t in (0.002, 0.015):
            led2 = LatencyLedger(0.0)
            led2.stamp(CAUSE_QUEUE_WAIT, 0.010)
            led2.note_admit_done(admit_t)
            led2.close(CAUSE_DECODE, 0.020)
            assert led2.total_s(CAUSE_JOURNAL_ADMIT) == 0.0
            assert led2.total_s(CAUSE_DECODE) == pytest.approx(0.010)
            assert not led2.violations()

    def test_unclosed_and_tampered_ledgers_violate(self):
        led = LatencyLedger(0.0)
        led.stamp("queue_wait", 1.0)
        assert led.violations()  # never closed
        led.close("decode", 2.0)
        assert not led.violations()
        # Tamper: an interval that no longer telescopes breaks the sum.
        led.intervals[0][2] = 0.5
        v = led.violations()
        assert v and "sum(intervals)" in v[0]

    def test_ttft_boundary_and_early_decode_checks(self):
        led = LatencyLedger(0.0)
        led.stamp("queue_wait", 0.010)
        led.stamp("prefill", 0.020)
        led.stamp("decode", 0.050)
        led.close("decode")
        assert not led.violations(ttft_ms=20.0)
        # First token instant not on a stamp boundary:
        assert any("boundary" in s for s in led.violations(ttft_ms=15.0))
        # decode attributed before the first token:
        assert any("before the first token" in s
                   for s in led.violations(ttft_ms=60.0))

    def test_telemetry_counts_violations(self):
        tel = ServeTelemetry(64)
        led = LatencyLedger(0.0)
        led.stamp("queue_wait", 1.0)  # never closed -> violation
        fin = FinishedRequest(
            uid=7, prompt=np.zeros((2,), np.int32),
            tokens=np.zeros((0,), np.int32),
            finish_reason=FINISH_TIMEOUT, ttft_ms=None, tpot_ms=None,
            arrival_t=0.0, first_token_t=None, ledger=led)
        tel.on_finished(fin)
        assert tel.ledger_conservation_violations == 1
        assert "uid 7" in tel.ledger_violation_last
        st = tel.stats()
        assert st["ledger_conservation_violations"] == 1
        # Redelivered results (no ledger) are skipped, never violations.
        tel.on_finished(FinishedRequest(
            uid=8, prompt=np.zeros((2,), np.int32),
            tokens=np.zeros((0,), np.int32),
            finish_reason=FINISH_TIMEOUT, ttft_ms=None, tpot_ms=None,
            arrival_t=0.0, first_token_t=None))
        assert tel.ledger_conservation_violations == 1

    def test_stats_keys_always_present(self):
        st = ServeTelemetry(64).stats()
        for c in LEDGER_CAUSES:
            assert st[f"ledger_{c}_ms_total"] == 0.0
        for c in TOKEN_CAUSES:
            assert st[f"ledger_tokens_{c}"] == 0
        assert st["ledger_requests"] == 0
        assert st["ledger_conservation_violations"] == 0


# Every axis value (greedy/sampled, paged/legacy, spec 0/2) appears at
# least twice across the tier-1 cases without the full 8-way product.
MATRIX_T1 = [
    ({"prefill_chunk": 4}, 0.0),
    ({"prefill_chunk": 4, "spec_k": 2}, 0.8),
    ({"kv_page_size": None, "prefill_bucket": 8}, 0.8),
    ({"kv_page_size": None, "prefill_bucket": 8, "spec_k": 2,
      "max_len": 40}, 0.0),
]
MATRIX_FULL = [
    (dict(base, **({} if spec == 0 else {"spec_k": spec,
                                         **({"max_len": 40}
                                            if "kv_page_size" in base
                                            else {})})), temp)
    for base in ({"prefill_chunk": 4},
                 {"kv_page_size": None, "prefill_bucket": 8})
    for spec in (0, 2)
    for temp in (0.0, 0.8)
]


class TestConservationMatrix:
    def _run(self, lm, prompts, cfg_kw, temp):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=6, temperature=temp, **cfg_kw))
        for p in prompts:
            eng.submit(p)
        done = eng.run()
        assert len(done) == len(prompts)
        _audit(done, eng)
        _ttft_split(done)
        st = eng.stats()
        assert st["ledger_requests"] == len(prompts)
        assert st["ledger_tokens_decode"] == st["tokens_emitted"]
        assert st["ledger_tokens_prefill"] == sum(p.size for p in prompts)
        assert st["ledger_tokens_recompute"] == 0
        if cfg_kw.get("spec_k"):
            assert st["ledger_tokens_spec_draft"] == st["drafted_tokens"]
            assert st["ledger_tokens_spec_accept"] == \
                st["accepted_tokens"]

    @pytest.mark.parametrize("cfg_kw,temp", MATRIX_T1)
    def test_conservation(self, lm, prompts, cfg_kw, temp):
        self._run(lm, prompts, cfg_kw, temp)

    @pytest.mark.slow
    @pytest.mark.parametrize("cfg_kw,temp", MATRIX_FULL)
    def test_conservation_full(self, lm, prompts, cfg_kw, temp):
        self._run(lm, prompts, cfg_kw, temp)


class TestChaosCompositions:
    def test_preempt_swap_spec_conserves(self, lm, lm_params2, prompts):
        """Preemption × hot-swap barrier × speculation in one run: the
        evicted request's ledger carries preempt_requeue + recompute,
        in-flight requests carry swap_barrier, everything conserves,
        and the recompute token counter mirrors the engine-global one."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=8, num_tiers=2,
            prefill_chunk=4, spec_k=2))
        eng.submit(prompts[0], priority=1, max_new_tokens=8)
        for _ in range(3):
            eng.step()
        eng.submit(prompts[1], priority=0, max_new_tokens=4)
        eng.arm_swap(lm_params2, epoch=1)
        done = eng.run()
        assert len(done) == 2
        st = eng.stats()
        assert st["requests_preempted"] == 1
        assert st["swaps_completed"] == 1
        _audit(done, eng)
        preempted = [f for f in done
                     if f.ledger.totals_ms().get(CAUSE_PREEMPT_REQUEUE)]
        assert len(preempted) == 1
        assert preempted[0].ledger.totals_ms().get(CAUSE_RECOMPUTE)
        assert any(CAUSE_SWAP_BARRIER in f.ledger.totals_ms()
                   for f in done)
        assert st["ledger_tokens_recompute"] == \
            st["preempted_token_recompute"]

    def test_mid_prefill_preempt_token_split(self, lm):
        """A request preempted MID-prefill re-prefills its whole prompt,
        but only the positions it had actually written count as
        recompute — the never-written tail stays first-time 'prefill'
        work, so ledger_tokens_prefill == the prompt size exactly and
        ledger_tokens_recompute == preempted_token_recompute."""
        model, params = lm
        rng = np.random.RandomState(7)
        long_prompt = rng.randint(0, VOCAB, size=16).astype(np.int32)
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=4, num_tiers=2,
            prefill_chunk=4))
        eng.submit(long_prompt, priority=1)
        eng.step()  # one 4-token chunk written, 12 to go
        seq = eng.scheduler.sequence(0)
        assert seq.prefilling and 0 < seq.prefill_pos < 16
        written = seq.prefill_pos
        eng.submit(rng.randint(0, VOCAB, size=3).astype(np.int32),
                   priority=0, max_new_tokens=2)
        done = eng.run()
        st = eng.stats()
        assert st["requests_preempted"] == 1
        assert st["preempted_token_recompute"] == written
        assert st["ledger_tokens_recompute"] == written
        assert st["ledger_tokens_prefill"] == 16 + 3
        _audit(done, eng)

    @pytest.mark.parametrize("temp", [0.0, 0.8])
    def test_crash_recovery_conserves(self, lm, prompts, tmp_path, temp):
        """Kill/restart on the journal: resumed requests bill pre_crash
        (durable tokens) + recovery (downtime/replay, wall-anchored) +
        recompute (the re-prefill), conserve exactly, and the recompute
        token counter mirrors tokens_recomputed_on_recovery."""
        model, params = lm
        cfg = dict(max_batch=2, max_new_tokens=8, prefill_chunk=4,
                   temperature=temp, journal_dir=str(tmp_path))
        eng = Engine(model, params, ServeConfig(**cfg))
        eng.recover()
        for p in prompts[:3]:
            eng.submit(p)
        for _ in range(4):
            eng.step()
        eng.journal.persist()
        eng.journal.crash()

        eng2 = Engine(model, params, ServeConfig(**cfg))
        rep = eng2.recover()
        done = eng2.run()
        st = eng2.stats()
        assert st["requests_recovered"] == 3
        fins = done + rep["completed_at_replay"]
        _audit(fins, eng2)
        resumed = [f for f in done
                   if f.ledger.totals_ms().get(CAUSE_RECOVERY)]
        assert resumed
        assert st["ledger_tokens_recompute"] == \
            st["tokens_recomputed_on_recovery"]
        # Redelivered results carry no ledger and are not audited.
        assert all(f.ledger is None for f in rep["redelivered"])
        assert st["ledger_conservation_violations"] == 0

    def test_queue_timeout_and_shed_conserve(self, lm, prompts):
        """The unit pin the issue names: requests finished with reason
        timeout (queue-side deadline) or shed (tier-aware drop) still
        conserve — their whole lifetime bills to waiting causes."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=4, prefill_chunk=4, num_tiers=2,
            max_queue_depth=2, ttft_deadline_ms=1.0))
        eng.submit(prompts[0], priority=1)
        eng.submit(prompts[1], priority=1)
        # Full queue + higher tier -> the newest tier-1 entry sheds.
        eng.submit(prompts[2], priority=0)
        time.sleep(0.005)  # run out the 1 ms TTFT deadlines
        done = eng.drain()
        st = eng.stats()
        reasons = sorted(f.finish_reason for f in done)
        assert "shed" in reasons and "timeout" in reasons, reasons
        _audit(done, eng)
        for f in done:
            if f.tokens.size == 0:  # never served: waiting causes only
                assert set(f.ledger.totals_ms()) <= {
                    CAUSE_QUEUE_WAIT, CAUSE_PREEMPT_REQUEUE}, \
                    f.ledger.totals_ms()

    def test_slot_deadline_eviction_conserves(self, lm, prompts):
        """A mid-decode total-deadline eviction (partial tokens) closes
        the ledger at the eviction boundary and conserves."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=40, prefill_chunk=4,
            deadline_ms=30.0))
        eng.submit(prompts[0])
        done = eng.run()
        assert len(done) == 1
        assert done[0].finish_reason in ("timeout", "length")
        _audit(done, eng)

    def test_queue_full_shed_at_submit_has_no_completion(self, lm,
                                                         prompts):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=4, max_queue_depth=1))
        eng.submit(prompts[0])  # queued (nothing has stepped yet)
        with pytest.raises(QueueFullError):
            eng.submit(prompts[1])  # full queue, nothing lower to shed
        done = eng.drain()
        assert len(done) == 1  # the rejected request never existed
        _audit(done, eng)


class TestLedgerTelemetry:
    def test_reset_stats_preserves_lifetime_histograms(self, lm,
                                                       prompts):
        """The round-17 precedent extended (the issue's bugfix): a
        warm-up window reset must preserve the per-cause lifetime
        histograms AND the conservation audit, while the windowed
        deterministic counters start fresh."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=4, prefill_chunk=4))
        for p in prompts[:2]:
            eng.submit(p)
        eng.run()
        tel = eng.telemetry
        decode_hist = tel.ledger_cause_ms[CAUSE_DECODE]
        assert decode_hist.total > 0
        counts_before = {c: tel.ledger_cause_ms[c].total
                         for c in LEDGER_CAUSES}
        # Stage a violation so the audit-carry is observable too.
        bad = LatencyLedger(0.0)
        bad.stamp(CAUSE_QUEUE_WAIT, 1.0)  # never closed
        tel.on_finished(FinishedRequest(
            uid=99, prompt=np.zeros((1,), np.int32),
            tokens=np.zeros((0,), np.int32),
            finish_reason=FINISH_TIMEOUT, ttft_ms=None, tpot_ms=None,
            arrival_t=0.0, first_token_t=None, ledger=bad))
        eng.reset_stats()
        st = eng.stats()
        # Lifetime evidence preserved...
        for c in LEDGER_CAUSES:
            assert eng.telemetry.ledger_cause_ms[c].total == \
                (counts_before[c] + (1 if c == CAUSE_QUEUE_WAIT else 0))
        assert st["ledger_conservation_violations"] == 1
        # ...windowed surfaces fresh: the SLA line's per-cause totals
        # describe only the requests the new window audits (warm-up
        # wall time never pollutes the decomposition).
        assert st["ledger_requests"] == 0
        for c in TOKEN_CAUSES:
            assert st[f"ledger_tokens_{c}"] == 0
        for c in LEDGER_CAUSES:
            assert st[f"ledger_{c}_ms_total"] == 0.0
        assert eng.telemetry.ledger_top == []

    def test_flight_surfaces_carry_ledger(self, lm, prompts, tmp_path):
        """The per-cause histograms and the slowest-request
        decomposition ride the serving section of dumps and live
        scrapes (strict JSON, flight_report-renderable)."""
        import json

        from distributed_training_tpu.observability.flight_recorder \
            import FlightRecorder

        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=4, prefill_chunk=4))
        for p in prompts:
            eng.submit(p)
        done = eng.run()
        snap = eng.flight_snapshot()
        srv = snap["serving"]
        assert srv["ledger_requests"] == len(done)
        assert f"ledger_{CAUSE_DECODE}_ms" in srv["histograms"]
        top = srv["ledger_top"]
        assert top and top[0]["lifetime_ms"] >= top[-1]["lifetime_ms"]
        assert set(top[0]["causes_ms"]) <= set(LEDGER_CAUSES)
        json.dumps(snap, allow_nan=False)  # strict JSON or bust
        path = str(tmp_path / "ledger_flight.json")
        eng.dump_flight(path)
        loaded = FlightRecorder.load(path)
        assert loaded["serving"]["ledger_requests"] == len(done)

        import tools.flight_report as fr

        text = fr.render(fr.summarize(loaded))
        assert "latency ledger" in text
        assert "0 conservation violation(s)" in text
