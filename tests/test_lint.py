"""graftlint: one positive + one negative fixture per rule, the CLI
exit-code contract, waiver semantics, and the zero-finding self-lint.

The lock-signal-safety positive is a minimal reproduction of the
round-13 bug the rule exists for (an inline SIGUSR1 rollback taking the
engine's non-reentrant swap lock); its negative is the shipped fix
(the handler only sets a ``threading.Event``). Fixtures run through
:func:`tools.lint.run_lint` in-process — no subprocesses — per the
round-8 keep-tier-1-lean note.
"""

import os
import textwrap

import pytest

from tools.lint import LintInputError, run_lint
from tools.lint.__main__ import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, source, rule, name="snippet.py"):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, _ = run_lint([str(path)], rules=[rule])
    return findings


def _exit_code(tmp_path, source, rule, name="snippet.py"):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return lint_main([str(path), "--rule", rule])


class TestHotPathTransfer:
    POSITIVE = """
        class Engine:
            def step(self):
                self._advance()
                return self.loss.item()
    """

    def test_positive_exits_1(self, tmp_path, capsys):
        assert _exit_code(tmp_path, self.POSITIVE,
                          "hot-path-transfer") == 1
        assert ".item()" in capsys.readouterr().out

    def test_negative_host_side_step_is_clean(self, tmp_path):
        # Same hot scope, host-side bookkeeping only — and the same
        # .item() OUTSIDE any hot scope is not the rule's business.
        assert not _lint(tmp_path, """
            class Engine:
                def step(self):
                    self._advance()
                    return self.counters["tokens"]

            def summarize(metrics):
                return metrics.item()
        """, "hot-path-transfer")

    def test_sync_journal_write_in_step_flagged(self, tmp_path, capsys):
        # The crash-durability round's bug class: a journal append that
        # fsyncs (or opens a file) inside Engine.step's compiled-
        # dispatch window stalls every decode slot on storage latency.
        assert _exit_code(tmp_path, """
            import os

            class Journal:
                def append(self, rec):
                    self._log = open("/data/wal.log", "ab")
                    self._log.write(rec)
                    os.fsync(self._log.fileno())

            class Engine:
                def step(self):
                    self.journal.append(b"tok")
        """, "hot-path-transfer") == 1
        out = capsys.readouterr().out
        assert "fsync" in out and "open(" in out

    def test_negative_enqueue_only_journal_append_is_clean(self,
                                                           tmp_path):
        # The shipped design: the hot path only ENQUEUES; the writer
        # thread (not reachable from Engine.step) owns open/fsync.
        assert not _lint(tmp_path, """
            import os

            class Journal:
                def append(self, rec):
                    with self._lock:
                        self._pending.append(rec)

                def _writer_loop(self):
                    fd = os.open("/data/wal.log", os.O_APPEND)
                    os.write(fd, self._drain())
                    os.fsync(fd)

            class Engine:
                def step(self):
                    self.journal.append(b"tok")
        """, "hot-path-transfer")

    def test_jitted_function_is_a_hot_root(self, tmp_path):
        findings = _lint(tmp_path, """
            import jax

            @jax.jit
            def fused(x):
                x.block_until_ready()
                return x
        """, "hot-path-transfer")
        assert len(findings) == 1 and "block_until_ready" in \
            findings[0].message


class TestScrapeSafety:
    def test_positive_handler_reaching_flush_exits_1(self, tmp_path):
        assert _exit_code(tmp_path, """
            class Handler:
                def do_GET(self):
                    self._respond(self._snapshot())

                def _snapshot(self):
                    self.recorder.flush()
                    return self.recorder.stats()
        """, "scrape-safety") == 1

    def test_negative_read_only_handler_is_clean(self, tmp_path):
        assert not _lint(tmp_path, """
            class Handler:
                def do_GET(self):
                    self._respond(self._snapshot())

                def _snapshot(self):
                    return dict(self.recorder.stats())
        """, "scrape-safety")

    def test_positive_control_room_provider_mutating_exits_1(
            self, tmp_path, capsys):
        # The control-room bug class this rule now guards: a
        # /timeseries or /alerts provider that force-fills the ring or
        # re-runs alert evaluation on the handler thread races the
        # engine thread's sampling cadence and double-counts fires.
        assert _exit_code(tmp_path, """
            class Engine:
                def timeseries_snapshot(self):
                    self.timeseries.record_sample(self._sample())
                    return self.timeseries.to_dict()

                def alerts_snapshot(self):
                    self.alerts.evaluate(self.timeseries, 0)
                    return self.alerts.to_dict()
        """, "scrape-safety") == 1
        out = capsys.readouterr().out
        assert "record_sample" in out and "evaluate" in out

    def test_negative_control_room_to_dict_views_are_clean(
            self, tmp_path):
        # The shipped design: providers return to_dict() views only;
        # record_sample/evaluate/capture live on the engine thread.
        assert not _lint(tmp_path, """
            class Engine:
                def timeseries_snapshot(self):
                    return self.timeseries.to_dict(last_n=64)

                def alerts_snapshot(self):
                    return self.alerts.to_dict()
        """, "scrape-safety")

    def test_positive_post_handler_driving_engine_exits_1(
            self, tmp_path, capsys):
        # The network-front-door bug class (round 22): a /generate
        # handler that steps the engine itself — instead of submitting
        # and letting the frontend's single serve-loop thread step —
        # races the scheduler and double-dispatches compiled programs.
        assert _exit_code(tmp_path, """
            class Handler:
                def do_POST(self):
                    self.engine.submit(self._parse())
                    self.engine.step()
        """, "scrape-safety") == 1
        assert "engine-driving" in capsys.readouterr().out

    def test_positive_probe_endpoint_mutating_trie_exits_1(
            self, tmp_path, capsys):
        # A routing probe must read residency, never claim pages — a
        # claim from the router's probe thread leaks refcounts against
        # requests that may never arrive.
        assert _exit_code(tmp_path, """
            class Engine:
                def probe_snapshot(self, tokens):
                    pages = self.prefix_cache.claim(tokens)
                    return {"hit_tokens": len(pages) * 8}
        """, "scrape-safety") == 1
        assert "prefix-trie mutation" in capsys.readouterr().out

    def test_positive_supervisor_snapshot_killing_exits_1(
            self, tmp_path, capsys):
        # The fleet-fault-tolerance bug class: a supervisor_snapshot
        # that notices a dead proc and restarts it INLINE runs the
        # restart ladder on the scrape thread, racing the monitor
        # thread's own death detection (double restart, double count).
        assert _exit_code(tmp_path, """
            class Supervisor:
                def supervisor_snapshot(self):
                    for i, h in enumerate(self.handles):
                        if h.proc.poll() is not None:
                            self.kill(i)
                    return {"replica_restarts": self.replica_restarts}
        """, "scrape-safety") == 1
        assert "fleet-supervision mutation" in capsys.readouterr().out

    def test_positive_router_snapshot_tripping_breaker_exits_1(
            self, tmp_path, capsys):
        # A counter view that trips breakers: two concurrent scrapes
        # double-count breaker_opens and can evict a healthy replica
        # from rotation without a single failed request.
        assert _exit_code(tmp_path, """
            class Router:
                def router_snapshot(self):
                    for i, r in enumerate(self.replicas):
                        if not self._reachable(r):
                            self.note_replica_failure(i)
                    return {"router_breaker_opens": self.breaker_opens}
        """, "scrape-safety") == 1
        assert "note_replica_failure" in capsys.readouterr().out

    def test_negative_breaker_accounting_on_proxy_thread_is_clean(
            self, tmp_path):
        # The shipped design: the do_POST proxy thread OWNS breaker
        # accounting (it observed the failure) and the failover-resume
        # counter; the snapshot providers are lock-guarded reads. The
        # snapshot-only clause must not flag the proxy path.
        assert not _lint(tmp_path, """
            class Supervisor:
                def supervisor_snapshot(self):
                    with self._lock:
                        return {
                            "replica_restarts": self.replica_restarts,
                            "restarts_by_replica": list(self._restarts),
                        }

            class Router:
                def do_POST(self):
                    idx = self._route_one()
                    try:
                        self._relay(idx)
                        self.note_replica_success(idx)
                    except OSError:
                        self.note_replica_failure(idx)
                        self.note_failover_resume()

                def router_snapshot(self):
                    with self._lock:
                        return {
                            "router_breaker_opens": self.breaker_opens,
                            "breaker_state": list(self._brk_state),
                        }
        """, "scrape-safety")

    def test_negative_front_door_admission_surface_is_clean(
            self, tmp_path):
        # The shipped round-22 design: the handler submits (lock-
        # guarded queue work), acks the journal delivery cursor, and
        # reads the probe via the read-only PrefixCache.probe; the
        # serve loop owns step/drain/arm_swap. router_snapshot is a
        # counter view.
        assert not _lint(tmp_path, """
            class Engine:
                def probe_snapshot(self, tokens):
                    hit = self.prefix_cache.probe(tokens, max_tokens=4)
                    return {"hit_tokens": len(hit) * 8}

            class Handler:
                def do_POST(self):
                    req = self.engine.submit(self._parse())
                    self._stream(req)
                    self.engine.journal.ack([req.uid])

            class Router:
                def router_snapshot(self):
                    with self._lock:
                        return {"routed": self.requests_routed}
        """, "scrape-safety")

    def test_positive_fleet_get_tripping_breaker_exits_1(
            self, tmp_path, capsys):
        # The federated-telemetry-plane bug class: a /fleet/metrics
        # fan-out that treats an unreachable replica as a FAILURE and
        # trips the breaker from the GET handler thread turns the
        # monitoring plane into a fault injector — a dashboard refresh
        # that opens a breaker IS an outage. Unreachable replicas get a
        # deterministic stale marker instead.
        assert _exit_code(tmp_path, """
            class Door:
                def do_GET(self):
                    self._respond(self._fleet_scrape())

                def _fleet_scrape(self):
                    out = {}
                    for i, rep in enumerate(self.replicas):
                        try:
                            out[rep.name] = rep.scrape_text("/metrics")
                        except OSError:
                            self.router.note_replica_failure(i)
                    return out
        """, "scrape-safety") == 1
        out = capsys.readouterr().out
        assert "GET scrape path" in out and "stale" in out

    def test_positive_fleet_get_restarting_replica_exits_1(
            self, tmp_path, capsys):
        # Same clause, supervision flavor: a GET that force-restarts a
        # stale replica races the supervisor's monitor thread (double
        # restart, double count) — and does so once per scraper.
        assert _exit_code(tmp_path, """
            class Door:
                def do_GET(self):
                    rows = []
                    for i, rep in enumerate(self.replicas):
                        if self._stale(rep):
                            self.supervisor.force_restart(i)
                        rows.append({"replica": rep.name})
                    self._respond(rows)
        """, "scrape-safety") == 1
        assert "force_restart" in capsys.readouterr().out

    def test_negative_fleet_scrape_with_stale_markers_is_clean(
            self, tmp_path):
        # The shipped design: fleet_snapshot is a counter view; the
        # /fleet fan-out marks breaker-open and unreachable replicas
        # stale and never touches breaker or supervision state. The
        # do_POST proxy keeps its legitimate note_* ownership alongside.
        assert not _lint(tmp_path, """
            class Door:
                def do_GET(self):
                    self._respond({
                        "fleet": self.fleet_snapshot(),
                        "replicas": self._fleet_scrape(),
                    })

                def fleet_snapshot(self):
                    with self._fleet_lock:
                        return {
                            "fleet_ledger_requests": self._led_requests,
                        }

                def _fleet_scrape(self):
                    out = {}
                    for i, rep in enumerate(self.replicas):
                        if self.router.breaker_open(i):
                            out[rep.name] = {"stale": True,
                                             "reason": "breaker_open"}
                            continue
                        try:
                            out[rep.name] = rep.scrape_json("/vars")
                        except OSError:
                            out[rep.name] = {"stale": True,
                                             "reason": "unreachable"}
                    return out

                def do_POST(self):
                    idx = self._route_one()
                    try:
                        self._relay(idx)
                        self.router.note_replica_success(idx)
                    except OSError:
                        self.router.note_replica_failure(idx)
        """, "scrape-safety")


class TestLockSignalSafety:
    # The pre-fix round-13 hot-swap pattern, minimized: serve()'s
    # SIGUSR1 handler runs the rollback INLINE, and the rollback takes
    # the engine's non-reentrant _swap_lock — which the serving loop
    # holds around the swap barrier on the very thread the signal
    # interrupts.
    ROUND13_BUG = """
        import signal
        import threading

        class Engine:
            def __init__(self):
                self._swap_lock = threading.Lock()
                self.params = None
                self._prev_params = None

            def rollback(self):
                with self._swap_lock:
                    self.params = self._prev_params

        def serve(engine):
            signal.signal(signal.SIGUSR1,
                          lambda *_: engine.rollback())
    """
    # The shipped fix: the handler only sets an Event; the watcher
    # thread services the rollback.
    ROUND13_FIX = """
        import signal
        import threading

        class HotSwapper:
            def __init__(self):
                self._rollback_requested = threading.Event()

            def request_rollback(self):
                self._rollback_requested.set()

        def serve(swapper):
            signal.signal(signal.SIGUSR1,
                          lambda *_: swapper.request_rollback())
    """

    def test_flags_the_round13_inline_rollback(self, tmp_path, capsys):
        assert _exit_code(tmp_path, self.ROUND13_BUG,
                          "lock-signal-safety") == 1
        out = capsys.readouterr().out
        assert "_swap_lock" in out and "signal handler" in out

    def test_negative_event_setting_handler_is_clean(self, tmp_path):
        assert not _lint(tmp_path, self.ROUND13_FIX,
                         "lock-signal-safety")

    def test_round13_shape_in_acquire_release_style(self, tmp_path):
        # The same deadlock written WITHOUT a with-statement — bare
        # acquire()/try/finally — must not lint clean: acquire() holds
        # for the rest of the sequence until release().
        findings = _lint(tmp_path, """
            import threading

            class Engine:
                def __init__(self):
                    self._swap_lock = threading.Lock()

                def barrier(self):
                    self._swap_lock.acquire()
                    try:
                        self.rollback()
                    finally:
                        self._swap_lock.release()

                def rollback(self):
                    with self._swap_lock:
                        pass
        """, "lock-signal-safety")
        assert len(findings) == 1 and "non-reentrant" in \
            findings[0].message

    def test_release_ends_the_acquire_style_hold(self, tmp_path):
        assert not _lint(tmp_path, """
            import threading

            class Engine:
                def __init__(self):
                    self._swap_lock = threading.Lock()

                def barrier(self):
                    self._swap_lock.acquire()
                    snapshot = dict(self.state)
                    self._swap_lock.release()
                    self.rollback()

                def rollback(self):
                    with self._swap_lock:
                        pass
        """, "lock-signal-safety")

    def test_lock_order_inversion(self, tmp_path):
        findings = _lint(tmp_path, """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def forward():
                with a:
                    with b:
                        pass

            def backward():
                with b:
                    with a:
                        pass
        """, "lock-signal-safety")
        assert len(findings) == 1 and "inversion" in findings[0].message

    def test_inversion_found_through_a_call_cycle(self, tmp_path):
        # Regression: the lock closure must be a fixpoint over the
        # reachable set — a memoized recursion caches an EMPTY set for
        # whichever function a cycle was entered through, and whether
        # the inversion was reported then depended on traversal order.
        findings = _lint(tmp_path, """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def ping(n):
                with a:
                    pass
                pong(n)

            def pong(n):
                ping(n)

            def caller_one():
                # Enters the cycle through ping (the acquirer): the
                # buggy recursion memoized pong's closure as EMPTY here,
                # hiding holds_b's b->a edge below.
                with a:
                    ping(1)

            def holds_b():
                with b:
                    pong(2)

            def holds_a_then_b():
                with a:
                    with b:
                        pass
        """, "lock-signal-safety")
        assert any("inversion" in f.message for f in findings), \
            [f.message for f in findings]

    def test_reacquire_through_a_call_while_held(self, tmp_path):
        findings = _lint(tmp_path, """
            import threading

            class Engine:
                def __init__(self):
                    self._swap_lock = threading.Lock()

                def barrier(self):
                    with self._swap_lock:
                        self.rollback()

                def rollback(self):
                    with self._swap_lock:
                        pass
        """, "lock-signal-safety")
        assert len(findings) == 1 and "non-reentrant" in \
            findings[0].message


class TestStaticShape:
    def test_positive_branch_on_traced_value_exits_1(self, tmp_path):
        assert _exit_code(tmp_path, """
            import jax

            @jax.jit
            def step(x, n):
                if n > 0:
                    return x
                return -x
        """, "static-shape") == 1

    def test_negative_static_guards_are_clean(self, tmp_path):
        assert not _lint(tmp_path, """
            from functools import partial

            import jax

            @partial(jax.jit, static_argnames=("n",))
            def step(x, n, mask=None):
                if n > 0:                 # static by declaration
                    x = x * n
                if mask is not None:      # identity test: static
                    x = x * mask
                if x.ndim == 2:           # shape attr: static
                    x = x.sum(axis=-1)
                return x
        """, "static-shape")


class TestDeterminism:
    def test_positive_unseeded_rng_and_wall_clock_exit_1(self, tmp_path,
                                                         capsys):
        assert _exit_code(tmp_path, """
            import random
            import time

            import numpy as np

            def corrupt_sample(batch):
                if random.random() < 0.5:
                    batch = batch + np.random.rand(*batch.shape)
                return batch, time.time()
        """, "determinism") == 1
        out = capsys.readouterr().out
        assert "random.random()" in out and "np.random.rand()" in out \
            and "time.time()" in out

    def test_negative_seeded_streams_and_intervals_clean(self, tmp_path):
        assert not _lint(tmp_path, """
            import time

            import numpy as np

            def augment(batch, seed):
                rng = np.random.RandomState(seed)
                t0 = time.perf_counter()
                return batch + rng.rand(*batch.shape), \\
                    time.perf_counter() - t0
        """, "determinism")

    def test_observability_files_are_allowlisted(self, tmp_path):
        assert not _lint(tmp_path, """
            import time

            def wall_stamp():
                return time.time()
        """, "determinism", name=os.path.join("observability",
                                              "clock.py"))


class TestArgparsePercent:
    def test_positive_bare_percent_exits_1(self, tmp_path):
        # The round-11 crash verbatim: one bare '%' in a help string.
        assert _exit_code(tmp_path, """
            import argparse

            p = argparse.ArgumentParser()
            p.add_argument("--remat", help="cuts activation memory "
                                           "by ~50% at 1/3 recompute")
        """, "argparse-percent") == 1

    def test_negative_escaped_and_mapping_forms_clean(self, tmp_path):
        assert not _lint(tmp_path, """
            import argparse

            p = argparse.ArgumentParser()
            p.add_argument("--remat", help="cuts memory by ~50%% "
                                           "(default %(default)s)")
        """, "argparse-percent")

    def test_unknown_mapping_key_still_flags(self, tmp_path):
        # '%(approx)s' LOOKS like a spec but argparse only supplies
        # vars(action)+prog — an unknown key KeyErrors --help exactly
        # like a bare '%', and so does a spec with no conversion char.
        findings = _lint(tmp_path, """
            import argparse

            p = argparse.ArgumentParser()
            p.add_argument("--x", help="about 50%(approx) faster")
            p.add_argument("--y", help="uses %(default) then text")
        """, "argparse-percent")
        assert len(findings) == 2


class TestCoreContract:
    def test_waivers_trailing_and_standalone(self, tmp_path):
        findings = _lint(tmp_path, """
            class Engine:
                def step(self):
                    a = self.loss.item()  # graftlint: disable=hot-path-transfer -- test waiver
                    # graftlint: disable=hot-path-transfer -- standalone covers next line
                    b = self.aux.item()
                    c = self.extra.item()
                    return a, b, c
        """, "hot-path-transfer")
        assert len(findings) == 1  # only the unwaived third sync

    def test_waiver_is_rule_scoped(self, tmp_path):
        findings = _lint(tmp_path, """
            class Engine:
                def step(self):
                    return self.loss.item()  # graftlint: disable=determinism -- wrong rule
        """, "hot-path-transfer")
        assert len(findings) == 1

    def test_malformed_waiver_is_malformed_input(self, tmp_path, capsys):
        path = tmp_path / "bad_waiver.py"
        path.write_text("x = 1  # graftlint: disallow=foo\n")
        with pytest.raises(LintInputError, match="without"):
            run_lint([str(path)])
        # Empty rule list: exit 2 with a one-line error through the
        # CLI, never a traceback (the exit-code contract).
        path.write_text("x = 1  # graftlint: disable=\n")
        assert lint_main([str(path)]) == 2
        assert "names no rules" in capsys.readouterr().err

    def test_exit_2_on_syntax_error_and_missing_path(self, tmp_path,
                                                     capsys):
        path = tmp_path / "torn.py"
        path.write_text("def step(:\n")
        assert lint_main([str(path)]) == 2
        assert "syntax error" in capsys.readouterr().err
        assert lint_main([str(tmp_path / "nope.py")]) == 2
        assert "graftlint: error:" in capsys.readouterr().err

    def test_unknown_rule_is_malformed_input(self, tmp_path, capsys):
        path = tmp_path / "ok.py"
        path.write_text("x = 1\n")
        assert lint_main([str(path), "--rule", "no-such-rule"]) == 2
        assert "unknown rule" in capsys.readouterr().err

    def test_json_output_shape(self, tmp_path, capsys):
        import json

        path = tmp_path / "hot.py"
        path.write_text(textwrap.dedent("""
            class Engine:
                def step(self):
                    return self.loss.item()
        """))
        assert lint_main([str(path), "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"] and payload["files"] == 1
        f = payload["findings"][0]
        assert f["rule"] == "hot-path-transfer" and f["line"] and f["path"]

    def test_absolute_paths_resolve_cross_module_imports(self, tmp_path):
        # Regression: module names used to be derived verbatim from the
        # display path, so linting by ABSOLUTE path made every
        # cross-module from-import look external — reachability stopped
        # at file boundaries and the gate went falsely green.
        pkg = tmp_path / "lintpkg"
        pkg.mkdir()
        (pkg / "helpers.py").write_text(textwrap.dedent("""
            def refresh(recorder):
                recorder.flush()
        """))
        (pkg / "handler.py").write_text(textwrap.dedent("""
            from lintpkg.helpers import refresh

            class Handler:
                def do_GET(self):
                    refresh(self.recorder)
        """))
        findings, _ = run_lint([str(pkg)], rules=["scrape-safety"])
        assert len(findings) == 1 and "flush" in findings[0].message

    def test_self_lint_is_clean(self, monkeypatch):
        # The acceptance bar: the package and its tooling lint clean
        # (deliberate syncs carry justified waivers; summary counts
        # them so a silently-dead waiver regime would show up as 0).
        monkeypatch.chdir(REPO)
        findings, summary = run_lint(
            ["distributed_training_tpu", "tools"])
        assert findings == [], [f.render() for f in findings]
        assert summary["waived"] >= 10
