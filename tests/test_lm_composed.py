"""Composed parallelism: TP×SP and PP×TP train-step correctness.

Round-2 extension (VERDICT r1 #6): the explicit strategies (ring-attention
sequence parallelism, GPipe pipelining) compose with declarative megatron TP
through *partial-manual* shard_map — the strategy's own axes are manual,
``model`` stays automatic, and GSPMD inserts the row-parallel psums inside
each shard. The invariant tested here is the same DDP-equivalence property
as the single-strategy oracles (SURVEY.md §4): one composed step == one
single-device step, loss and every updated parameter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state
from distributed_training_tpu.parallel.tensor_parallel import tp_state_shardings
from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
from distributed_training_tpu.train.lm_step import (
    lm_batch_shardings,
    make_lm_batch,
    make_lm_train_step,
    make_pp_lm_train_step,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.train_state import init_train_state

VOCAB = 64

# Shared xfail for the known partial-manual env gap (see tests/conftest.py).
from conftest import needs_partial_manual


@pytest.fixture(scope="module")
def sp_tp_mesh():
    return create_mesh(MeshConfig(data=2, sequence=2, model=2))


@pytest.fixture(scope="module")
def pp_tp_mesh():
    return create_mesh(MeshConfig(data=2, pipe=2, model=2))


def _make_state(seq_axis, seed=0):
    model = get_model(
        "transformer_lm", num_classes=VOCAB, seq_axis=seq_axis,
        num_layers=2, num_heads=2, hidden_dim=32, max_len=128)
    # SGD: strict 1e-5 equivalence (Adam amplifies reassociation noise).
    tx = optax.sgd(0.1)
    state = init_train_state(
        model, jax.random.PRNGKey(seed), (2, 16), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
        input_dtype=jnp.int32)
    return model, state


def _tokens(b=4, t=33, seed=0):
    return np.random.RandomState(seed).randint(0, VOCAB, (b, t)).astype(np.int32)


def _oracle_step(state, batch, rng):
    def loss_fn(params):
        logits = state.apply_fn(
            {"params": params}, jnp.asarray(batch["tokens"]), train=True,
            rngs={"dropout": rng})
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.asarray(batch["targets"])).mean()
    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads), loss


def _assert_tree_close(a, b, atol=1e-5, rtol=1e-4):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), atol=atol, rtol=rtol), a, b)


class TestSequenceTensorComposition:
    @needs_partial_manual
    def test_sp_tp_step_matches_single_device(self, sp_tp_mesh):
        """(data=2 × sequence=2 × model=2) ring step with megatron-sharded
        weights == single-device step."""
        batch = make_lm_batch(_tokens())
        rng = jax.random.PRNGKey(7)

        _, oracle = _make_state(None)
        oracle_new, oracle_loss = jax.jit(_oracle_step)(oracle, batch, rng)

        model, sp = _make_state("sequence")
        sp = place_state(sp, tp_state_shardings(sp, sp_tp_mesh, zero_stage=0))
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            lm_batch_shardings(sp_tp_mesh))
        step = make_lm_train_step(sp_tp_mesh, model=model, donate=False)
        sp_new, metrics = step(sp, gbatch, rng)

        np.testing.assert_allclose(
            float(metrics["loss"]), float(oracle_loss), atol=1e-5, rtol=1e-5)
        _assert_tree_close(sp_new.params, oracle_new.params)

    def test_sp_tp_weights_actually_sharded(self, sp_tp_mesh):
        """The composed state's attention/MLP weights really split over the
        model axis (not silently replicated)."""
        _, state = _make_state("sequence")
        placed = place_state(
            state, tp_state_shardings(state, sp_tp_mesh, zero_stage=0))
        qkv = placed.params["block0"]["attn"]["qkv"]["kernel"]
        # [d, 3, H, hd] with H=2 sharded over model=2 → per-device H dim 1.
        shard_shape = qkv.sharding.shard_shape(qkv.shape)
        assert shard_shape[2] == qkv.shape[2] // 2
        fc1 = placed.params["block0"]["mlp"]["fc1"]["kernel"]
        assert fc1.sharding.shard_shape(fc1.shape)[1] == fc1.shape[1] // 2

    @needs_partial_manual
    def test_sp_tp_loss_decreases(self, sp_tp_mesh):
        """Smoke: 25 composed steps on a learnable pattern drop the loss."""
        start = np.random.RandomState(0).randint(0, VOCAB, (8, 1))
        tokens = (start + np.arange(33)) % VOCAB
        batch = make_lm_batch(tokens.astype(np.int32))
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            lm_batch_shardings(sp_tp_mesh))

        model, state = _make_state("sequence")
        state = place_state(
            state, tp_state_shardings(state, sp_tp_mesh, zero_stage=0))
        step = make_lm_train_step(sp_tp_mesh, model=model, donate=False)
        rng = jax.random.PRNGKey(0)
        first = None
        for _ in range(25):
            rng, sub = jax.random.split(rng)
            state, metrics = step(state, gbatch, sub)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first * 0.6, (
            first, float(metrics["loss"]))


class TestPipelineTensorComposition:
    @needs_partial_manual
    def test_pp_tp_step_matches_single_device(self, pp_tp_mesh):
        """(data=2 × pipe=2 × model=2) GPipe step with megatron-sharded
        stage weights == single-device step."""
        from distributed_training_tpu.parallel.pipeline import (
            stack_block_params,
        )
        from distributed_training_tpu.train.train_state import TrainState

        model, _ = _make_state(None)
        rng0 = jax.random.PRNGKey(0)
        batch = make_lm_batch(_tokens())
        step_rng = jax.random.PRNGKey(7)

        variables = model.init({"params": rng0}, jnp.zeros((1, 8), jnp.int32),
                               train=False)

        def oracle_step(params, batch):
            def loss_fn(p):
                logits = model.apply(
                    {"params": p}, jnp.asarray(batch["tokens"]), train=False)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, jnp.asarray(batch["targets"])).mean()
            loss, grads = jax.value_and_grad(loss_fn)(params)
            return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads), loss

        oracle_params, oracle_loss = jax.jit(oracle_step)(
            dict(variables["params"]), batch)
        oracle_stacked, oracle_rest = stack_block_params(
            oracle_params, model.num_layers)

        step = make_pp_lm_train_step(pp_tp_mesh, model=model,
                                     num_microbatches=2, donate=False)
        plm = step.pipelined
        assert plm.tp_size == 2
        state = TrainState.create(
            apply_fn=plm.apply_fn, params=plm.init_params(rng0),
            tx=optax.sgd(0.1),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = place_state(state, step.state_shardings(state))
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            step.batch_shardings)
        new_state, metrics = step(state, gbatch, step_rng)

        np.testing.assert_allclose(
            float(metrics["loss"]), float(oracle_loss), atol=1e-5, rtol=1e-5)
        _assert_tree_close(new_state.params["blocks"], oracle_stacked)
        for key in ("tok_embed", "pos_embed", "ln_f", "lm_head"):
            _assert_tree_close(new_state.params[key], oracle_rest[key])

    def test_pp_tp_weights_sharded_both_axes(self, pp_tp_mesh):
        """Stacked block weights split over pipe (layer dim) AND model (TP
        dim); vocab-parallel embed/head split over model."""
        from distributed_training_tpu.train.train_state import TrainState

        model, _ = _make_state(None)
        step = make_pp_lm_train_step(pp_tp_mesh, model=model,
                                     num_microbatches=2, donate=False)
        plm = step.pipelined
        state = TrainState.create(
            apply_fn=plm.apply_fn, params=plm.init_params(jax.random.PRNGKey(0)),
            tx=optax.sgd(0.1),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        placed = place_state(state, step.state_shardings(state))
        qkv = placed.params["blocks"]["attn"]["qkv"]["kernel"]
        # [L, d, 3, H, hd]: L over pipe, H over model.
        ss = qkv.sharding.shard_shape(qkv.shape)
        assert ss[0] == qkv.shape[0] // 2, "layer dim not pipe-sharded"
        assert ss[3] == qkv.shape[3] // 2, "head dim not model-sharded"
        emb = placed.params["tok_embed"]["embedding"]
        assert emb.sharding.shard_shape(emb.shape)[0] == emb.shape[0] // 2, (
            "vocab dim not model-sharded")


class TestLMTrainerComposition:
    def _cfg(self, **mesh_kw):
        from distributed_training_tpu.config import (
            DataConfig,
            LMConfig,
            MeshSpec,
            TrainConfig,
        )

        return TrainConfig(
            model="transformer_lm",
            num_epochs=1,
            log_interval=2,
            eval_every=1,
            mesh=MeshSpec(data=-1, **mesh_kw),
            data=DataConfig(batch_size=8, max_steps_per_epoch=4),
            lm=LMConfig(seq_len=32, vocab_size=VOCAB, num_layers=2,
                        num_heads=2, hidden_dim=32, max_len=64,
                        train_sequences=64, eval_sequences=16),
        )

    @needs_partial_manual
    def test_lm_trainer_runs_sp_tp(self):
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        trainer = LMTrainer(self._cfg(sequence=2, model=2))
        assert trainer.strategy == "sequence" and trainer.tp_size == 2
        result = trainer.fit()
        assert result["steps"] == 4
        assert np.isfinite(result["final_perplexity"])

    @needs_partial_manual
    def test_lm_trainer_runs_pp_tp(self):
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        trainer = LMTrainer(self._cfg(pipe=2, model=2))
        assert trainer.strategy == "pipeline" and trainer.tp_size == 2
        result = trainer.fit()
        assert result["steps"] == 4
        assert np.isfinite(result["final_perplexity"])

    @needs_partial_manual
    def test_lm_trainer_runs_sequence_pipe(self):
        """seq×pipe composes since round 5 (was the engine's last refusal):
        the pipeline strategy drives a seq_axis model with ring attention
        inside each tick."""
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        trainer = LMTrainer(self._cfg(sequence=2, pipe=2))
        assert trainer.strategy == "pipeline"
        result = trainer.fit()
        assert result["steps"] == 4
        assert np.isfinite(result["final_perplexity"])


class TestSequenceExpertComposition:
    """EP×SP (VERDICT r2 #8): MoE decoder FFNs under the ring strategy.

    Expert parallelism is pure *placement* — the gate, capacity, and aux
    loss are shard-local under SP either way (the DeepSpeed per-rank
    semantics) — so the invariant is placement-invariance: the dp×sp×ep
    step must trace exactly the dp×sp step with experts unsharded, while
    the expert weights actually live split over the expert axis.
    """

    def _moe_state(self, seed=0):
        model = get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis="sequence",
            num_layers=2, num_heads=2, hidden_dim=32, max_len=128,
            moe_num_experts=4, moe_top_k=1, moe_capacity_factor=2.0,
            moe_expert_axis="expert")
        tx = optax.sgd(0.1)
        state = init_train_state(
            model, jax.random.PRNGKey(seed), (2, 16), tx,
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)
        return model, state

    @needs_partial_manual
    def test_sp_ep_step_is_placement_invariant(self):
        devices = jax.devices()
        ep_mesh = create_mesh(MeshConfig(data=2, sequence=2, expert=2),
                              devices=devices)
        ref_mesh = create_mesh(MeshConfig(data=2, sequence=2),
                               devices=devices[:4])
        batch = make_lm_batch(_tokens(b=4, t=33))
        rng = jax.random.PRNGKey(9)

        def run(mesh):
            model, state = self._moe_state()
            step = make_lm_train_step(mesh, model=model, donate=False)
            state = place_state(state, step.state_shardings(state))
            gbatch = jax.device_put(
                {k: jnp.asarray(v) for k, v in batch.items()},
                step.batch_shardings)
            new_state, metrics = step(state, gbatch, rng)
            return new_state, metrics

        s_ep, m_ep = run(ep_mesh)
        s_ref, m_ref = run(ref_mesh)
        np.testing.assert_allclose(float(m_ep["loss"]), float(m_ref["loss"]),
                                   atol=1e-6, rtol=1e-6)
        assert float(m_ep["aux_loss"]) > 0  # the MoE objective is live
        _assert_tree_close(
            jax.tree.map(np.asarray, s_ep.params),
            jax.tree.map(np.asarray, s_ref.params), atol=1e-5, rtol=1e-4)

        # Placement claim: expert weights split over the expert axis.
        w1 = s_ep.params["block1"]["moe_mlp"]["experts"]["w1"]
        assert w1.sharding.shard_shape(w1.shape)[0] == w1.shape[0] // 2

    @needs_partial_manual
    def test_lm_trainer_runs_sp_ep(self):
        import dataclasses

        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TestLMTrainerComposition()._cfg(sequence=2)
        cfg = cfg.replace(
            mesh=dataclasses.replace(cfg.mesh, data=2, sequence=2, expert=2),
            moe=dataclasses.replace(
                cfg.moe, enabled=True, num_experts=(4,), top_k=1,
                capacity_factor=2.0),
            lm=dataclasses.replace(cfg.lm, train_sequences=64,
                                   eval_sequences=32))
        trainer = LMTrainer(cfg)
        assert trainer.strategy == "sequence"
        result = trainer.fit()
        assert np.isfinite(result["final_perplexity"])


class TestSequenceGradAccum:
    @needs_partial_manual
    def test_sp_accum_matches_single_shot(self, sp_tp_mesh):
        """SP grad accumulation (scan inside the shard_map body) == the
        single-shot step on the same effective batch: equal-sized
        microbatches make the mean of micro-means the full-batch mean, so
        grads, loss, and the updated params agree to fp32 tolerance.
        Composes with TP (model axis) for free — same partial-manual body."""
        tokens = _tokens(b=8)
        batch = make_lm_batch(tokens)
        rng = jax.random.PRNGKey(3)

        model, base = _make_state("sequence")
        placed = place_state(
            base, tp_state_shardings(base, sp_tp_mesh, zero_stage=0))
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            lm_batch_shardings(sp_tp_mesh))

        one = make_lm_train_step(sp_tp_mesh, model=model, donate=False)
        acc = make_lm_train_step(sp_tp_mesh, model=model, donate=False,
                                 grad_accum_steps=2)
        s1, m1 = one(placed, gbatch, rng)
        s2, m2 = acc(placed, gbatch, rng)

        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-6)
        _assert_tree_close(s2.params, s1.params, atol=1e-6, rtol=1e-5)

    def test_lm_trainer_runs_sp_accum(self):
        import dataclasses

        from distributed_training_tpu.train.lm_trainer import LMTrainer

        cfg = TestLMTrainerComposition()._cfg(sequence=2)
        # sequence=2 leaves data=4; eval stays micro-sized (8×4=32), so the
        # eval split must cover at least one global batch.
        cfg = cfg.replace(
            gradient_accumulation_steps=2,
            # accum doubles the effective train batch to 64 sequences/step;
            # the splits must cover max_steps_per_epoch=4 of them (and eval
            # one micro-sized global batch of 32).
            lm=dataclasses.replace(cfg.lm, train_sequences=256,
                                   eval_sequences=64))
        trainer = LMTrainer(cfg)
        assert trainer.grad_accum == 2 and trainer.strategy == "sequence"
        result = trainer.fit()
        assert result["steps"] == 4
        assert np.isfinite(result["final_perplexity"])


class TestSequencePipeComposition:
    """SP×PP (round 5): ring attention over the manual sequence axis
    INSIDE each pipeline tick — two explicit schedules over one
    activation stream, previously the engine's last composition refusal.
    The oracle property: identical params + batch ⇒ the composed step
    matches the plain (seq_axis=None) pipeline step, whose own
    equivalence to the single-device model is already pinned."""

    @needs_partial_manual
    def test_sp_pp_step_matches_plain_pp(self):
        from distributed_training_tpu.train.train_state import TrainState

        toks = _tokens(b=8, t=17)
        batch = make_lm_batch(toks)
        rng = jax.random.PRNGKey(7)

        def run(seq_axis, mesh):
            model = get_model(
                "transformer_lm", num_classes=VOCAB, seq_axis=seq_axis,
                num_layers=2, num_heads=2, hidden_dim=32, max_len=128)
            step = make_pp_lm_train_step(mesh, model=model,
                                         num_microbatches=2, donate=False)
            plm = step.pipelined
            state = TrainState.create(
                apply_fn=plm.apply_fn,
                params=plm.init_params(jax.random.PRNGKey(0)),
                tx=optax.sgd(0.1),
                loss_scale=LossScaleState.create(
                    PrecisionConfig(dtype="fp32")))
            state = jax.device_put(state, step.state_shardings(state))
            gbatch = jax.device_put(
                {k: jnp.asarray(v) for k, v in batch.items()},
                step.batch_shardings)
            new_state, m = step(state, gbatch, rng)
            return jax.device_get(new_state.params), m

        pp = create_mesh(MeshConfig(data=4, pipe=2))
        spp = create_mesh(MeshConfig(data=2, pipe=2, sequence=2))
        ref_params, ref_m = run(None, pp)
        got_params, got_m = run("sequence", spp)
        np.testing.assert_allclose(float(got_m["loss"]),
                                   float(ref_m["loss"]), rtol=1e-6)
        _assert_tree_close(got_params, ref_params, atol=1e-6, rtol=1e-5)

    @needs_partial_manual
    def test_pp_sp_tp_one_program_matches_plain_pp(self):
        """Every explicit axis at once (pipe × sequence × model in one
        compiled SPMD program; data=1 — ZeRO would be a no-op sharding
        here and is deliberately left out of the claim): the loss matches
        the plain PP oracle. A dropped psum on any of the three axes
        would break the equality."""
        from distributed_training_tpu.train.train_state import TrainState

        toks = _tokens(b=8, t=17)
        batch = make_lm_batch(toks)
        rng = jax.random.PRNGKey(7)

        def run(seq_axis, mesh):
            model = get_model(
                "transformer_lm", num_classes=VOCAB, seq_axis=seq_axis,
                num_layers=2, num_heads=2, hidden_dim=32, max_len=128)
            step = make_pp_lm_train_step(mesh, model=model,
                                         num_microbatches=2, donate=False)
            plm = step.pipelined
            state = TrainState.create(
                apply_fn=plm.apply_fn,
                params=plm.init_params(jax.random.PRNGKey(0)),
                tx=optax.sgd(0.1),
                loss_scale=LossScaleState.create(
                    PrecisionConfig(dtype="fp32")))
            state = jax.device_put(state, step.state_shardings(state))
            gbatch = jax.device_put(
                {k: jnp.asarray(v) for k, v in batch.items()},
                step.batch_shardings)
            _, m = step(state, gbatch, rng)
            return m

        ref = run(None, create_mesh(MeshConfig(data=4, pipe=2)))
        deep = run("sequence",
                   create_mesh(MeshConfig(data=1, pipe=2, sequence=2,
                                          model=2)))
        np.testing.assert_allclose(float(deep["loss"]), float(ref["loss"]),
                                   rtol=1e-5)
        assert float(deep["grads_finite"]) == 1.0

    @needs_partial_manual
    def test_sp_pp_zero1_circular(self):
        """The deeper product: sequence × pipe × circular schedule ×
        ZeRO-1 runs one finite step."""
        from distributed_training_tpu.train.train_state import TrainState

        mesh = create_mesh(MeshConfig(data=2, pipe=2, sequence=2))
        model = get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis="sequence",
            num_layers=4, num_heads=2, hidden_dim=32, max_len=128)
        step = make_pp_lm_train_step(mesh, model=model, num_microbatches=2,
                                     donate=False, zero_stage=1,
                                     virtual_stages=2)
        plm = step.pipelined
        state = TrainState.create(
            apply_fn=plm.apply_fn,
            params=plm.init_params(jax.random.PRNGKey(0)),
            tx=optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = jax.device_put(state, step.state_shardings(state))
        batch = jax.device_put(
            {k: jnp.asarray(v) for k, v in
             make_lm_batch(_tokens(b=8, t=17)).items()},
            step.batch_shardings)
        _, m = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
        assert float(m["grads_finite"]) == 1.0
