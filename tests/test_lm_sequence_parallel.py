"""TransformerLM + sequence-parallel train step correctness.

The context-parallel invariant: a (data × sequence)-sharded train step must
produce the same loss, gradients, and updated params as a single-device step
on the full batch — the long-context generalization of the DDP-equivalence
property (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
from distributed_training_tpu.train.lm_step import (
    lm_batch_shardings,
    make_lm_batch,
    make_lm_train_step,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.train_state import init_train_state

VOCAB = 64


@pytest.fixture(scope="module")
def lm_mesh():
    return create_mesh(MeshConfig(data=2, fsdp=1, model=1, expert=1, sequence=4))


def _make_state(seq_axis, dtype="fp32", seed=0, max_len=128, opt="adam"):
    model = get_model(
        "transformer_lm", num_classes=VOCAB, seq_axis=seq_axis,
        num_layers=2, num_heads=2, hidden_dim=32, max_len=max_len)
    # SGD for strict equivalence tests: Adam's 1/sqrt(v) normalization
    # amplifies fp32 collective-reassociation noise into O(lr) param diffs.
    tx = (optax.sgd(0.1) if opt == "sgd" else
          optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3)))
    state = init_train_state(
        model, jax.random.PRNGKey(seed), (2, 16), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype=dtype)),
        input_dtype=jnp.int32)
    return model, state


def _tokens(b=4, t=65, seed=0):
    return np.random.RandomState(seed).randint(0, VOCAB, (b, t)).astype(np.int32)


def test_lm_forward_shapes():
    _, state = _make_state(None)
    batch = make_lm_batch(_tokens())
    logits = state.apply_fn(
        {"params": state.params}, jnp.asarray(batch["tokens"]), train=False)
    assert logits.shape == (4, 64, VOCAB)
    assert logits.dtype == jnp.float32


def test_sequence_parallel_step_matches_single_device(lm_mesh):
    """One (data=2 × sequence=4) step == one single-device step: loss and
    every updated parameter."""
    tokens = _tokens()
    batch = make_lm_batch(tokens)
    rng = jax.random.PRNGKey(7)

    # Oracle: unsharded model, plain full-batch step.
    _, oracle = _make_state(None, opt="sgd")

    def oracle_step(state, batch):
        def loss_fn(params):
            logits = state.apply_fn(
                {"params": params}, jnp.asarray(batch["tokens"]), train=True,
                rngs={"dropout": rng})
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.asarray(batch["targets"])).mean()
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), loss

    oracle_new, oracle_loss = jax.jit(oracle_step)(oracle, batch)

    # Sequence-parallel: same init seed → same initial params.
    model, sp = _make_state("sequence", opt="sgd")
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()},
        lm_batch_shardings(lm_mesh))
    # model= path: the bound derives from the positional table itself.
    step = make_lm_train_step(lm_mesh, model=model, donate=False)
    sp_new, metrics = step(sp, gbatch, rng)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(oracle_loss), atol=1e-5, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        sp_new.params, oracle_new.params)
    assert float(metrics["perplexity"]) == pytest.approx(
        float(np.exp(float(oracle_loss))), rel=1e-4)


def test_lm_loss_decreases_under_sequence_parallelism(lm_mesh):
    """Smoke: 30 sequence-parallel steps on a learnable pattern drop the loss."""
    # Learnable data: next token = (token + 1) % VOCAB.
    start = np.random.RandomState(0).randint(0, VOCAB, (8, 1))
    tokens = (start + np.arange(33)) % VOCAB
    batch = make_lm_batch(tokens.astype(np.int32))
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()},
        lm_batch_shardings(lm_mesh))

    model, state = _make_state("sequence")
    step = make_lm_train_step(lm_mesh, max_len=128, donate=False)
    rng = jax.random.PRNGKey(0)
    first = None
    for i in range(30):
        rng, sub = jax.random.split(rng)
        state, metrics = step(state, gbatch, sub)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first * 0.5, (first, last)


@pytest.mark.xfail(
    strict=False,
    reason="pre-existing on the baked jax 0.4.37 CPU mesh: the ZeRO-1 "
           "reduce-scatter reassociates differently from the replicated "
           "all-reduce and 3 Adam steps amplify it past the strict "
           "1e-6/1e-5 tolerance (max |Δparam| ~4e-5; tracked with the "
           "round-6/7 environment gaps in CHANGES.md)")
def test_sequence_parallel_zero1_matches_replicated(lm_mesh):
    """SP×ZeRO-1 (VERDICT r2 #2): the flagship long-context path with Adam
    state sharded over the data × sequence replica group must trace the
    SAME training trajectory as the replicated-state SP step — ZeRO is a
    placement, not a math change — while the moments actually live
    sharded."""
    from distributed_training_tpu.parallel.sharding import place_state

    tokens = _tokens(b=4, t=33)
    batch = make_lm_batch(tokens)

    def run(zero_stage, steps=3):
        model, state = _make_state("sequence", opt="adam")
        step = make_lm_train_step(lm_mesh, model=model, donate=False,
                                  zero_stage=zero_stage)
        state = place_state(state, step.state_shardings(state))
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            step.batch_shardings)
        for i in range(steps):
            state, metrics = step(state, gbatch, jax.random.PRNGKey(i))
        return state, metrics

    s0, m0 = run(0)
    s1, m1 = run(1)
    np.testing.assert_allclose(float(m1["loss"]), float(m0["loss"]),
                               atol=1e-6, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5),
        s1.params, s0.params)

    # The placement claim: at least the transformer-block Adam moments are
    # sharded over the 8-way data×sequence group (divisible dims shard;
    # tiny biases legitimately stay replicated).
    def sharded_leaves(tree):
        return [x for x in jax.tree.leaves(tree)
                if not x.sharding.is_fully_replicated]

    assert not sharded_leaves(s1.params)  # stage 1 keeps params replicated
    n_sharded = len(sharded_leaves(s1.opt_state))
    assert n_sharded > 0, "zero-1 opt state is fully replicated"
    assert not sharded_leaves(s0.opt_state)


def test_sequence_parallel_zero3_shards_params(lm_mesh):
    """Stage 3 under SP: params stored sharded over the replica group,
    gathered on use at step entry; the step still trains (finite loss,
    params move)."""
    from distributed_training_tpu.parallel.sharding import place_state

    model, state = _make_state("sequence", opt="adam")
    step = make_lm_train_step(lm_mesh, model=model, donate=False,
                              zero_stage=3)
    state = place_state(state, step.state_shardings(state))
    assert any(not x.sharding.is_fully_replicated
               for x in jax.tree.leaves(state.params))
    before = jax.tree.map(np.asarray, state.params)
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in make_lm_batch(_tokens()).items()},
        step.batch_shardings)
    state, metrics = step(state, gbatch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    moved = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - b).max()),
        state.params, before))
    assert max(moved) > 0


def test_sequence_parallel_flash_matches_exact_impl(lm_mesh):
    """attn_impl='flash' under the sequence strategy (ring+flash, VERDICT
    r2 #3): the Pallas hop kernel must trace the same training trajectory
    as the exact-hop ring step."""
    tokens = _tokens(b=4, t=65)
    batch = make_lm_batch(tokens)

    def run(attn_impl, steps=2):
        model = get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis="sequence",
            attn_impl=attn_impl,
            num_layers=2, num_heads=2, hidden_dim=32, max_len=128)
        tx = optax.sgd(0.1)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (2, 16), tx,
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)
        step = make_lm_train_step(lm_mesh, model=model, donate=False)
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            step.batch_shardings)
        for i in range(steps):
            state, metrics = step(state, gbatch, jax.random.PRNGKey(i))
        return state, metrics

    s_exact, m_exact = run("exact")
    s_flash, m_flash = run("flash")
    np.testing.assert_allclose(float(m_flash["loss"]),
                               float(m_exact["loss"]), atol=1e-5, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        s_flash.params, s_exact.params)


@pytest.mark.parametrize("ce_chunk", [None, 8])
def test_sharded_eval_matches_unsharded_oracle(lm_mesh, ce_chunk):
    """Eval at trained lengths under SP (VERDICT r2 #4): the sharded ring
    eval forward must produce the same mean CE as an unsharded twin — and
    it is the only eval path that works when the context fits only
    sharded."""
    from distributed_training_tpu.train.lm_step import make_lm_eval_fn

    model, state = _make_state("sequence")
    batch = make_lm_batch(_tokens(b=4, t=65, seed=11))
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()},
        lm_batch_shardings(lm_mesh))

    eval_fn = make_lm_eval_fn(lm_mesh, model=model, ce_chunk=ce_chunk)
    ce_sharded = float(eval_fn(state.params, gbatch))

    twin = model.clone(seq_axis=None)
    logits = twin.apply({"params": state.params},
                        jnp.asarray(batch["tokens"]), train=False)
    ce_oracle = float(optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.asarray(batch["targets"])).mean())
    assert ce_sharded == pytest.approx(ce_oracle, abs=1e-5, rel=1e-5)


def test_lm_trainer_sequence_eval_end_to_end(lm_mesh):
    """LMTrainer.evaluate under the sequence strategy goes through the
    sharded path and returns a finite perplexity."""
    from distributed_training_tpu.config import (
        DataConfig,
        LMConfig,
        TrainConfig,
    )
    from distributed_training_tpu.train.lm_trainer import LMTrainer

    cfg = TrainConfig(
        model="transformer_lm", num_epochs=1, eval_every=1,
        lm=LMConfig(seq_len=32, vocab_size=VOCAB, num_layers=2, num_heads=2,
                    hidden_dim=32, max_len=64, train_sequences=64,
                    eval_sequences=16, ce_chunk_size=8),
        data=DataConfig(batch_size=8, prefetch=0))
    tr = LMTrainer(cfg, mesh=lm_mesh)
    _, eval_loader = tr.make_loaders()
    ppl = tr.evaluate(eval_loader)
    assert np.isfinite(ppl) and ppl > 1.0


def test_lm_dynamic_loss_scale_skips_bad_step(lm_mesh):
    """An overflowed gradient skips the whole update: params frozen, step
    not ticked, one hysteresis credit consumed — the commit_gradients skip
    transaction driven through the full sequence-parallel step."""
    model, state = _make_state("sequence", dtype="fp16")
    assert state.loss_scale.dynamic
    batch = make_lm_batch(_tokens())
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()},
        lm_batch_shardings(lm_mesh))
    step = make_lm_train_step(lm_mesh, max_len=128, donate=False)

    # Good step first: update applies, counter ticks.
    good_state, metrics = step(state, gbatch, jax.random.PRNGKey(0))
    assert float(metrics["grads_finite"]) == 1.0
    assert int(good_state.step) == 1

    # Force an overflow: a loss scale beyond fp32 range makes the scaled
    # loss (and thus every gradient) infinite.
    bad = good_state.replace(
        loss_scale=good_state.loss_scale.replace(scale=jnp.float32(1e38)))
    skipped, metrics = step(bad, gbatch, jax.random.PRNGKey(1))
    assert float(metrics["grads_finite"]) == 0.0
    assert int(skipped.step) == 1  # NOT ticked: the scheduler must not move
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        skipped.params, bad.params)
    # First overflow consumes a hysteresis credit (DS hysteresis=2 default)
    # without halving the scale yet.
    assert int(skipped.loss_scale.hysteresis_left) == \
        int(bad.loss_scale.hysteresis_left) - 1
    assert float(skipped.loss_scale.scale) == pytest.approx(1e38)
    assert int(skipped.loss_scale.good_steps) == 0
