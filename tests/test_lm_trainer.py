"""LMTrainer end-to-end: strategy selection by mesh, data layer, resume.

The LM engine has no reference counterpart (SURVEY.md §5 "Long-context":
absent); its contract mirrors the image Trainer's — epoch loop, periodic
eval (perplexity), functional checkpoint/resume — with the parallel
strategy derived from the mesh axes.
"""

import numpy as np
import pytest

from distributed_training_tpu.config import (
    CheckpointConfig,
    DataConfig,
    LMConfig,
    MeshSpec,
    TrainConfig,
    ZeroConfig,
)
from distributed_training_tpu.data.lm_text import (
    TokenLoader,
    byte_corpus,
    synthetic_tokens,
)
from distributed_training_tpu.train.lm_trainer import LMTrainer

from conftest import needs_partial_manual

LM = LMConfig(seq_len=32, num_layers=2, num_heads=4, hidden_dim=32,
              max_len=64, train_sequences=256, eval_sequences=64,
              num_microbatches=2)


def _cfg(mesh, ckpt_dir, *, zero=0, epochs=2, resume=-1, interval=0):
    return TrainConfig(model="transformer_lm").replace(
        num_epochs=epochs, log_interval=4,
        data=DataConfig(batch_size=8, max_steps_per_epoch=4),
        lm=LM,
        mesh=mesh,
        zero=ZeroConfig(stage=zero),
        checkpoint=CheckpointConfig(
            directory=str(ckpt_dir), interval=interval, resume=resume),
    )


# -- data layer --------------------------------------------------------------


def test_synthetic_tokens_learnable_pattern():
    toks = synthetic_tokens(4, 16, vocab_size=64, seed=0)
    assert toks.shape == (4, 17)
    np.testing.assert_array_equal(toks[:, 1:], (toks[:, :-1] + 1) % 64)


def test_byte_corpus_windows(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_bytes(bytes(range(256)) * 4)
    toks = byte_corpus(str(p), 8, 16, seed=0)
    assert toks.shape == (8, 17)
    # Consecutive bytes of the file are consecutive values mod 256.
    np.testing.assert_array_equal(toks[:, 1:] % 256, (toks[:, :-1] + 1) % 256)
    with pytest.raises(ValueError, match="bytes"):
        byte_corpus(str(p), 2, 5000)


def test_token_loader_shards_and_reshuffles():
    toks = synthetic_tokens(64, 8, seed=0)
    loader = TokenLoader(toks, global_batch_size=16, seed=3,
                         process_index=1, process_count=2)
    assert len(loader) == 4
    b0 = [b["tokens"] for b in loader]
    assert all(b.shape == (8, 9) for b in b0)  # per-process half of 16
    b0_again = [b["tokens"] for b in loader]
    np.testing.assert_array_equal(b0[0], b0_again[0])  # same epoch = same order
    loader.set_epoch(1)
    b1 = [b["tokens"] for b in loader]
    assert not np.array_equal(b0[0], b1[0])  # set_epoch reshuffles


# -- engine ------------------------------------------------------------------

@pytest.mark.parametrize("name,mesh,zero", [
    ("sequence", MeshSpec(data=2, sequence=4), 0),
    ("tensor/dp", MeshSpec(data=2, model=4), 1),
    ("pipeline", MeshSpec(data=4, pipe=2), 0),
    ("tensor/dp", MeshSpec(data=-1), 0),
])
def test_lm_trainer_strategies_learn(tmp_path, name, mesh, zero):
    trainer = LMTrainer(_cfg(mesh, tmp_path, zero=zero))
    assert trainer.strategy == name
    result = trainer.fit()
    assert np.isfinite(result["final_perplexity"])
    # Steps per epoch depend on the mesh's data extent (global batch =
    # batch_size × data shards); the engine's own counter is the contract.
    assert result["steps"] == trainer._global_step > 0
    # The synthetic pattern is trivially learnable: even 8 tiny steps must
    # push held-out perplexity below the uniform-vocab 256.
    assert result["final_perplexity"] < 250


def test_lm_trainer_checkpoint_resume(tmp_path):
    mesh = MeshSpec(data=-1)
    r1 = LMTrainer(_cfg(mesh, tmp_path, epochs=2, interval=1)).fit()
    resumed = LMTrainer(_cfg(mesh, tmp_path, epochs=4, resume=1, interval=0))
    r2 = resumed.fit()
    # 2 epochs ran before the save, 2 more after resume; the step counter
    # carried through the checkpoint.
    assert r2["steps"] == r1["steps"] + 8


def test_lm_trainer_rejects_bad_meshes(tmp_path):
    # sequence×model and pipe×model compose since round 2, sequence×pipe
    # since round 5 (ring attention inside the pipeline stage) — the
    # remaining mesh errors are divisibility ones.
    with pytest.raises(ValueError, match="num_heads"):
        cfg = _cfg(MeshSpec(data=1, model=8), tmp_path)
        LMTrainer(cfg)


@needs_partial_manual
def test_lm_trainer_sequence_pipe_composes(tmp_path):
    """seq×pipe (round 5): the pipeline engine drives a seq_axis model —
    ring attention over the manual sequence axis inside each tick."""
    cfg = _cfg(MeshSpec(data=2, sequence=2, pipe=2), tmp_path)
    result = LMTrainer(cfg).fit()
    assert np.isfinite(result["final_perplexity"])


def test_metrics_accuracy_off_drops_key_same_loss(tmp_path):
    """lm.metrics_accuracy=False removes the per-step vocab argmax (a full
    extra HBM pass over the logits): the 'accuracy' metric key disappears
    while the training math — loss trajectory, steps — is unchanged."""
    import dataclasses as dc

    base = _cfg(MeshSpec(data=-1), tmp_path)
    on = LMTrainer(base)
    off = LMTrainer(base.replace(lm=dc.replace(LM, metrics_accuracy=False)))
    train_on, _ = on.make_loaders()
    train_off, _ = off.make_loaders()
    m_on = on.train_epoch(0, train_on)
    m_off = off.train_epoch(0, train_off)
    assert "accuracy" in m_on and "accuracy" not in m_off
    assert m_off["loss"] == pytest.approx(m_on["loss"], rel=1e-6)


def test_lm_trainer_circular_pipeline_zero1(tmp_path):
    """Round-4 knobs through the PRODUCT surface: LMTrainer with the
    circular schedule (virtual_stages=2), PP×ZeRO-1, bf16 logits, and no
    head bias trains and evaluates finitely."""
    import dataclasses

    cfg = _cfg(MeshSpec(data=4, pipe=2), tmp_path, zero=1, epochs=1)
    cfg = cfg.replace(lm=dataclasses.replace(
        LM, num_layers=4, virtual_stages=2, logits_dtype="bf16",
        head_bias=False))
    trainer = LMTrainer(cfg)
    assert trainer.train_step.pipelined.virtual_stages == 2
    assert trainer.train_step.pipelined.bubble_fraction < 1 / 3
    assert "bias" not in trainer.state.params["lm_head"]
    result = trainer.fit()
    assert np.isfinite(result["final_perplexity"])


def test_restore_head_bias_mismatch_names_the_knob(tmp_path):
    """Resuming a pre-round-5 checkpoint (lm_head WITH bias) into today's
    bias-less template must surface "set lm.head_bias=True", not a raw
    pytree-structure error (mirrors gpt/jax_tpu/generate.py's handler)."""
    import jax
    import jax.numpy as jnp
    import optax
    import pytest

    from distributed_training_tpu import checkpoint as ckpt_lib
    from distributed_training_tpu.config import PrecisionConfig
    from distributed_training_tpu.models import get_model
    from distributed_training_tpu.train.lm_trainer import restore_lm_checkpoint
    from distributed_training_tpu.train.precision import LossScaleState
    from distributed_training_tpu.train.train_state import init_train_state

    def state_for(head_bias):
        model = get_model("transformer_lm", num_classes=16, num_layers=1,
                          num_heads=2, hidden_dim=8, max_len=16,
                          head_bias=head_bias)
        return init_train_state(
            model, jax.random.PRNGKey(0), (1, 8), optax.sgd(0.1),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)

    ckpt_lib.save_checkpoint(str(tmp_path), 0, state_for(head_bias=True))
    with pytest.raises(ValueError, match="head_bias"):
        restore_lm_checkpoint(str(tmp_path), 0, state_for(head_bias=False))
    # The matching tree still restores through the guarded path.
    restored, _, _ = restore_lm_checkpoint(
        str(tmp_path), 0, state_for(head_bias=True))
    assert "bias" in restored.params["lm_head"]
