"""Model unit tests: shapes, dtypes, param counts (SURVEY.md §4 'Unit')."""

import jax
import jax.numpy as jnp
import pytest

from distributed_training_tpu.models import available_models, get_model
from distributed_training_tpu.train.train_state import param_count


@pytest.mark.parametrize("name,num_classes", [("resnet18", 10), ("resnet50", 10)])
def test_resnet_forward_shapes(name, num_classes):
    model = get_model(name, num_classes=num_classes)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, num_classes)
    assert logits.dtype == jnp.float32


def test_resnet18_param_count_torchvision_parity():
    # torchvision resnet18(num_classes=10): 11,181,642 params.
    model = get_model("resnet18", num_classes=10)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    n = param_count(variables["params"])
    # BatchNorm running stats live in batch_stats, not params — count
    # trainable only, exactly like model.parameters() in torch.
    assert n == 11_181_642, n


def test_resnet50_param_count_torchvision_parity():
    # torchvision resnet50(num_classes=1000): 25,557,032 params.
    model = get_model("resnet50", num_classes=1000)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False)
    assert param_count(variables["params"]) == 25_557_032


def test_bf16_compute_fp32_params():
    model = get_model("resnet18", num_classes=10, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    leaves = jax.tree.leaves(variables["params"])
    assert all(l.dtype == jnp.float32 for l in leaves)
    logits = model.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32  # fp32 logits for stable CE


def test_batch_stats_update_in_train_mode():
    model = get_model("resnet18", num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree.leaves(variables["batch_stats"])
    new = jax.tree.leaves(mutated["batch_stats"])
    assert any(
        not jnp.allclose(a, b) for a, b in zip(old, new)), "BN stats must move"


def test_vit_forward():
    model = get_model("vit_b16", num_classes=10, hidden_size=64,
                      num_layers=2, num_heads=4, mlp_dim=128)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_registry_lists_model_families():
    names = available_models()
    for required in ("resnet18", "resnet34", "resnet50", "resnet101",
                     "resnet152", "vit_b16"):
        assert required in names
