"""MoE layer tests: gating invariants, expert parallelism, DS flag parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.models import get_model
from distributed_training_tpu.models.moe import MoEMlp, TopKGate
from distributed_training_tpu.parallel.sharding import replicated
from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh


def _tokens(t=64, d=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(t, d).astype(np.float32))


def test_gate_dispatch_invariants():
    gate = TopKGate(num_experts=4, top_k=1, capacity_factor=2.0)
    x = _tokens()
    (combine, dispatch, aux), _ = gate.init_with_output(
        {"params": jax.random.PRNGKey(0)}, x, train=False)
    t, e, c = combine.shape
    assert (e, t) == (4, 64)
    # Each token goes to at most top_k expert-slots.
    assert int(dispatch.sum()) <= t
    # No slot double-booked: at most one token per (expert, slot).
    assert np.asarray(dispatch.sum(axis=0)).max() <= 1
    # top-1 (Switch semantics): combine weight is the router probability of
    # the selected expert — in (1/E, 1] after softmax, NOT renormalized to 1
    # (that scaling is the router's gradient path).
    per_token = np.asarray(combine.sum(axis=(1, 2)))
    routed = np.asarray(dispatch.any(axis=(1, 2)))
    assert (per_token[routed] > 1.0 / 4).all()
    assert (per_token[routed] <= 1.0 + 1e-5).all()
    assert float(aux) > 0


def test_gate_top2_combine_weights_renormalized():
    gate = TopKGate(num_experts=4, top_k=2, capacity_factor=2.0)
    x = _tokens()
    (combine, dispatch, _), _ = gate.init_with_output(
        {"params": jax.random.PRNGKey(0)}, x, train=False)
    per_token = np.asarray(combine.sum(axis=(1, 2)))
    both_kept = np.asarray(dispatch.sum(axis=(1, 2))) == 2
    np.testing.assert_allclose(per_token[both_kept], 1.0, atol=1e-5)


def test_gate_top2_routes_two_experts():
    gate = TopKGate(num_experts=4, top_k=2, capacity_factor=2.0)
    x = _tokens(t=32)
    (combine, dispatch, _), _ = gate.init_with_output(
        {"params": jax.random.PRNGKey(0)}, x, train=False)
    per_token_slots = np.asarray(dispatch.sum(axis=(1, 2)))
    assert per_token_slots.max() == 2
    assert (np.asarray(combine) >= 0).all()


def test_gate_capacity_drops_overflow():
    # capacity_factor tiny → capacity 1 per expert → at most E tokens kept.
    gate = TopKGate(num_experts=2, top_k=1, capacity_factor=0.01,
                    min_capacity=1)
    x = _tokens(t=64)
    (_, dispatch, _), _ = gate.init_with_output(
        {"params": jax.random.PRNGKey(0)}, x, train=False)
    assert int(dispatch.sum()) <= 2


def test_gate_rejects_top3():
    gate = TopKGate(num_experts=4, top_k=3)
    with pytest.raises(ValueError, match="top 1 and 2"):
        gate.init(jax.random.PRNGKey(0), _tokens(), train=False)


@pytest.mark.parametrize("policy", ["RSample", "Jitter"])
def test_noisy_gate_policies_perturb_routing(policy):
    gate = TopKGate(num_experts=8, top_k=1, noisy_gate_policy=policy)
    x = _tokens(t=128, d=8, seed=1)
    variables = gate.init(
        {"params": jax.random.PRNGKey(0), "gate": jax.random.PRNGKey(1)},
        x, train=True)
    out_a = gate.apply(variables, x, train=True,
                       rngs={"gate": jax.random.PRNGKey(2)})
    out_b = gate.apply(variables, x, train=True,
                       rngs={"gate": jax.random.PRNGKey(3)})
    out_eval = gate.apply(variables, x, train=False)
    out_eval2 = gate.apply(variables, x, train=False)
    assert not np.allclose(np.asarray(out_a[0]), np.asarray(out_b[0]))
    np.testing.assert_array_equal(
        np.asarray(out_eval[0]), np.asarray(out_eval2[0]))  # eval: no noise


@pytest.mark.parametrize("mlp_type", ["standard", "residual"])
def test_moe_mlp_forward(mlp_type):
    moe = MoEMlp(num_experts=4, hidden_dim=32, mlp_type=mlp_type)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
    variables = moe.init(jax.random.PRNGKey(0), x, train=False)
    out, aux_vars = moe.apply(variables, x, train=False, mutable=["aux_loss"])
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    leaves = jax.tree.leaves(dict(aux_vars).get("aux_loss", {}))
    assert leaves and float(leaves[0]) > 0


def test_moe_mlp_rejects_bad_type():
    moe = MoEMlp(num_experts=4, hidden_dim=32, mlp_type="bogus")
    x = jnp.zeros((2, 4, 16))
    with pytest.raises(ValueError, match="standard, residual"):
        moe.init(jax.random.PRNGKey(0), x, train=False)


def test_expert_parallel_matches_single_device(mesh):
    """EP sharding must be a pure placement choice: outputs identical."""
    moe = MoEMlp(num_experts=8, hidden_dim=32, expert_axis=None)
    x = jnp.asarray(np.random.RandomState(2).randn(4, 8, 16).astype(np.float32))
    variables = moe.init(jax.random.PRNGKey(0), x, train=False)
    ref, _ = moe.apply(variables, x, train=False, mutable=["aux_loss"])

    ep_mesh = create_mesh(MeshConfig(data=1, expert=8, fsdp=1, model=1,
                                     sequence=1))
    moe_ep = MoEMlp(num_experts=8, hidden_dim=32, expert_axis="expert")

    def fwd(v, x):
        out, _ = moe_ep.apply(v, x, train=False, mutable=["aux_loss"])
        return out

    with ep_mesh:
        out = jax.jit(fwd, in_shardings=(replicated(ep_mesh),
                                         replicated(ep_mesh)),
                      out_shardings=replicated(ep_mesh))(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_moe_model_registry_and_forward():
    model = get_model("moe_mlp", num_classes=10, num_experts=(4,),
                      mlp_type="residual", top_k=2)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
