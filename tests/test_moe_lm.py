"""MoE decoder blocks in the TransformerLM (expert-parallel FFNs).

The reference parses MoE flags but trains a dense model
(``resnet/deepspeed/deepspeed_train.py:61-106`` vs ``:223``); here the same
surface swaps alternating decoder FFNs for GShard-style expert layers. The
invariants: expert parallelism is numerically invisible (EP placement == the
single-device MoE model), aux load-balancing loss flows into the objective,
and the LMTrainer drives it end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_training_tpu.config import (
    DataConfig,
    LMConfig,
    MeshSpec,
    MoEConfig,
    TrainConfig,
)
from distributed_training_tpu.models import get_model
from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
from distributed_training_tpu.train.lm_step import (
    make_lm_batch,
    make_tp_lm_train_step,
)
from distributed_training_tpu.train.lm_trainer import LMTrainer

from conftest import needs_partial_manual

VOCAB = 64


def _moe_model(expert_axis=None):
    return get_model(
        "transformer_lm", num_classes=VOCAB, seq_axis=None,
        num_layers=2, num_heads=2, hidden_dim=32, max_len=64,
        moe_num_experts=4, moe_top_k=2, moe_expert_axis=expert_axis)


def test_moe_every_alternates():
    model = _moe_model()
    variables = model.init({"params": jax.random.PRNGKey(0)},
                           jnp.zeros((1, 8), jnp.int32), train=False)
    params = variables["params"]
    # moe_every=2 → block0 dense, block1 MoE.
    assert "mlp" in params["block0"] and "moe_mlp" not in params["block0"]
    assert "moe_mlp" in params["block1"] and "mlp" not in params["block1"]
    assert params["block1"]["moe_mlp"]["experts"]["w1"].shape[0] == 4


def test_moe_aux_loss_reaches_objective():
    """The sown load-balancing loss contributes to the training loss."""
    model = _moe_model()
    variables = model.init({"params": jax.random.PRNGKey(0)},
                           jnp.zeros((1, 8), jnp.int32), train=False)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (2, 16)), jnp.int32)
    logits, mutated = model.apply(
        variables, tokens, train=True, mutable=["aux_loss"],
        rngs={"gate": jax.random.PRNGKey(1)})
    aux = jax.tree.leaves(dict(mutated)["aux_loss"])
    assert aux and all(float(a) > 0 for a in aux)


def test_ep_matches_single_device():
    """(data=2 × expert=4) MoE step == the unsharded MoE step."""
    mesh = create_mesh(MeshConfig(data=2, expert=4))
    batch = make_lm_batch(
        np.random.RandomState(0).randint(0, VOCAB, (4, 17)).astype(np.int32))
    rng = jax.random.PRNGKey(3)

    import optax
    from distributed_training_tpu.config import PrecisionConfig
    from distributed_training_tpu.train.precision import LossScaleState
    from distributed_training_tpu.train.train_state import init_train_state

    def make_state(expert_axis):
        model = _moe_model(expert_axis)
        return model, init_train_state(
            model, jax.random.PRNGKey(0), (2, 8), optax.sgd(0.1),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)

    # Oracle: unsharded MoE, plain jit on the full batch.
    _, oracle = make_state(None)
    from distributed_training_tpu.train.lm_step import _lm_loss_and_grads

    def oracle_step(state, batch):
        grads, ce, aux, _ = _lm_loss_and_grads(
            state, jnp.asarray(batch["tokens"]),
            jnp.asarray(batch["targets"]), rng)
        return state.apply_gradients(grads), ce + aux

    oracle_new, oracle_loss = jax.jit(oracle_step)(oracle, batch)

    model, ep_state = make_state("expert")
    step = make_tp_lm_train_step(mesh, model=model, donate=False)
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()}, step.batch_shardings)
    ep_new, metrics = step(ep_state, gbatch, rng)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(oracle_loss), atol=1e-5, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        ep_new.params, oracle_new.params)
    # Expert weights really land sharded over the expert axis.
    w1 = ep_new.params["block1"]["moe_mlp"]["experts"]["w1"]
    assert w1.sharding.spec == P("expert", None, None)
    assert w1.addressable_shards[0].data.shape[0] == 1  # 4 experts / 4 ranks


def test_lm_trainer_moe_ep(tmp_path):
    cfg = TrainConfig(model="transformer_lm").replace(
        num_epochs=2, log_interval=4,
        data=DataConfig(batch_size=8, max_steps_per_epoch=4),
        lm=LMConfig(seq_len=32, num_layers=2, num_heads=4, hidden_dim=32,
                    max_len=64, train_sequences=256, eval_sequences=64),
        moe=MoEConfig(enabled=True, num_experts=(4,), top_k=2),
        mesh=MeshSpec(data=4, expert=2),
    )
    result = LMTrainer(cfg).fit()
    assert np.isfinite(result["final_perplexity"])
    assert result["final_perplexity"] < 250


def test_lm_trainer_moe_rejects_bad_mesh(tmp_path):
    cfg = TrainConfig(model="transformer_lm").replace(
        moe=MoEConfig(enabled=True, num_experts=(4,)),
        mesh=MeshSpec(data=2, pipe=2, expert=2),
        lm=LMConfig(num_layers=2))
    # The PP×MoE refusal is a documented parity contract, not a gap: the
    # message must cite DeepSpeed's own pipeline-engine restriction
    # (VERDICT r4 item 7).
    with pytest.raises(NotImplementedError,
                       match="PipelineModule cannot carry MoE"):
        LMTrainer(cfg)
    cfg = TrainConfig(model="transformer_lm").replace(
        moe=MoEConfig(enabled=True, num_experts=(3,)),
        mesh=MeshSpec(data=4, expert=2),
        lm=LMConfig(num_layers=2))
    with pytest.raises(ValueError, match="num_experts"):
        LMTrainer(cfg)


class TestMoeParamGroup:
    """--moe-param-group (DeepSpeed: expert params in their own optimizer
    groups so ZeRO partitions their state per EP group). The rule table
    always shards expert moments over the expert axis — the flag's
    semantics ARE the implemented behavior — so the contract is: ZeRO×EP
    requires the flag (no silent implication), and with it the expert
    moments really are expert-sharded while dense moments shard over data.
    """

    def _cfg(self, stage, param_group):
        from distributed_training_tpu.config import ZeroConfig

        return TrainConfig(model="transformer_lm").replace(
            num_epochs=1, log_interval=4,
            data=DataConfig(batch_size=8, max_steps_per_epoch=2),
            lm=LMConfig(seq_len=32, num_layers=2, num_heads=4, hidden_dim=32,
                        max_len=64, train_sequences=64, eval_sequences=32),
            moe=MoEConfig(enabled=True, num_experts=(4,), top_k=2,
                          moe_param_group=param_group),
            mesh=MeshSpec(data=4, expert=2),
            zero=ZeroConfig(stage=1),
        ) if stage else TrainConfig(model="transformer_lm")

    def test_zero_ep_requires_flag(self):
        with pytest.raises(ValueError, match="moe-param-group"):
            LMTrainer(self._cfg(1, False))

    def test_expert_moments_expert_sharded_dense_moments_data_sharded(self):
        trainer = LMTrainer(self._cfg(1, True))
        # Expert moment: leading E dim sharded over the expert axis.
        flat = jax.tree_util.tree_flatten_with_path(trainer.state.opt_state)[0]
        expert_specs = [v.sharding.spec for p, v in flat
                        if "experts" in str(p) and "w1" in str(p)]
        assert expert_specs, "no expert moment leaves found"
        assert all(s[0] == "expert" for s in expert_specs), expert_specs
        # Dense moment (fc1 kernel): sharded over data (ZeRO-1), not expert.
        dense_specs = [v.sharding.spec for p, v in flat
                       if "fc1" in str(p) and "kernel" in str(p)]
        assert dense_specs, "no dense moment leaves found"
        for s in dense_specs:
            flat_axes = [a for e in s if e for a in
                         ((e,) if isinstance(e, str) else e)]
            assert "expert" not in flat_axes
            assert "data" in flat_axes, dense_specs


class TestPerLayerExperts:
    """DeepSpeed `--num-experts 4 8` per-layer lists (round 4): each MoE
    layer builds its own expert count; EP sharding requires every count to
    divide the expert axis."""

    def test_layer_map(self):
        from distributed_training_tpu.models.gpt import moe_layer_experts

        assert moe_layer_experts(4, 2, (4, 8)) == {1: 4, 3: 8}
        assert moe_layer_experts(4, 2, (4,)) == {1: 4, 3: 4}
        assert moe_layer_experts(4, 2, 4) == {1: 4, 3: 4}
        assert moe_layer_experts(4, 2, 0) == {}
        with pytest.raises(ValueError, match="do not match"):
            moe_layer_experts(4, 2, (4, 8, 16))

    def test_model_builds_per_layer_counts(self):
        from distributed_training_tpu.models import get_model

        model = get_model(
            "transformer_lm", num_classes=32, seq_axis=None,
            num_layers=4, num_heads=2, hidden_dim=16, max_len=64,
            moe_num_experts=(4, 8), moe_top_k=1)
        params = model.init(
            {"params": jax.random.PRNGKey(0), "gate": jax.random.PRNGKey(1)},
            jnp.zeros((2, 8), jnp.int32), train=False)["params"]
        assert params["block1"]["moe_mlp"]["experts"]["w1"].shape[0] == 4
        assert params["block3"]["moe_mlp"]["experts"]["w1"].shape[0] == 8
        assert "moe_mlp" not in params["block0"]
        logits = model.apply(
            {"params": params}, jnp.zeros((2, 8), jnp.int32),
            rngs={"gate": jax.random.PRNGKey(2)})
        assert np.all(np.isfinite(np.asarray(logits, np.float32)))

    def test_trainer_end_to_end_per_layer(self):
        cfg = TrainConfig(model="transformer_lm").replace(
            num_epochs=1, log_interval=4,
            data=DataConfig(batch_size=8, max_steps_per_epoch=4),
            lm=LMConfig(seq_len=32, num_layers=4, num_heads=4, hidden_dim=32,
                        max_len=64, train_sequences=64, eval_sequences=32),
            moe=MoEConfig(enabled=True, num_experts=(4, 8), top_k=1),
            mesh=MeshSpec(data=4, expert=2),
        )
        result = LMTrainer(cfg).fit()
        assert np.isfinite(result["final_perplexity"])

    def test_ep_divisibility_checked_per_layer(self):
        cfg = TrainConfig(model="transformer_lm").replace(
            data=DataConfig(batch_size=8),
            lm=LMConfig(seq_len=32, num_layers=4, num_heads=4, hidden_dim=32,
                        max_len=64),
            moe=MoEConfig(enabled=True, num_experts=(4, 3), top_k=1),
            mesh=MeshSpec(data=4, expert=2),
        )
        with pytest.raises(ValueError, match="every"):
            LMTrainer(cfg)


class TestPipelineMoE:
    """PP × MoE (round 5): homogeneous MoE stacks (moe_every=1, one expert
    count) run through the pipeline executor — beyond DeepSpeed, whose
    PipelineModule cannot carry MoE at all. Routing granularity is per
    (data shard × microbatch) — the standard pipeline-MoE semantics — so
    exactness vs the GSPMD path holds when the shard IS the whole batch."""

    def _model(self, **kw):
        return get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis=None,
            num_layers=2, num_heads=2, hidden_dim=16, max_len=64,
            moe_num_experts=4, moe_every=1, moe_top_k=2, **kw)

    def _pp_run(self, mesh, model, host, rng, num_microbatches):
        import optax

        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.train.lm_step import (
            make_pp_lm_train_step,
        )
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import TrainState

        step = make_pp_lm_train_step(
            mesh, model=model, num_microbatches=num_microbatches,
            donate=False)
        plm = step.pipelined
        state = TrainState.create(
            apply_fn=plm.apply_fn,
            params=plm.init_params(jax.random.PRNGKey(0)),
            tx=optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = jax.device_put(state, step.state_shardings(state))
        batch = jax.device_put(
            {k: jnp.asarray(v) for k, v in host.items()},
            step.batch_shardings)
        _, m = step(state, batch, rng)
        return m

    def _ref_run(self, model, host, rng, devices):
        import optax

        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.parallel.sharding import place_state
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import (
            init_train_state,
        )

        mesh = create_mesh(MeshConfig(data=1), devices=devices[:1])
        step = make_tp_lm_train_step(mesh, model=model, donate=False)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (2, 8), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
            input_dtype=jnp.int32)
        state = place_state(state, step.state_shardings(state))
        batch = jax.device_put(
            {k: jnp.asarray(v) for k, v in host.items()},
            step.batch_shardings)
        _, m = step(state, batch, rng)
        return m

    @needs_partial_manual
    def test_exact_vs_plain_at_whole_batch_granularity(self, devices):
        """data=1 × m=1: the PP stage routes the identical token set, so
        loss AND aux match the plain GSPMD model to fp32 tolerance."""
        model = self._model()
        toks = np.random.RandomState(0).randint(
            0, VOCAB, (8, 17)).astype(np.int32)
        host = make_lm_batch(toks)
        rng = jax.random.PRNGKey(5)
        rm = self._ref_run(model, host, rng, devices)
        mesh = create_mesh(MeshConfig(data=1, pipe=2), devices=devices[:2])
        pm = self._pp_run(mesh, model, host, rng, num_microbatches=1)
        np.testing.assert_allclose(float(pm["loss"]), float(rm["loss"]),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(pm["aux_loss"]),
                                   float(rm["aux_loss"]), rtol=1e-4)

    @needs_partial_manual
    def test_dp_pp_ep_zero1_step(self, devices):
        """The full product: data × pipe × expert mesh, ZeRO-1 moments,
        microbatched schedule — aux flows, gradients finite."""
        import optax

        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.train.lm_step import (
            make_pp_lm_train_step,
        )
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import TrainState

        mesh = create_mesh(MeshConfig(data=2, pipe=2, expert=2))
        model = self._model(moe_expert_axis="expert")
        step = make_pp_lm_train_step(mesh, model=model, num_microbatches=2,
                                     donate=False, zero_stage=1)
        plm = step.pipelined
        state = TrainState.create(
            apply_fn=plm.apply_fn,
            params=plm.init_params(jax.random.PRNGKey(0)),
            tx=optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = jax.device_put(state, step.state_shardings(state))
        toks = np.random.RandomState(0).randint(
            0, VOCAB, (8, 17)).astype(np.int32)
        batch = jax.device_put(
            {k: jnp.asarray(v) for k, v in make_lm_batch(toks).items()},
            step.batch_shardings)
        _, m = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
        assert float(m["aux_loss"]) > 0
        assert float(m["grads_finite"]) == 1.0

    def test_heterogeneous_stack_refused(self, devices):
        """Alternating (moe_every=2) stays refused with the DeepSpeed
        citation — heterogeneous trees cannot stack."""
        from distributed_training_tpu.parallel.pipeline import PipelinedLM

        mesh = create_mesh(MeshConfig(data=4, pipe=2))
        model = get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis=None,
            num_layers=2, num_heads=2, hidden_dim=16, max_len=64,
            moe_num_experts=4, moe_every=2)
        with pytest.raises(NotImplementedError,
                           match="PipelineModule cannot carry MoE"):
            PipelinedLM(model, mesh, num_microbatches=2)

    @needs_partial_manual
    def test_trainer_end_to_end(self, devices):
        """LMTrainer drives pipe × expert × homogeneous MoE (config
        surface: moe.every=1)."""
        cfg = TrainConfig(
            model="transformer_lm", num_epochs=1, eval_every=1,
            mesh=MeshSpec(data=2, pipe=2, expert=2),
            moe=MoEConfig(enabled=True, num_experts=(4,), every=1,
                          top_k=2),
            data=DataConfig(batch_size=4, max_steps_per_epoch=2),
            lm=LMConfig(seq_len=16, vocab_size=VOCAB, num_layers=2,
                        num_heads=2, hidden_dim=16, max_len=32,
                        num_microbatches=2, train_sequences=64,
                        eval_sequences=32),
        )
        result = LMTrainer(cfg).fit()
        assert np.isfinite(result["final_perplexity"])
