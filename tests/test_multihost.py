"""Multi-process rendezvous (SURVEY.md §4 'Multi-host').

The reference approximates multi-node with 2 local ranks + a TCP store
(``mp.spawn`` + MASTER_ADDR=localhost, ``resnet/pytorch_ddp/ddp_train.py:
79-85,112-114``). The JAX analogue: 2 *processes* (one per would-be host),
``jax.distributed.initialize`` against a local coordinator, 4 virtual CPU
devices each → one 8-device global mesh; a psum must see all 8 devices and
the sharded loader must hand each process disjoint halves of every global
batch.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from distributed_training_tpu.runtime.distributed import initialize_distributed
    initialize_distributed()  # from MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE

    import numpy as np
    import jax.numpy as jnp
    from distributed_training_tpu.runtime.coordinator import Coordinator
    from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
    from distributed_training_tpu.parallel.sharding import batch_sharding
    from distributed_training_tpu.data.pipeline import (
        ShardedDataLoader, to_global_batch)
    from distributed_training_tpu.data.cifar10 import synthetic_cifar10

    coord = Coordinator()
    assert coord.process_count == 2, coord.process_count
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    with coord.priority_execution("test"):
        pass  # serialized section must not deadlock
    coord.barrier("sync")

    mesh = create_mesh(MeshConfig(data=-1))

    x, y = synthetic_cifar10(64, train=True)
    loader = ShardedDataLoader(x, y, global_batch_size=16, shuffle=True,
                               drop_last=True, augment="none", train=True)
    assert loader.local_batch_size == 8
    batch = next(iter(loader))
    shardings = {k: batch_sharding(mesh, v.ndim) for k, v in batch.items()}
    gbatch = to_global_batch(batch, mesh, shardings)
    assert gbatch["image"].shape[0] == 16  # global logical batch

    # A cross-process collective: each process contributes a DIFFERENT
    # local shard of a global array sharded across both processes' devices;
    # the jitted sum must communicate to see all shards. rank0 holds
    # [1,2,3,4], rank1 [5,6,7,8] -> global sum 36 on both.
    from jax.sharding import NamedSharding, PartitionSpec as Pspec
    sharding = NamedSharding(mesh, Pspec("data"))
    local = np.arange(1, 5, dtype=np.float32) + 4 * coord.process_index
    garr = jax.make_array_from_process_local_data(sharding, local)
    assert garr.shape == (8,)
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, Pspec()))(garr)
    # And through the sharded array: mean label must match on all processes.
    mean_label = float(jnp.mean(gbatch["label"].astype(jnp.float32)))
    print(f"OK rank={coord.process_index} total={float(total)} "
          f"mean_label={mean_label:.4f}", flush=True)
""")


TRAIN_CKPT_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from distributed_training_tpu.runtime.distributed import initialize_distributed
    initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    import optax
    from distributed_training_tpu import checkpoint as ckpt_lib
    from distributed_training_tpu.config import PrecisionConfig
    from distributed_training_tpu.models import get_model
    from distributed_training_tpu.parallel.sharding import (
        batch_sharding, place_state, state_shardings)
    from distributed_training_tpu.runtime.coordinator import Coordinator
    from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
    from distributed_training_tpu.data.pipeline import to_global_batch
    from distributed_training_tpu.train.precision import LossScaleState
    from distributed_training_tpu.train.step import make_train_step
    from distributed_training_tpu.train.train_state import init_train_state

    ckpt_dir = os.environ["CKPT_DIR"]
    coord = Coordinator()
    assert jax.device_count() == 8 and jax.local_device_count() == 4

    mesh = create_mesh(MeshConfig(data=-1))
    model = get_model("resnet18", num_classes=10, stem="cifar")
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3))
    state = init_train_state(
        model, jax.random.PRNGKey(0), (8, 8, 8, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
    shardings = state_shardings(state, mesh, zero_stage=1)
    state = place_state(state, shardings)
    step = make_train_step(mesh, zero_stage=1, donate=False)

    def global_batch(seed):
        rng = np.random.RandomState(seed)
        # Each process contributes its own half of the global batch.
        local = {
            "image": rng.rand(16, 8, 8, 3).astype(np.float32)[
                coord.process_index * 8:(coord.process_index + 1) * 8],
            "label": rng.randint(0, 10, 16).astype(np.int32)[
                coord.process_index * 8:(coord.process_index + 1) * 8],
        }
        shard = {k: batch_sharding(mesh, v.ndim) for k, v in local.items()}
        return to_global_batch(local, mesh, shard)

    # N train steps, then a coordinated orbax save: every process writes
    # only its addressable shards of the zero-1-sharded state.
    losses = []
    for i in range(3):
        state, metrics = step(state, global_batch(i), jax.random.PRNGKey(i))
        losses.append(float(metrics["loss"]))
    ckpt_lib.save_checkpoint(ckpt_dir, 0, state, epoch_step=3)
    coord.barrier("saved")

    # One more step BEFORE restore; then restore must rewind to the save.
    drifted, _ = step(state, global_batch(9), jax.random.PRNGKey(9))
    template = place_state(init_train_state(
        model, jax.random.PRNGKey(1), (8, 8, 8, 3), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32"))),
        shardings)
    restored, next_epoch, estep = ckpt_lib.restore_checkpoint(
        ckpt_dir, 0, template)
    assert next_epoch == 1 and estep == 3, (next_epoch, estep)
    same = jax.tree.map(
        lambda a, b: bool(jnp.allclose(a, b, atol=0, rtol=0)),
        jax.device_get(jax.tree.leaves(restored.params)),
        jax.device_get(jax.tree.leaves(state.params)))
    assert all(same), "restore is not step-accurate"
    diff = jax.tree.map(
        lambda a, b: bool(jnp.allclose(a, b)),
        jax.device_get(jax.tree.leaves(restored.params)),
        jax.device_get(jax.tree.leaves(drifted.params)))
    assert not all(diff), "restore returned the post-save drifted params"

    # Training continues from the restored state across both processes.
    cont, metrics = step(restored, global_batch(3), jax.random.PRNGKey(3))
    print(f"OK rank={coord.process_index} losses={losses[0]:.4f}->"
          f"{losses[-1]:.4f} cont={float(metrics['loss']):.4f}", flush=True)
""")


TP_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from distributed_training_tpu.runtime.distributed import initialize_distributed
    initialize_distributed()

    import numpy as np
    import jax.numpy as jnp
    import optax
    from distributed_training_tpu import checkpoint as ckpt_lib
    from distributed_training_tpu.config import PrecisionConfig
    from distributed_training_tpu.models import get_model
    from distributed_training_tpu.parallel.sharding import place_state
    from distributed_training_tpu.runtime.coordinator import Coordinator
    from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
    from distributed_training_tpu.train.lm_step import (
        make_lm_batch, make_tp_lm_train_step)
    from distributed_training_tpu.train.precision import LossScaleState
    from distributed_training_tpu.train.train_state import init_train_state

    ckpt_dir = os.environ["CKPT_DIR"]
    coord = Coordinator()
    assert jax.device_count() == 8 and jax.local_device_count() == 4

    # Permute the device order so the MODEL axis pairs device i (process 0)
    # with device i+4 (process 1): every megatron row-parallel psum then
    # crosses the process boundary — the DCN-like path a single-process
    # virtual mesh can never exercise.
    devs = jax.devices()
    order = [devs[(i // 2) + 4 * (i % 2)] for i in range(8)]
    mesh = create_mesh(MeshConfig(data=4, model=2), devices=order)
    ax = dict(zip(mesh.axis_names, range(len(mesh.axis_names))))
    pairs = np.moveaxis(mesh.devices, ax["model"], -1).reshape(-1, 2)
    pidx = np.vectorize(lambda d: d.process_index)(pairs)
    assert (pidx[:, 0] != pidx[:, 1]).all(), (
        "model axis must cross the process boundary")

    model = get_model(
        "transformer_lm", num_classes=32, seq_axis=None,
        num_layers=2, num_heads=2, hidden_dim=16, max_len=64)
    tx = optax.adam(1e-3)
    state = init_train_state(
        model, jax.random.PRNGKey(0), (2, 8), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
        input_dtype=jnp.int32)
    step = make_tp_lm_train_step(mesh, model=model, zero_stage=1,
                                 donate=False)
    shardings = step.state_shardings(state)
    state = place_state(state, shardings)

    def global_batch(seed):
        toks = np.random.RandomState(seed).randint(
            0, 32, (8, 17)).astype(np.int32)
        host = make_lm_batch(toks)
        # Both processes hold the full deterministic array; each device
        # materializes only its addressable shard.
        return {
            k: jax.make_array_from_callback(
                v.shape, step.batch_shardings[k],
                lambda idx, v=v: v[idx])
            for k, v in host.items()
        }

    losses = []
    for i in range(3):
        state, metrics = step(state, global_batch(i), jax.random.PRNGKey(i))
        losses.append(round(float(metrics["loss"]), 6))
    ckpt_lib.save_checkpoint(ckpt_dir, 0, state, epoch_step=3)
    coord.barrier("saved")

    drifted, _ = step(state, global_batch(9), jax.random.PRNGKey(9))
    template = place_state(init_train_state(
        model, jax.random.PRNGKey(1), (2, 8), tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
        input_dtype=jnp.int32), shardings)
    restored, next_epoch, estep = ckpt_lib.restore_checkpoint(
        ckpt_dir, 0, template)
    assert next_epoch == 1 and estep == 3, (next_epoch, estep)

    # TP-sharded leaves span BOTH processes, so device_get cannot fetch
    # them; compare under jit with a replicated scalar result instead.
    from jax.sharding import NamedSharding, PartitionSpec as Pspec
    repl = NamedSharding(mesh, Pspec())

    def trees_equal(t1, t2):
        f = jax.jit(
            lambda a, b: jnp.stack([
                jnp.all(u == v)
                for u, v in zip(jax.tree.leaves(a), jax.tree.leaves(b))
            ]).all(),
            out_shardings=repl)
        return bool(f(t1, t2))

    assert trees_equal(restored.params, state.params), \\
        "restore is not step-accurate"
    assert not trees_equal(restored.params, drifted.params), \\
        "restore returned the post-save drifted params"

    cont, metrics = step(restored, global_batch(3), jax.random.PRNGKey(3))
    print(f"OK rank={coord.process_index} losses={losses} "
          f"cont={float(metrics['loss']):.6f}", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_process(worker: str, extra_env: dict | None = None,
                     timeout: int = 420):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            PYTHONPATH=REPO,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            RANK=str(rank),
            WORLD_SIZE="2",
            **(extra_env or {}),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        # A crashed rank leaves its peer blocked in a collective: kill the
        # survivors so the REAL failure surfaces (not a timeout) and no
        # orphan keeps the rendezvous port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
    return [o.strip().splitlines()[-1] for _, o, _ in outs]


@pytest.mark.slow
def test_two_process_rendezvous_and_sharding():
    lines = _run_two_process(WORKER)
    assert any("rank=0" in l for l in lines)
    assert any("rank=1" in l for l in lines)
    # Both processes computed over the same 8-device world and agree on the
    # globally-sharded batch content.
    total0 = [l for l in lines if "rank=0" in l][0]
    total1 = [l for l in lines if "rank=1" in l][0]
    assert total0.split("total=")[1] == total1.split("total=")[1]
    assert total0.split("mean_label=")[1] == total1.split("mean_label=")[1]
    assert "total=36.0" in total0


@pytest.mark.slow
def test_two_process_train_and_checkpoint(tmp_path):
    """End-to-end across 2 real processes (SURVEY §4 'Multi-host', closed
    fully in round 4): N zero-1 train steps on process-disjoint batch
    halves, a coordinated orbax save where each process writes only its
    addressable shards, a step-accurate restore (rewinds past a post-save
    drift step), and continued training from the restored state. Exercises
    the classic multi-host checkpoint corruption/deadlock class."""
    lines = _run_two_process(
        TRAIN_CKPT_WORKER, extra_env={"CKPT_DIR": str(tmp_path / "ckpt")})
    assert any("rank=0" in l for l in lines), lines
    assert any("rank=1" in l for l in lines), lines
    # Both processes observed identical global losses and the identical
    # post-restore continuation loss.
    l0 = [l for l in lines if "rank=0" in l][0]
    l1 = [l for l in lines if "rank=1" in l][0]
    assert l0.split("losses=")[1] == l1.split("losses=")[1]


@pytest.mark.slow
def test_two_process_tensor_parallel(tmp_path):
    """A NON-data axis crosses the process boundary (round 5, VERDICT item
    4): the TP worker permutes the device order so every megatron model-axis
    psum spans the two processes, runs 3 ZeRO-1 train steps on a
    deterministic global batch, does the coordinated orbax save +
    step-accurate restore, and continues training. The observed losses must
    match a single-process 8-device run of the identical program — the
    cross-process collectives change the transport, not the math."""
    lines = _run_two_process(
        TP_WORKER, extra_env={"CKPT_DIR": str(tmp_path / "ckpt")})
    l0 = [l for l in lines if "rank=0" in l][0]
    l1 = [l for l in lines if "rank=1" in l][0]
    assert l0.split("losses=")[1] == l1.split("losses=")[1]

    # Single-process oracle: same mesh shape, same params, same batches on
    # the pytest process's own 8 virtual devices.
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_training_tpu.config import PrecisionConfig
    from distributed_training_tpu.models import get_model
    from distributed_training_tpu.parallel.sharding import place_state
    from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
    from distributed_training_tpu.train.lm_step import (
        make_lm_batch,
        make_tp_lm_train_step,
    )
    from distributed_training_tpu.train.precision import LossScaleState
    from distributed_training_tpu.train.train_state import init_train_state

    mesh = create_mesh(MeshConfig(data=4, model=2))
    model = get_model(
        "transformer_lm", num_classes=32, seq_axis=None,
        num_layers=2, num_heads=2, hidden_dim=16, max_len=64)
    state = init_train_state(
        model, jax.random.PRNGKey(0), (2, 8), optax.adam(1e-3),
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")),
        input_dtype=jnp.int32)
    step = make_tp_lm_train_step(mesh, model=model, zero_stage=1,
                                 donate=False)
    state = place_state(state, step.state_shardings(state))
    want = []
    for i in range(3):
        toks = np.random.RandomState(i).randint(0, 32, (8, 17)).astype(
            np.int32)
        batch = jax.device_put(
            {k: jnp.asarray(v) for k, v in make_lm_batch(toks).items()},
            step.batch_shardings)
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
        want.append(round(float(metrics["loss"]), 6))
    got = eval(l0.split("losses=")[1].split(" cont=")[0])
    np.testing.assert_allclose(got, want, rtol=1e-5)
