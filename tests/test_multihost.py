"""Multi-process rendezvous (SURVEY.md §4 'Multi-host').

The reference approximates multi-node with 2 local ranks + a TCP store
(``mp.spawn`` + MASTER_ADDR=localhost, ``resnet/pytorch_ddp/ddp_train.py:
79-85,112-114``). The JAX analogue: 2 *processes* (one per would-be host),
``jax.distributed.initialize`` against a local coordinator, 4 virtual CPU
devices each → one 8-device global mesh; a psum must see all 8 devices and
the sharded loader must hand each process disjoint halves of every global
batch.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")

    from distributed_training_tpu.runtime.distributed import initialize_distributed
    initialize_distributed()  # from MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE

    import numpy as np
    import jax.numpy as jnp
    from distributed_training_tpu.runtime.coordinator import Coordinator
    from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
    from distributed_training_tpu.parallel.sharding import batch_sharding
    from distributed_training_tpu.data.pipeline import (
        ShardedDataLoader, to_global_batch)
    from distributed_training_tpu.data.cifar10 import synthetic_cifar10

    coord = Coordinator()
    assert coord.process_count == 2, coord.process_count
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    with coord.priority_execution("test"):
        pass  # serialized section must not deadlock
    coord.barrier("sync")

    mesh = create_mesh(MeshConfig(data=-1))

    x, y = synthetic_cifar10(64, train=True)
    loader = ShardedDataLoader(x, y, global_batch_size=16, shuffle=True,
                               drop_last=True, augment="none", train=True)
    assert loader.local_batch_size == 8
    batch = next(iter(loader))
    shardings = {k: batch_sharding(mesh, v.ndim) for k, v in batch.items()}
    gbatch = to_global_batch(batch, mesh, shardings)
    assert gbatch["image"].shape[0] == 16  # global logical batch

    # A cross-process collective: each process contributes a DIFFERENT
    # local shard of a global array sharded across both processes' devices;
    # the jitted sum must communicate to see all shards. rank0 holds
    # [1,2,3,4], rank1 [5,6,7,8] -> global sum 36 on both.
    from jax.sharding import NamedSharding, PartitionSpec as Pspec
    sharding = NamedSharding(mesh, Pspec("data"))
    local = np.arange(1, 5, dtype=np.float32) + 4 * coord.process_index
    garr = jax.make_array_from_process_local_data(sharding, local)
    assert garr.shape == (8,)
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, Pspec()))(garr)
    # And through the sharded array: mean label must match on all processes.
    mean_label = float(jnp.mean(gbatch["label"].astype(jnp.float32)))
    print(f"OK rank={coord.process_index} total={float(total)} "
          f"mean_label={mean_label:.4f}", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_rendezvous_and_sharding():
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            PYTHONPATH=REPO,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            RANK=str(rank),
            WORLD_SIZE="2",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env))

    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]
    lines = [o.strip().splitlines()[-1] for _, o, _ in outs]
    assert any("rank=0" in l for l in lines)
    assert any("rank=1" in l for l in lines)
    # Both processes computed over the same 8-device world and agree on the
    # globally-sharded batch content.
    total0 = [l for l in lines if "rank=0" in l][0]
    total1 = [l for l in lines if "rank=1" in l][0]
    assert total0.split("total=")[1] == total1.split("total=")[1]
    assert total0.split("mean_label=")[1] == total1.split("mean_label=")[1]
    assert "total=36.0" in total0
