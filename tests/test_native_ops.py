"""Native C++ augmentation library: parity with the numpy path.

The native lib is an accelerator, never a dependency — tests skip when no
compiler/lib is available (the numpy fallback is covered in test_data.py).
"""

import numpy as np
import pytest

from distributed_training_tpu.data import transforms
from distributed_training_tpu.ops.native import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native augment lib unavailable")


def _imgs(n=32, h=32, w=32, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, h, w, 3), dtype=np.uint8)


def test_pad_crop_flip_matches_numpy_bytewise():
    x = _imgs()
    a = transforms.pad_crop_flip(x, np.random.RandomState(7), use_native=True)
    b = transforms.pad_crop_flip(x, np.random.RandomState(7), use_native=False)
    np.testing.assert_array_equal(a, b)


def test_pad_crop_flip_edge_offsets():
    """Extreme crop offsets (0 and 2·pad) hit the zero-padding borders."""
    x = _imgs(n=4)
    pad = 4
    for y0, x0, flip in [(0, 0, 0), (8, 8, 1), (0, 8, 1), (8, 0, 0)]:
        ys = np.full(4, y0, np.int32)
        xs = np.full(4, x0, np.int32)
        fl = np.full(4, flip, np.uint8)
        out = native.pad_crop_flip(x, ys, xs, fl, pad)
        # Build numpy reference directly from the same offsets.
        padded = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        ref = padded[:, y0:y0 + 32, x0:x0 + 32, :]
        if flip:
            ref = ref[:, :, ::-1, :]
        np.testing.assert_array_equal(out, ref)


def test_u8_to_f32_affine():
    x = _imgs(n=2)
    out = native.u8_to_f32(x, 2.0 / 255.0, -1.0)
    ref = x.astype(np.float32) * (2.0 / 255.0) - 1.0
    np.testing.assert_allclose(out, ref, atol=1e-6)
    assert out.dtype == np.float32


def test_non_contiguous_input_handled():
    x = _imgs(n=8)[::2]  # stride-2 view
    a = transforms.pad_crop_flip(x, np.random.RandomState(3), use_native=True)
    b = transforms.pad_crop_flip(
        np.ascontiguousarray(x), np.random.RandomState(3), use_native=False)
    np.testing.assert_array_equal(a, b)


def test_native_faster_than_numpy():
    import time

    big = _imgs(n=1024, seed=5)

    def bench(use_native):
        rng = np.random.RandomState(0)
        t0 = time.perf_counter()
        for _ in range(5):
            transforms.pad_crop_flip(big, rng, use_native=use_native)
        return time.perf_counter() - t0

    bench(True)  # warm the thread pool/page cache
    t_native = bench(True)
    t_numpy = bench(False)
    # Regression guard only (CI machines vary): native must not be slower.
    assert t_native < t_numpy * 1.5, (t_native, t_numpy)
