"""Metric sinks (TensorBoard/JSONL) + preemption checkpoint-restart.

The reference has neither durable metrics nor any failure handling
(SURVEY.md §5); these tests pin the extensions: MetricsWriter fan-out,
PreemptionGuard signal latching, and the Trainer's SIGTERM →
save-checkpoint → auto_resume round trip.
"""

import json
import os
import signal

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import (
    CheckpointConfig,
    DataConfig,
    TrainConfig,
)
from distributed_training_tpu.runtime.preemption import PreemptionGuard
from distributed_training_tpu.utils.metrics_io import MetricsWriter


class TestMetricsWriter:
    def test_jsonl_lines(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with MetricsWriter(jsonl_path=path) as w:
            w.write(10, {"loss": 1.5, "step": 10})
            w.write(20, {"loss": 0.5, "step": 20}, prefix="eval")
        rows = [json.loads(l) for l in open(path)]
        assert rows == [
            {"step": 10, "prefix": "train", "loss": 1.5},
            {"step": 20, "prefix": "eval", "loss": 0.5},
        ]

    def test_jsonl_appends_across_writers(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with MetricsWriter(jsonl_path=path) as w:
            w.write(1, {"loss": 1.0})
        with MetricsWriter(jsonl_path=path) as w:
            w.write(2, {"loss": 2.0})
        assert len(open(path).readlines()) == 2

    def test_tensorboard_events_written(self, tmp_path):
        tb = pytest.importorskip("torch.utils.tensorboard")
        del tb
        d = str(tmp_path / "tb")
        with MetricsWriter(tensorboard_dir=d) as w:
            w.write(1, {"loss": 3.0})
        files = [f for f in os.listdir(d) if "tfevents" in f]
        assert files, f"no event files in {os.listdir(d)}"

    def test_disabled_is_noop(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with MetricsWriter(jsonl_path=path, enabled=False) as w:
            w.write(1, {"loss": 1.0})
        assert not os.path.exists(path)


class TestPreemptionGuard:
    def test_sigterm_latches(self):
        with PreemptionGuard() as guard:
            assert not guard.triggered
            signal.raise_signal(signal.SIGTERM)
            assert guard.triggered

    def test_handler_restored_on_exit(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionGuard():
            assert signal.getsignal(signal.SIGTERM) != before
        assert signal.getsignal(signal.SIGTERM) == before

    def test_should_stop_single_process_every_step(self):
        with PreemptionGuard() as guard:
            assert not guard.should_stop(at_sync_point=False)
            signal.raise_signal(signal.SIGTERM)
            # Single process: no cross-host agreement needed; stop anywhere.
            assert guard.should_stop(at_sync_point=False)

    def test_custom_previous_handler_gets_second_signal(self):
        hits = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
        try:
            with PreemptionGuard() as guard:
                signal.raise_signal(signal.SIGTERM)
                assert guard.triggered and not hits
                signal.raise_signal(signal.SIGTERM)
                assert hits == [signal.SIGTERM]
        finally:
            signal.signal(signal.SIGTERM, prev)


def _cfg(tmp_path, **kw):
    return TrainConfig(
        model="resnet_micro",
        num_epochs=2,
        log_interval=2,
        eval_every=0,
        data=DataConfig(dataset="synthetic_cifar", batch_size=4,
                        max_steps_per_epoch=4, prefetch=0),
        checkpoint=CheckpointConfig(
            directory=str(tmp_path / "ckpt"), interval=0, **kw),
    )


class TestTrainerPreemption:
    def test_sigterm_saves_and_auto_resume_completes(self, mesh, tmp_path):
        from distributed_training_tpu import checkpoint as ckpt_lib
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _cfg(tmp_path, auto_resume=True)
        tr = Trainer(cfg, mesh=mesh)

        # Deliver SIGTERM from inside the 2nd step of epoch 0: wrap the
        # train step so the signal arrives while the guard is installed.
        real_step = tr.train_step
        calls = []

        def step_then_signal(state, batch, rng):
            out = real_step(state, batch, rng)
            calls.append(1)
            if len(calls) == 2:
                signal.raise_signal(signal.SIGTERM)
            return out

        tr.train_step = step_then_signal
        result = tr.fit()
        assert result["preempted"] is True
        assert calls, "no steps ran"
        # Preemption checkpoint exists and resumes at epoch 0 (partial).
        assert ckpt_lib.latest_epoch(cfg.checkpoint.directory) == 0
        steps_before = result["steps"]

        # Fresh trainer with auto_resume picks it up and runs to completion.
        tr2 = Trainer(cfg, mesh=mesh)
        result2 = tr2.fit()
        assert result2["preempted"] is False
        assert result2["steps"] > steps_before
        # Step-accurate resume: epoch 0 resumes AFTER its already-trained
        # prefix (steps_before batches), so the total equals an uninterrupted
        # 2×4-step run — no batch trains twice.
        assert result2["steps"] == 8

    def test_metrics_jsonl_written_by_trainer(self, mesh, tmp_path):
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _cfg(tmp_path).replace(
            num_epochs=1, metrics_jsonl=str(tmp_path / "metrics.jsonl"))
        Trainer(cfg, mesh=mesh).fit()
        rows = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
        assert rows and all("loss" in r for r in rows)
        assert rows[-1]["step"] == 4


class TestCheckpointNextEpoch:
    def test_mid_epoch_save_resumes_same_epoch(self, mesh, tmp_path):
        import optax

        from distributed_training_tpu import checkpoint as ckpt_lib
        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.models import get_model
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import init_train_state

        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        state = init_train_state(
            model, jax.random.PRNGKey(0), (1, 8, 8, 3), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        d = str(tmp_path / "c")
        ckpt_lib.save_checkpoint(d, 3, state, next_epoch=3)
        _, start, _ = ckpt_lib.restore_checkpoint(d, 3, state)
        assert start == 3
        ckpt_lib.save_checkpoint(d, 3, state)  # normal end-of-epoch save
        _, start, _ = ckpt_lib.restore_checkpoint(d, 3, state)
        assert start == 4

    def test_old_format_checkpoint_restores_with_epoch_plus_one(
            self, mesh, tmp_path):
        """Pre-next_epoch checkpoints (meta = {epoch} only) still restore,
        with the old epoch+1 resume semantics."""
        import optax
        import orbax.checkpoint as ocp
        from flax import serialization

        from distributed_training_tpu import checkpoint as ckpt_lib
        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.models import get_model
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import init_train_state

        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        state = init_train_state(
            model, jax.random.PRNGKey(0), (1, 8, 8, 3), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        path = str(tmp_path / "c" / "epoch_2")
        ocp.PyTreeCheckpointer().save(path, {
            "state": serialization.to_state_dict(state),
            "meta": {"epoch": np.int32(2)},
        })
        _, start, _ = ckpt_lib.restore_checkpoint(str(tmp_path / "c"), 2, state)
        assert start == 3

    def test_preempt_during_first_epoch_roundtrips(self, mesh, tmp_path):
        import optax

        from distributed_training_tpu import checkpoint as ckpt_lib
        from distributed_training_tpu.config import PrecisionConfig
        from distributed_training_tpu.models import get_model
        from distributed_training_tpu.train.precision import LossScaleState
        from distributed_training_tpu.train.train_state import init_train_state

        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        state = init_train_state(
            model, jax.random.PRNGKey(0), (1, 8, 8, 3), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        d = str(tmp_path / "c")
        ckpt_lib.save_checkpoint(d, 0, state, next_epoch=0)
        assert ckpt_lib.latest_epoch(d) == 0
        _, start, _ = ckpt_lib.restore_checkpoint(d, 0, state)
        assert start == 0


class TestEpochBoundaryPreemption:
    def test_sigterm_in_final_interval_rolls_to_next_epoch(
            self, mesh, tmp_path):
        """A SIGTERM that lands in the last log interval lets the epoch
        complete; the preemption save must then point at epoch+1/step 0 —
        a resume at skip == len(loader) would be refused as geometry
        drift."""
        from distributed_training_tpu import checkpoint as ckpt_lib
        from distributed_training_tpu.train.trainer import Trainer

        cfg = _cfg(tmp_path, auto_resume=True)
        tr = Trainer(cfg, mesh=mesh)
        real_step = tr.train_step
        calls = []

        def step_then_signal(state, batch, rng):
            out = real_step(state, batch, rng)
            calls.append(1)
            if len(calls) == 4:  # last step of the 4-step epoch 0
                signal.raise_signal(signal.SIGTERM)
            return out

        tr.train_step = step_then_signal
        result = tr.fit()
        assert result["preempted"] is True and result["steps"] == 4
        _, start_epoch, start_step = ckpt_lib.restore_checkpoint(
            cfg.checkpoint.directory, 0, tr.state)
        assert (start_epoch, start_step) == (1, 0)

        # Resume completes epoch 1 only: total = uninterrupted 8 steps.
        result2 = Trainer(cfg, mesh=mesh).fit()
        assert result2["preempted"] is False
        assert result2["steps"] == 8
