"""Optimizer-factory tests: SGD/LAMB families, weight-decay masking."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import OptimizerConfig, SchedulerConfig
from distributed_training_tpu.train.optim import decay_mask, make_optimizer

PARAMS = {
    "dense": {"kernel": jnp.ones((3, 4)), "bias": jnp.ones((4,))},
    "bn": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
}


def _step(tx, params, grads=None):
    grads = grads if grads is not None else jax.tree.map(jnp.ones_like, params)
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    return optax.apply_updates(params, updates)


class TestSGD:
    def test_matches_optax_sgd_momentum(self):
        cfg = OptimizerConfig(name="sgd", lr=0.1, momentum=0.9,
                              weight_decay=0.0)
        ours = make_optimizer(cfg)
        ref = optax.sgd(0.1, momentum=0.9)
        p1, p2 = dict(PARAMS), dict(PARAMS)
        s1, s2 = ours.init(p1), ref.init(p2)
        g = jax.tree.map(lambda x: 0.5 * jnp.ones_like(x), PARAMS)
        for _ in range(3):
            u1, s1 = ours.update(g, s1, p1)
            u2, s2 = ref.update(g, s2, p2)
            p1 = optax.apply_updates(p1, u1)
            p2 = optax.apply_updates(p2, u2)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), p1, p2)

    def test_nesterov_differs_from_plain(self):
        plain = make_optimizer(OptimizerConfig(name="sgd", lr=0.1))
        nest = make_optimizer(OptimizerConfig(name="sgd", lr=0.1,
                                              nesterov=True))
        g = jax.tree.map(jnp.ones_like, PARAMS)
        sp, sn = plain.init(PARAMS), nest.init(PARAMS)
        # Second step: momentum buffers populated, nesterov lookahead shows.
        up, sp = plain.update(g, sp, PARAMS)
        up2, _ = plain.update(g, sp, PARAMS)
        un, sn = nest.update(g, sn, PARAMS)
        un2, _ = nest.update(g, sn, PARAMS)
        a = float(up2["dense"]["kernel"][0, 0])
        b = float(un2["dense"]["kernel"][0, 0])
        assert a != pytest.approx(b)

    def test_weight_decay_torch_semantics(self):
        """L2 joins the gradient BEFORE momentum (torch SGD)."""
        cfg = OptimizerConfig(name="sgd", lr=1.0, momentum=0.0,
                              weight_decay=0.1)
        tx = make_optimizer(cfg)
        p = {"w": jnp.full((2, 2), 2.0)}
        new = _step(tx, p, grads={"w": jnp.zeros((2, 2))})
        # grad 0 + wd*p = 0.2 → p' = 2.0 - 1.0*0.2
        np.testing.assert_allclose(np.asarray(new["w"]), 1.8, rtol=1e-6)


class TestLamb:
    def test_runs_and_trust_ratio_scales(self):
        cfg = OptimizerConfig(name="lamb", lr=0.01, weight_decay=0.01)
        tx = make_optimizer(cfg)
        new = _step(tx, PARAMS)
        finite = jax.tree.map(lambda x: bool(np.isfinite(x).all()), new)
        assert all(jax.tree.leaves(finite))

    def test_matches_optax_lamb(self):
        cfg = OptimizerConfig(name="lamb", lr=0.01, betas=(0.9, 0.999),
                              eps=1e-6, weight_decay=0.0)
        ours = make_optimizer(cfg)
        ref = optax.lamb(0.01, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0)
        g = jax.tree.map(lambda x: 0.3 * jnp.ones_like(x), PARAMS)
        p1 = _step_with(ours, PARAMS, g, 3)
        p2 = _step_with(ref, PARAMS, g, 3)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5), p1, p2)


def _step_with(tx, params, grads, n):
    state = tx.init(params)
    for _ in range(n):
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


class TestDecayMask:
    def test_no_1d_excludes_biases_and_norms(self):
        mask = decay_mask(OptimizerConfig(weight_decay_mask="no_1d"))(PARAMS)
        assert mask["dense"]["kernel"] is True
        assert mask["dense"]["bias"] is False
        assert mask["bn"]["scale"] is False and mask["bn"]["bias"] is False

    def test_stacked_norm_params_still_excluded(self):
        """Pipeline stacking turns [D] norm params into [L, D]; the name
        check keeps them out of the decay set regardless of rank."""
        stacked = {"blocks": {"ln1": {"scale": jnp.ones((4, 8)),
                                      "bias": jnp.zeros((4, 8))},
                              "mlp": {"kernel": jnp.ones((4, 8, 16))}}}
        mask = decay_mask(OptimizerConfig(weight_decay_mask="no_1d"))(stacked)
        assert mask["blocks"]["ln1"]["scale"] is False
        assert mask["blocks"]["ln1"]["bias"] is False
        assert mask["blocks"]["mlp"]["kernel"] is True

    def test_all_returns_none(self):
        assert decay_mask(OptimizerConfig(weight_decay_mask="all")) is None

    def test_unknown_mask_rejected(self):
        with pytest.raises(ValueError, match="weight_decay_mask"):
            decay_mask(OptimizerConfig(weight_decay_mask="bogus"))

    def test_masked_decay_leaves_1d_untouched(self):
        cfg = OptimizerConfig(name="sgd", lr=1.0, momentum=0.0,
                              weight_decay=0.5, weight_decay_mask="no_1d")
        tx = make_optimizer(cfg)
        zero_g = jax.tree.map(jnp.zeros_like, PARAMS)
        new = _step(tx, PARAMS, grads=zero_g)
        # kernel decayed, 1-d params untouched
        np.testing.assert_allclose(np.asarray(new["dense"]["kernel"]), 0.5)
        np.testing.assert_allclose(np.asarray(new["dense"]["bias"]), 1.0)
        np.testing.assert_allclose(np.asarray(new["bn"]["scale"]), 1.0)


class TestDsConfigIngestion:
    def test_sgd_from_ds_config(self):
        from distributed_training_tpu.config import from_ds_config

        cfg = from_ds_config({
            "optimizer": {"type": "SGD",
                          "params": {"lr": 0.1, "momentum": 0.95,
                                     "nesterov": True,
                                     "weight_decay": 1e-4}},
        })
        o = cfg.optimizer
        assert (o.name, o.lr, o.momentum, o.nesterov, o.weight_decay) == (
            "sgd", 0.1, 0.95, True, 1e-4)

    def test_lamb_from_ds_config(self):
        from distributed_training_tpu.config import from_ds_config

        cfg = from_ds_config({
            "optimizer": {"type": "Lamb",
                          "params": {"lr": 2e-3, "betas": [0.9, 0.99]}},
        })
        assert cfg.optimizer.name == "lamb"
        assert cfg.optimizer.betas == (0.9, 0.99)

    def test_unknown_optimizer_rejected(self):
        from distributed_training_tpu.config import from_ds_config

        with pytest.raises(ValueError, match="unsupported ds optimizer"):
            from_ds_config({"optimizer": {"type": "Adagrad"}})

    def test_activation_checkpointing_maps_to_remat(self):
        from distributed_training_tpu.config import from_ds_config

        # In DeepSpeed the block only configures the checkpointing API —
        # nothing is checkpointed unless the model opts in — so remat needs
        # an explicit opt-in (truthy partition_activations or the dedicated
        # "enabled" extension key); an all-false block leaves remat off.
        assert from_ds_config(
            {"activation_checkpointing": {"partition_activations": True}}
        ).remat is True
        assert from_ds_config(
            {"activation_checkpointing": {"enabled": True}}
        ).remat is True
        # Any truthy functional sub-knob describes a model that checkpoints.
        assert from_ds_config(
            {"activation_checkpointing": {"cpu_checkpointing": True,
                                          "number_checkpoints": 4}}
        ).remat is True
        assert from_ds_config(
            {"activation_checkpointing": {"partition_activations": False,
                                          "cpu_checkpointing": False,
                                          "profile": True}}
        ).remat is False
        assert from_ds_config({"activation_checkpointing": True}).remat is True
        assert from_ds_config({"activation_checkpointing": False}).remat is False
        assert from_ds_config({}).remat is False

    def test_prescale_gradients_documented_noop(self):
        from distributed_training_tpu.config import from_ds_config

        # prescale divides grads by world_size before the all-reduce (a GPU
        # fp16-overflow trick); reduction here is a fused fp32-accumulating
        # mean, so both values yield the averaged gradient — accepted no-op.
        # Structural equality pins the no-op contract.
        assert from_ds_config({"prescale_gradients": True}) == from_ds_config({})
        assert from_ds_config({"prescale_gradients": False}) == from_ds_config({})
        with pytest.raises(ValueError, match="prescale_gradients"):
            from_ds_config({"prescale_gradients": "yes"})

    def test_activation_checkpointing_typo_keys_raise(self):
        from distributed_training_tpu.config import from_ds_config

        with pytest.raises(ValueError, match="activation_checkpointing"):
            from_ds_config(
                {"activation_checkpointing": {"partition_activation": True}})


class TestCliOverrides:
    def test_resnet_cli_overrides_optimizer(self):
        import sys

        from conftest import load_cli_module

        mod = load_cli_module("resnet/jax_tpu/train.py")
        argv = sys.argv
        try:
            sys.argv = ["train.py", "--optimizer", "sgd", "--lr", "0.05",
                        "--momentum", "0.85", "--nesterov",
                        "--weight-decay", "1e-4",
                        "--weight-decay-mask", "no_1d"]
            args = mod.add_argument()
        finally:
            sys.argv = argv
        cfg = mod.build_config(args)
        o = cfg.optimizer
        assert (o.name, o.lr, o.momentum, o.nesterov) == (
            "sgd", 0.05, 0.85, True)
        assert o.weight_decay == 1e-4 and o.weight_decay_mask == "no_1d"
