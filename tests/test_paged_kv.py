"""Page-allocator unit tests (serving/pages.py) — host-side only.

The PagePool is the admission-safety keystone of the paged serving
engine: every guarantee the engine makes about never corrupting a
neighbor's KV mid-flight reduces to this allocator's invariants —
typed exhaustion, no leaks, no aliasing, commitment arithmetic that
cannot strand pages. All tests are pure Python (no jax), so the whole
file runs in milliseconds.
"""

import numpy as np
import pytest

from distributed_training_tpu.inference.sampler import CacheBudgetError
from distributed_training_tpu.serving import NULL_PAGE, PagePool, pages_for


class TestPagesFor:
    def test_ceil_division(self):
        assert pages_for(0, 8) == 0
        assert pages_for(1, 8) == 1
        assert pages_for(8, 8) == 1
        assert pages_for(9, 8) == 2
        with pytest.raises(ValueError, match="tokens"):
            pages_for(-1, 8)


class TestAllocFree:
    def test_alloc_never_hands_out_null_page(self):
        pool = PagePool(num_pages=4, page_size=8)
        pages = pool.alloc(4, committed=False)
        assert NULL_PAGE not in pages
        assert sorted(pages) == [1, 2, 3, 4]

    def test_lifo_reuse(self):
        """A just-freed page is reused first — deterministic reuse keeps
        the device working set dense and test runs reproducible."""
        pool = PagePool(num_pages=4, page_size=8)
        a = pool.alloc(2, committed=False)
        pool.free([a[1]])
        b = pool.alloc(1, committed=False)
        assert b == [a[1]]

    def test_exhaustion_raises_typed_with_page_accounting(self):
        pool = PagePool(num_pages=3, page_size=8)
        pool.alloc(2, committed=False)
        with pytest.raises(CacheBudgetError,
                           match=r"requested 2 page\(s\) but 1"):
            pool.alloc(2, committed=False)
        # The failed alloc must not have consumed anything.
        assert pool.num_free == 1 and pool.num_allocated == 2

    def test_double_free_and_foreign_page_raise(self):
        pool = PagePool(num_pages=2, page_size=8)
        pages = pool.alloc(1, committed=False)
        pool.free(pages)
        with pytest.raises(ValueError, match="not allocated"):
            pool.free(pages)
        with pytest.raises(ValueError, match="not allocated"):
            pool.free([NULL_PAGE])


class TestCommitment:
    def test_commit_gates_admission(self):
        pool = PagePool(num_pages=4, page_size=8)
        pool.commit(3)
        assert pool.available == 1
        assert not pool.can_commit(2)
        with pytest.raises(CacheBudgetError, match="pool exhausted"):
            pool.commit(2)

    def test_alloc_draws_from_commitment(self):
        pool = PagePool(num_pages=4, page_size=8)
        pool.commit(2)
        pool.alloc(2)  # committed=True default
        assert pool.committed == 0 and pool.num_allocated == 2
        with pytest.raises(CacheBudgetError):
            pool.alloc(1)  # nothing committed anymore

    def test_free_with_uncommit_releases_unused_worst_case(self):
        """An early-EOS request frees its pages AND its unallocated
        commitment tail in one call."""
        pool = PagePool(num_pages=4, page_size=8)
        pool.commit(3)
        pages = pool.alloc(1)
        pool.free(pages, uncommit=2)
        pool.check_balanced()

    def test_release_over_committed_raises(self):
        pool = PagePool(num_pages=4, page_size=8)
        pool.commit(1)
        with pytest.raises(ValueError, match="release"):
            pool.release(2)


class TestNoLeaksUnderRandomizedAdmission:
    def test_randomized_admission_evict_cycles_stay_balanced(self):
        """Fragmentation-free invariant: after ANY interleaving of
        commit → on-demand alloc → free(+uncommit) request lifecycles,
        free + allocated == total, nothing committed, nothing aliased —
        pages are interchangeable, so no admission order can fragment
        the pool."""
        rng = np.random.RandomState(0)
        pool = PagePool(num_pages=16, page_size=8)
        live: list[tuple[list[int], int]] = []  # (pages, commit_left)
        for _ in range(500):
            op = rng.randint(3)
            if op == 0:  # admission: commit a worst case
                n = int(rng.randint(1, 5))
                if pool.can_commit(n):
                    pool.commit(n)
                    live.append(([], n))
                else:
                    with pytest.raises(CacheBudgetError):
                        pool.commit(n)
            elif op == 1 and live:  # decode progress: on-demand alloc
                i = rng.randint(len(live))
                pages, left = live[i]
                if left > 0:
                    pages.extend(pool.alloc(1))
                    live[i] = (pages, left - 1)
            elif op == 2 and live:  # eviction: free + uncommit tail
                pages, left = live.pop(rng.randint(len(live)))
                pool.free(pages, uncommit=left)
            # Mid-flight audit: every page is exactly one of
            # free/allocated and the null page never escaped.
            assert pool.num_free + pool.num_allocated == pool.num_pages
            assert NULL_PAGE not in pool._allocated
        for pages, left in live:
            pool.free(pages, uncommit=left)
        pool.check_balanced()
