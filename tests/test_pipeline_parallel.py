"""Pipeline-parallel (GPipe over ``pipe`` mesh axis) correctness.

PP is absent from the reference (SURVEY.md §2.3 "PP: Absent"); this
framework provides it as an SPMD scan + ppermute schedule
(``parallel/pipeline.py``). The invariants: the pipelined forward is the
plain TransformerLM forward; the backward pipeline that autodiff derives
from the forward schedule produces the single-device gradients; training
through the pipeline learns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.pipeline import (
    PipelinedLM,
    stack_block_params,
    unstack_block_params,
)
from distributed_training_tpu.runtime.mesh import (
    AXIS_PIPE,
    MeshConfig,
    create_mesh,
)
from distributed_training_tpu.train.lm_step import (
    make_lm_batch,
    make_pp_lm_train_step,
)
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.train_state import TrainState

VOCAB = 64


@pytest.fixture(scope="module")
def pp_mesh():
    return create_mesh(MeshConfig(data=2, pipe=4))


def _model(num_layers=4):
    return get_model(
        "transformer_lm", num_classes=VOCAB, seq_axis=None,
        num_layers=num_layers, num_heads=2, hidden_dim=32, max_len=128)


def _tokens(b=4, t=17, seed=0):
    return np.random.RandomState(seed).randint(0, VOCAB, (b, t)).astype(np.int32)


def test_stack_unstack_roundtrip():
    model = _model()
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False)
    params = dict(variables["params"])
    stacked, rest = stack_block_params(params, model.num_layers)
    qkv = stacked["attn"]["qkv"]["kernel"]
    assert qkv.shape[0] == model.num_layers
    assert "block0" not in rest and "tok_embed" in rest
    restored = unstack_block_params(stacked, rest)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored, params)


def test_pipelined_forward_matches_plain(pp_mesh):
    """PipelinedLM.apply_fn == TransformerLM.apply on identical params."""
    model = _model()
    rng = jax.random.PRNGKey(0)
    variables = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                           train=False)
    plm = PipelinedLM(model, pp_mesh, num_microbatches=2)
    pp_params = plm.init_params(rng)

    tokens = jnp.asarray(_tokens())
    ref = model.apply(variables, tokens, train=False)
    got = jax.jit(lambda p, t: plm.apply_fn({"params": p}, t))(pp_params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def _pp_state(plm, rng, opt="sgd"):
    tx = (optax.sgd(0.1) if opt == "sgd" else
          optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3)))
    return TrainState.create(
        apply_fn=plm.apply_fn, params=plm.init_params(rng), tx=tx,
        loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))


def test_pp_step_matches_single_device(pp_mesh):
    """One (data=2 × pipe=4) GPipe step == one single-device step — the
    autodiff-derived backward pipeline produces the true gradients."""
    model = _model()
    rng0 = jax.random.PRNGKey(0)
    batch = make_lm_batch(_tokens())
    step_rng = jax.random.PRNGKey(7)

    # Oracle on the unstacked model.
    variables = model.init({"params": rng0}, jnp.zeros((1, 8), jnp.int32),
                           train=False)

    def oracle_step(params, batch):
        def loss_fn(p):
            logits = model.apply({"params": p},
                                 jnp.asarray(batch["tokens"]), train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.asarray(batch["targets"])).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads), loss

    oracle_params, oracle_loss = jax.jit(oracle_step)(
        dict(variables["params"]), batch)
    oracle_stacked, oracle_rest = stack_block_params(
        oracle_params, model.num_layers)

    # Pipelined step from the same init.
    step = make_pp_lm_train_step(pp_mesh, model=model, num_microbatches=2,
                                 donate=False)
    state = _pp_state(step.pipelined, rng0, opt="sgd")
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()}, step.batch_shardings)
    new_state, metrics = step(state, gbatch, step_rng)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(oracle_loss), atol=1e-5, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        new_state.params["blocks"], oracle_stacked)
    for key in ("tok_embed", "pos_embed", "ln_f", "lm_head"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
            new_state.params[key], oracle_rest[key])


def test_pp_blocks_actually_sharded(pp_mesh):
    """Stacked blocks land with their layer dim split across pipe ranks."""
    model = _model()
    step = make_pp_lm_train_step(pp_mesh, model=model, num_microbatches=2,
                                 donate=False)
    state = _pp_state(step.pipelined, jax.random.PRNGKey(0))
    batch = make_lm_batch(_tokens())
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()}, step.batch_shardings)
    new_state, _ = step(state, gbatch, jax.random.PRNGKey(0))
    qkv = new_state.params["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == P(AXIS_PIPE)
    assert qkv.addressable_shards[0].data.shape[0] == 1  # 4 layers / 4 stages


def test_pp_loss_decreases(pp_mesh):
    """Smoke: 30 GPipe steps on a learnable pattern drop the loss."""
    start = np.random.RandomState(0).randint(0, VOCAB, (8, 1))
    tokens = (start + np.arange(33)) % VOCAB
    batch = make_lm_batch(tokens.astype(np.int32))

    model = _model()
    step = make_pp_lm_train_step(pp_mesh, model=model, num_microbatches=4,
                                 donate=False)
    state = _pp_state(step.pipelined, jax.random.PRNGKey(0), opt="adam")
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()}, step.batch_shardings)
    rng = jax.random.PRNGKey(0)
    first = None
    for _ in range(30):
        rng, sub = jax.random.split(rng)
        state, metrics = step(state, gbatch, sub)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first * 0.5, (first, last)


def test_pp_ce_chunk_matches_full_logits(pp_mesh):
    """ce_chunk through the pipeline executor (VERDICT r2 #7): chunked CE
    over return_hidden must trace the same trajectory as the full-logits
    step."""
    model = _model()
    batch = make_lm_batch(_tokens(t=33))

    def run(ce_chunk):
        step = make_pp_lm_train_step(pp_mesh, model=model,
                                     num_microbatches=2, donate=False,
                                     ce_chunk=ce_chunk)
        state = _pp_state(step.pipelined, jax.random.PRNGKey(0), opt="sgd")
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            step.batch_shardings)
        for i in range(2):
            state, metrics = step(state, gbatch, jax.random.PRNGKey(i))
        return state, metrics

    s_full, m_full = run(None)
    s_chunk, m_chunk = run(8)
    np.testing.assert_allclose(float(m_chunk["loss"]), float(m_full["loss"]),
                               atol=1e-5, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        s_chunk.params, s_full.params)


def test_pp_dropout_trains_and_draws_distinct_masks(pp_mesh):
    """Dropout rngs thread through the stage scan (VERDICT r2 #7): a
    dropout model trains through the pipeline, train-mode losses vary with
    the rng (masks actually apply), and eval mode is deterministic."""
    model = get_model(
        "transformer_lm", num_classes=VOCAB, seq_axis=None, num_layers=4,
        num_heads=2, hidden_dim=32, max_len=128, dropout_rate=0.5)
    step = make_pp_lm_train_step(pp_mesh, model=model, num_microbatches=2,
                                 donate=False)
    state = _pp_state(step.pipelined, jax.random.PRNGKey(0), opt="sgd")
    batch = make_lm_batch(_tokens())
    gbatch = jax.device_put(
        {k: jnp.asarray(v) for k, v in batch.items()}, step.batch_shardings)

    _, m1 = step(state, gbatch, jax.random.PRNGKey(1))
    _, m2 = step(state, gbatch, jax.random.PRNGKey(2))
    assert float(m1["loss"]) != float(m2["loss"])  # masks drawn from rng

    # Same rng → same loss (deterministic given the key).
    _, m1b = step(state, gbatch, jax.random.PRNGKey(1))
    assert float(m1["loss"]) == float(m1b["loss"])

    # Eval path (train=False) ignores dropout entirely.
    tokens = jnp.asarray(_tokens())
    e1 = step.pipelined.apply_fn({"params": state.params}, tokens)
    e2 = step.pipelined.apply_fn({"params": state.params}, tokens)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def test_pp_remat_matches_plain(pp_mesh):
    """model.remat checkpoints each layer inside the stage scan without
    changing the math (VERDICT r2 #7)."""
    batch = make_lm_batch(_tokens())

    def run(remat):
        model = get_model(
            "transformer_lm", num_classes=VOCAB, seq_axis=None,
            num_layers=4, num_heads=2, hidden_dim=32, max_len=128,
            remat=remat)
        step = make_pp_lm_train_step(pp_mesh, model=model,
                                     num_microbatches=2, donate=False)
        state = _pp_state(step.pipelined, jax.random.PRNGKey(0), opt="sgd")
        gbatch = jax.device_put(
            {k: jnp.asarray(v) for k, v in batch.items()},
            step.batch_shardings)
        state, metrics = step(state, gbatch, jax.random.PRNGKey(0))
        return state, metrics

    s_plain, m_plain = run(False)
    s_remat, m_remat = run(True)
    np.testing.assert_allclose(float(m_remat["loss"]), float(m_plain["loss"]),
                               atol=1e-6, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5),
        s_remat.params, s_plain.params)


def test_pp_rejects_bad_config(pp_mesh):
    model = get_model("transformer_lm", num_classes=VOCAB, seq_axis=None,
                      num_layers=3, num_heads=2, hidden_dim=32, max_len=128)
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedLM(model, pp_mesh, num_microbatches=2)
    seq_model = get_model("transformer_lm", num_classes=VOCAB,
                          seq_axis="sequence", num_layers=4, num_heads=2,
                          hidden_dim=32, max_len=128)
    with pytest.raises(ValueError, match="seq_axis"):
        PipelinedLM(seq_model, pp_mesh, num_microbatches=2)


class TestCircularSchedule:
    """Interleaved/circular pipeline (virtual_stages > 1, round 4): same
    math as GPipe and the plain model, smaller bubble."""

    def test_layer_order_roundtrip(self):
        from distributed_training_tpu.parallel.pipeline import (
            circular_layer_order,
        )

        order = circular_layer_order(8, 4, 2)
        # device d's contiguous slice (2 rows) = chunks {d, d+4} of 1 layer
        assert order == [0, 4, 1, 5, 2, 6, 3, 7]
        model = _model(num_layers=8)
        variables = model.init(
            {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
            train=False)
        params = dict(variables["params"])
        stacked, rest = stack_block_params(params, 8, layer_order=order)
        restored = unstack_block_params(stacked, rest, layer_order=order)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), restored, params)

    def test_circular_forward_matches_plain(self, pp_mesh):
        model = _model(num_layers=8)
        rng = jax.random.PRNGKey(0)
        variables = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                               train=False)
        plm = PipelinedLM(model, pp_mesh, num_microbatches=4,
                          virtual_stages=2)
        pp_params = plm.init_params(rng)
        tokens = jnp.asarray(_tokens(b=8))
        ref = model.apply(variables, tokens, train=False)
        got = jax.jit(lambda p, t: plm.apply_fn({"params": p}, t))(
            pp_params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-4)

    def test_circular_grads_match_gpipe(self, pp_mesh):
        """The schedule is an execution order, not math: circular and GPipe
        steps from the same init must produce the same updated params."""
        model = _model(num_layers=8)
        rng0 = jax.random.PRNGKey(0)
        batch = make_lm_batch(_tokens(b=8))
        results = {}
        for v, m in ((1, 4), (2, 4)):
            step = make_pp_lm_train_step(
                pp_mesh, model=model, num_microbatches=m, virtual_stages=v)
            plm = step.pipelined
            state = _pp_state(plm, rng0)
            state = jax.device_put(state, step.state_shardings(state))
            new_state, metrics = step(
                state, jax.device_put(batch, step.batch_shardings),
                jax.random.PRNGKey(7))
            # Compare in the canonical (unstacked) layout: the two
            # schedules store layers in different stacking orders.
            results[v] = (
                unstack_block_params(
                    new_state.params["blocks"],
                    {k: w for k, w in new_state.params.items()
                     if k != "blocks"},
                    layer_order=plm.layer_order),
                float(metrics["loss"]))
        np.testing.assert_allclose(results[1][1], results[2][1],
                                   atol=1e-5, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-4),
            results[1][0], results[2][0])

    def test_bubble_fraction_drops(self, pp_mesh):
        model = _model(num_layers=8)
        gpipe = PipelinedLM(model, pp_mesh, num_microbatches=8)
        circ = PipelinedLM(model, pp_mesh, num_microbatches=8,
                           virtual_stages=2)
        assert gpipe.bubble_fraction == pytest.approx(3 / 11)
        assert circ.bubble_fraction == pytest.approx(3 / 19)
        assert circ.bubble_fraction < gpipe.bubble_fraction

    def test_microbatch_group_constraint(self, pp_mesh):
        model = _model(num_layers=8)
        with pytest.raises(ValueError, match="groups of the pipe size"):
            PipelinedLM(model, pp_mesh, num_microbatches=3, virtual_stages=2)


class TestPPZero:
    """PP x ZeRO-1 (round 4): optimizer state shards over data on dims the
    pipe spec leaves free; stage 3 refused (DeepSpeed parity)."""

    def test_opt_state_sharded_over_data(self, pp_mesh):
        model = _model()
        step = make_pp_lm_train_step(
            pp_mesh, model=model, num_microbatches=2, zero_stage=1)
        state = _pp_state(step.pipelined, jax.random.PRNGKey(0), opt="adam")
        sh = step.state_shardings(state)
        flat = jax.tree_util.tree_flatten_with_path(sh.opt_state)[0]
        block_mu = [s for p, s in flat
                    if "blocks" in str(p) and "mu" in str(p)
                    and "qkv" in str(p) and "kernel" in str(p)]
        assert block_mu, "no block moment shardings found"
        for s in block_mu:
            axes = [a for e in s.spec if e
                    for a in ((e,) if isinstance(e, str) else e)]
            assert "pipe" in axes and "data" in axes, s.spec
        # Non-block (embedding) moments shard over data too.
        embed_mu = [s for p, s in flat
                    if "tok_embed" in str(p) and "mu" in str(p)]
        assert embed_mu
        for s in embed_mu:
            axes = [a for e in s.spec if e
                    for a in ((e,) if isinstance(e, str) else e)]
            assert "data" in axes, s.spec

    def test_pp_zero1_step_matches_pp_zero0(self, pp_mesh):
        model = _model()
        rng0 = jax.random.PRNGKey(0)
        batch = make_lm_batch(_tokens())
        results = {}
        for stage in (0, 1):
            step = make_pp_lm_train_step(
                pp_mesh, model=model, num_microbatches=2, zero_stage=stage)
            state = _pp_state(step.pipelined, rng0, opt="adam")
            state = jax.device_put(state, step.state_shardings(state))
            new_state, metrics = step(
                state, jax.device_put(batch, step.batch_shardings),
                jax.random.PRNGKey(7))
            results[stage] = (new_state.params, float(metrics["loss"]))
        np.testing.assert_allclose(results[0][1], results[1][1],
                                   rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5),
            results[0][0], results[1][0])

    def test_stage3_refused(self, pp_mesh):
        model = _model()
        with pytest.raises(NotImplementedError, match="stage 3"):
            make_pp_lm_train_step(
                pp_mesh, model=model, num_microbatches=2, zero_stage=3)


def test_circular_checkpoint_layout_guard(tmp_path):
    """A checkpoint saved under one stacking layout must refuse to restore
    into a different one (shape-identical but permuted weights)."""
    from distributed_training_tpu.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    model = _model(num_layers=8)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    plm = PipelinedLM(model, mesh, num_microbatches=4, virtual_stages=2)
    state = _pp_state(plm, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path), 0, state,
                    layout={"pipe_size": 4, "virtual_stages": 2})
    # Same layout restores fine.
    restored, nxt, st = restore_checkpoint(
        str(tmp_path), 0, state,
        layout={"pipe_size": 4, "virtual_stages": 2})
    assert nxt == 1 and st == 0
    # Different virtual_stages (or a GPipe run) refuses.
    with pytest.raises(ValueError, match="PERMUTED"):
        restore_checkpoint(str(tmp_path), 0, state,
                           layout={"virtual_stages": 1})
    # Legacy save without layout meta counts as identity: restoring into a
    # circular run refuses too.
    save_checkpoint(str(tmp_path), 1, state)
    with pytest.raises(ValueError, match="PERMUTED"):
        restore_checkpoint(str(tmp_path), 1, state,
                           layout={"pipe_size": 4, "virtual_stages": 2})
