"""Dynamic loss-scaler state machine tests (DeepSpeed fp16 semantics,
``resnet/deepspeed/deepspeed_train.py:203-207``)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.train.precision import (
    LossScaleState,
    all_finite,
    select_tree,
)


def _cfg(**kw):
    base = dict(dtype="fp16", initial_scale_power=15, loss_scale_window=500,
                hysteresis=2, min_loss_scale=1.0)
    base.update(kw)
    return PrecisionConfig(**base)


def test_initial_scale_is_2_pow_15():
    s = LossScaleState.create(_cfg())
    assert float(s.scale) == 2.0 ** 15
    assert s.dynamic


def test_window_of_good_steps_doubles_scale():
    s = LossScaleState.create(_cfg(loss_scale_window=3))
    for _ in range(2):
        s = s.update(jnp.bool_(True))
        assert float(s.scale) == 2.0 ** 15
    s = s.update(jnp.bool_(True))  # 3rd good step hits the window
    assert float(s.scale) == 2.0 ** 16
    assert int(s.good_steps) == 0


def test_hysteresis_defers_halving():
    # hysteresis=2: first overflow consumes a credit, second halves.
    s = LossScaleState.create(_cfg())
    s = s.update(jnp.bool_(False))
    assert float(s.scale) == 2.0 ** 15
    assert int(s.hysteresis_left) == 1
    s = s.update(jnp.bool_(False))
    assert float(s.scale) == 2.0 ** 14
    assert int(s.hysteresis_left) == 2  # refilled after halving


def test_overflow_resets_good_step_count():
    s = LossScaleState.create(_cfg(loss_scale_window=4))
    for _ in range(3):
        s = s.update(jnp.bool_(True))
    assert int(s.good_steps) == 3
    s = s.update(jnp.bool_(False))
    assert int(s.good_steps) == 0


def test_min_loss_scale_floor():
    s = LossScaleState.create(_cfg(initial_scale_power=1, hysteresis=1,
                                   min_loss_scale=1.0))
    for _ in range(10):
        s = s.update(jnp.bool_(False))
    assert float(s.scale) == 1.0


def test_good_step_refills_hysteresis_only_at_window():
    s = LossScaleState.create(_cfg(loss_scale_window=2))
    s = s.update(jnp.bool_(False))           # consume one credit
    assert int(s.hysteresis_left) == 1
    s = s.update(jnp.bool_(True))            # good step: credit unchanged
    assert int(s.hysteresis_left) == 1
    s = s.update(jnp.bool_(True))            # window hit: doubled + refilled
    assert int(s.hysteresis_left) == 2


def test_static_scale_never_moves():
    s = LossScaleState.create(_cfg(static_loss_scale=1024.0))
    assert not s.dynamic
    s2 = s.update(jnp.bool_(False))
    assert float(s2.scale) == 1024.0


def test_bf16_and_fp32_scaler_inert():
    for dtype in ("bf16", "fp32"):
        s = LossScaleState.create(PrecisionConfig(dtype=dtype))
        assert float(s.scale) == 1.0
        assert not s.dynamic


def test_scaler_update_is_jittable_without_recompile():
    s = LossScaleState.create(_cfg())
    traces = []

    @jax.jit
    def step(s, finite):
        traces.append(1)
        return s.update(finite)

    s = step(s, jnp.bool_(True))
    s = step(s, jnp.bool_(False))
    s = step(s, jnp.bool_(True))
    assert len(traces) == 1, "scaler transition must not retrigger tracing"


def test_all_finite_detects_overflow():
    good = {"a": jnp.ones(3), "b": jnp.zeros(2)}
    bad = {"a": jnp.ones(3), "b": jnp.array([1.0, jnp.inf])}
    nan = {"a": jnp.array([jnp.nan]), "b": jnp.zeros(2)}
    assert bool(all_finite(good))
    assert not bool(all_finite(bad))
    assert not bool(all_finite(nan))


def test_select_tree_skips_update_on_overflow():
    old = {"w": jnp.zeros(3)}
    new = {"w": jnp.ones(3)}
    out = select_tree(jnp.bool_(False), new, old)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.zeros(3))
    out = select_tree(jnp.bool_(True), new, old)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(3))


def test_scale_loss_unscale_grads_roundtrip():
    s = LossScaleState.create(_cfg())
    loss = jnp.float32(2.5)
    assert float(s.scale_loss(loss)) == 2.5 * 2 ** 15
    grads = {"w": jnp.full(4, 2.0 ** 15)}
    un = s.unscale_grads(grads)
    np.testing.assert_allclose(np.asarray(un["w"]), np.ones(4))


def test_commit_guards_optimizer_internal_overflow():
    """Finite gradients whose OPTIMIZER update overflows must skip: the
    grad finiteness check alone cannot see an overflow that happens inside
    the transform (observed in the round-2 fp16 convergence run: a NaN
    committed into conv_init/kernel with the loss scale at its floor).
    The guard checks the candidate params, catching any update-path
    overflow regardless of mechanism."""
    import optax

    from distributed_training_tpu.train.precision import commit_gradients
    from distributed_training_tpu.train.train_state import TrainState

    def overflowing_update(updates, state, params=None):
        # Stand-in for any optimizer-internal overflow (g², trust ratios,
        # schedule math...): finite input, non-finite update.
        return jax.tree.map(lambda g: g * jnp.inf, updates), state

    tx = optax.GradientTransformation(optax.adam(1e-3).init,
                                      overflowing_update)
    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.ones(4)},
        tx=tx,
        loss_scale=LossScaleState.create(_cfg()),  # dynamic fp16 scaler
    )
    finite_grads = {"w": jnp.full(4, 0.5, jnp.float32)}
    new_state, finite = commit_gradients(state, finite_grads)
    assert not bool(finite)  # grads were finite; the UPDATE was not
    np.testing.assert_array_equal(np.asarray(new_state.params["w"]),
                                  np.ones(4))  # params untouched
    assert int(new_state.step) == 0

    # The same grads through a sane optimizer still commit.
    ok_state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.ones(4)},
        tx=optax.adam(1e-3),
        loss_scale=LossScaleState.create(_cfg()),
    )
    new_state, finite = commit_gradients(ok_state, finite_grads)
    assert bool(finite) and int(new_state.step) == 1
