"""SLO-tiered scheduling + lossless preempt-and-requeue tests.

Load-bearing properties, in order of importance:

1. **Lossless preemption** (the repo's signature invariant, extended):
   a sequence evicted mid-flight to seat a higher tier — pages freed,
   commitment released, requeued carrying its emitted tokens — produces
   a final token stream BITWISE identical to an uninterrupted run.
   The re-seat re-prefills prompt+emitted (same positions, same
   ``fold_in(rng, position)`` stream) and continues decoding exactly
   where it left off. Pinned greedy AND sampled, paged AND legacy,
   speculation on AND off; ``check_balanced()`` stays leak-free after
   every preempt/requeue cycle.
2. **Selective degradation mechanics**: strict tier order with no
   lower-tier skip-ahead past a blocked higher tier, weighted-fair
   tenant selection within a tier, per-tenant quotas that fall through
   (never idle slots), tier-aware shedding (best-effort drops first,
   the high tier never sheds while lower work is queued), and reserved
   slot headroom for tier 0.
3. **Drain + deadline correctness under preemption**: ``drain()``
   completes requeued sequences rather than dropping them, and a
   preempted sequence whose deadline expires reports
   ``preempted_timeout`` (not ``timeout``) so telemetry attributes the
   miss to preemption pressure.
4. **Traffic scenarios** (tools/traffic.py): every generator is a pure
   function of (seed, params) — deterministic, arrival-sorted, and
   admissible by construction.

Engines compile real XLA programs, so the model is tiny and parameter
combinations are trimmed to cover every axis value in both greedy and
sampled modes rather than the full product.
"""

import json
import time

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.serving import (
    FINISH_LENGTH,
    FINISH_PREEMPT_TIMEOUT,
    FINISH_SHED,
    FINISH_TIMEOUT,
    ActiveSequence,
    Engine,
    QueueFullError,
    Request,
    RequestQueue,
    SlotScheduler,
)

VOCAB = 31
MAX_LEN = 48


@pytest.fixture(scope="module")
def lm():
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=1, num_heads=2,
        hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(3)
    return [rng.randint(0, VOCAB, size=l).astype(np.int32)
            for l in (5, 7, 3, 6)]


def _solo_outputs(model, params, reqs, **cfg_kw):
    """Uninterrupted oracle: serve ``reqs`` one at a time on a single
    slot (uids follow submission order, matching the preemption run's
    — the RNG stream is fold_in(seed, uid), so uid parity is what
    bitwise comparison requires)."""
    eng = Engine(model, params, ServeConfig(max_batch=1, **cfg_kw))
    out = {}
    for prompt, max_new in reqs:
        req = eng.submit(prompt, max_new_tokens=max_new)
        for fin in eng.run():
            out[fin.uid] = fin.tokens.tolist()
        assert req.uid in out
    return out


# Every axis value (paged/legacy, spec 0/2) appears under both greedy
# and sampled temperatures without paying for the full 8-way product.
PREEMPT_CASES = [
    ({"prefill_chunk": 4}, 0.0),
    ({"prefill_chunk": 4}, 0.8),
    ({"kv_page_size": None, "prefill_bucket": 8}, 0.0),
    ({"kv_page_size": None, "prefill_bucket": 8}, 0.8),
    ({"prefill_chunk": 4, "spec_k": 2}, 0.0),
    # legacy + speculation needs budget + spec_k slack in the table
    ({"kv_page_size": None, "prefill_bucket": 8, "spec_k": 2,
      "max_len": 40}, 0.8),
]


class TestLosslessPreemption:
    @pytest.mark.parametrize("cfg_kw,temp", PREEMPT_CASES)
    def test_preempted_resumed_bitwise(self, lm, prompts, cfg_kw, temp):
        """THE invariant: preempt a mid-decode best-effort sequence for
        a tier-0 arrival; both outputs must equal the uninterrupted
        single-slot oracle bitwise, and the pool must drain balanced."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=8, num_tiers=2,
            temperature=temp, **cfg_kw))
        low = eng.submit(prompts[0], priority=1, max_new_tokens=8)
        for _ in range(3):  # emit a few tokens before the interloper
            eng.step()
        assert len(eng.scheduler.sequence(0).tokens) >= 1
        high = eng.submit(prompts[1], priority=0, max_new_tokens=4)
        done = {f.uid: f for f in eng.run()}
        if eng.paged:
            eng.pool.check_balanced()
        stats = eng.stats()
        assert stats["requests_preempted"] >= 1
        assert stats["preempted_token_recompute"] >= prompts[0].size
        assert done[low.uid].finish_reason == FINISH_LENGTH
        # The high tier finished FIRST despite arriving second — that
        # is what the preemption bought.
        assert (done[high.uid].last_token_t
                < done[low.uid].last_token_t)
        solo = _solo_outputs(model, params,
                             [(prompts[0], 8), (prompts[1], 4)],
                             temperature=temp, **cfg_kw)
        assert done[low.uid].tokens.tolist() == solo[low.uid]
        assert done[high.uid].tokens.tolist() == solo[high.uid]

    def test_preempt_mid_prefill_restarts_clean(self, lm, prompts):
        """A sequence evicted while still CHUNK-PREFILLING (no token
        emitted yet) restarts from its prompt: same TTFT clock, same
        output, pool balanced."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=6, num_tiers=2,
            prefill_chunk=2))
        low = eng.submit(prompts[1], priority=1)  # 7 tokens = 4 chunks
        eng.step()  # first chunk only — still prefilling
        seq = eng.scheduler.sequence(0)
        assert seq.prefilling and not seq.tokens
        high = eng.submit(prompts[2], priority=0, max_new_tokens=4)
        done = {f.uid: f for f in eng.run()}
        eng.pool.check_balanced()
        assert eng.stats()["requests_preempted"] == 1
        solo = _solo_outputs(model, params,
                             [(prompts[1], 6), (prompts[2], 4)],
                             prefill_chunk=2)
        assert done[low.uid].tokens.tolist() == solo[low.uid]
        assert done[high.uid].tokens.tolist() == solo[high.uid]

    def test_repeated_preemption_cycles_leak_free(self, lm, prompts):
        """Several preempt/requeue cycles across a 2-slot engine with an
        oversubscribed pool: every request still completes bitwise-equal
        to the oracle and the pool drains balanced."""
        model, params = lm
        cfg_kw = dict(max_new_tokens=6, prefill_chunk=4, kv_pages=14)
        eng = Engine(model, params, ServeConfig(
            max_batch=2, num_tiers=2, **cfg_kw))
        subs = []  # (uid, prompt, max_new)
        for p in (prompts[0], prompts[1]):
            subs.append((eng.submit(p, priority=1).uid, p, 6))
        for _ in range(3):
            eng.step()
        # Two high-tier arrivals: with 2 slots both low-tier sequences
        # are evicted (pages AND slots contended).
        for p in (prompts[2], prompts[3]):
            subs.append((
                eng.submit(p, priority=0, max_new_tokens=4).uid, p, 4))
        assert eng.phase in ("serving", "overloaded")
        done = {f.uid: f for f in eng.run()}
        eng.pool.check_balanced()
        stats = eng.stats()
        assert stats["requests_preempted"] >= 2
        assert stats["tier1_requests_preempted"] >= 2
        assert stats["tier0_requests_preempted"] == 0
        solo = _solo_outputs(
            model, params, [(p, m) for _, p, m in subs], **cfg_kw)
        for uid, _, _ in subs:
            assert done[uid].tokens.tolist() == solo[uid], uid


class TestDrainAndDeadlines:
    def test_drain_completes_requeued(self, lm, prompts):
        """drain() owes a preempted-and-requeued sequence its
        completion: admission closes, but the resumption re-seats and
        finishes with its full budget — nothing is dropped."""
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=8, num_tiers=2,
            prefill_chunk=4))
        low = eng.submit(prompts[0], priority=1)
        for _ in range(4):
            eng.step()
        eng.submit(prompts[1], priority=0, max_new_tokens=4)
        # Force the preemption pass (the high arrival preempts low).
        eng.step()
        assert eng.stats()["requests_preempted"] == 1
        done = {f.uid: f for f in eng.drain()}
        eng.pool.check_balanced()
        assert done[low.uid].finish_reason == FINISH_LENGTH
        assert done[low.uid].tokens.size == 8
        assert eng.stats()["drained"] is True

    def test_preempted_then_expired_reports_preempted_timeout(
            self, lm, prompts):
        """Satellite bugfix pin: the deadline clock keeps running while
        a preempted sequence waits requeued; its eviction must report
        ``preempted_timeout`` (carrying the partial tokens), never plain
        ``timeout`` — and the two counters stay distinct. The deadline
        is rewound on the REQUEUED entry directly (a generous config
        deadline would otherwise race the first-step compile time)."""
        import dataclasses

        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=1, max_new_tokens=8, num_tiers=2,
            prefill_chunk=4, deadline_ms=600000.0))
        low = eng.submit(prompts[0], priority=1)
        for _ in range(3):
            eng.step()
        emitted_before = len(eng.scheduler.sequence(0).tokens)
        assert emitted_before >= 1
        eng.submit(prompts[1], priority=0, max_new_tokens=8)
        eng.step()  # preempts low
        assert eng.stats()["requests_preempted"] == 1
        entry = eng.queue.peek()
        assert isinstance(entry, ActiveSequence)
        assert entry.request.uid == low.uid
        # Rewind the requeued sequence's total deadline into the past —
        # exactly what waiting out a 600 s queue delay would do.
        entry.request = dataclasses.replace(
            entry.request, deadline_t=time.perf_counter() - 1.0)
        done = {f.uid: f for f in eng.drain()}
        eng.pool.check_balanced()
        fin = done[low.uid]
        assert fin.finish_reason == FINISH_PREEMPT_TIMEOUT
        assert fin.slot is None  # evicted queue-side, no slot track
        assert fin.tokens.size == emitted_before  # partial tokens kept
        stats = eng.stats()
        assert stats["requests_preempt_timed_out"] == 1
        assert stats["requests_timed_out"] == 0

    def test_finish_reason_attribution_unit(self):
        """ActiveSequence.finish_reason: the same expired deadline is
        ``timeout`` for a never-preempted sequence and
        ``preempted_timeout`` after a preemption."""
        req = Request(uid=0, prompt=np.ones(3, np.int32),
                      max_new_tokens=8, arrival_t=0.0, deadline_t=1.0)
        seq = ActiveSequence(request=req, slot=0)
        seq.note_token(5, 0.5)
        assert seq.finish_reason(None, now=2.0) == FINISH_TIMEOUT
        seq.prepare_resume()
        assert seq.preempts == 1
        assert seq.finish_reason(None, now=2.0) == FINISH_PREEMPT_TIMEOUT
        # EOS/length still beat the deadline either way.
        seq.tokens = [1] * 8
        assert seq.finish_reason(None, now=2.0) == FINISH_LENGTH

    def test_resume_prefix_snapshot_unit(self):
        """prepare_resume snapshots prompt+emitted-minus-last; the
        prefix must NOT drift as more tokens land after the re-seat."""
        req = Request(uid=0, prompt=np.asarray([1, 2, 3], np.int32),
                      max_new_tokens=8, arrival_t=0.0)
        seq = ActiveSequence(request=req, slot=0)
        for i, tok in enumerate((7, 8, 9)):
            seq.note_token(tok, float(i))
        seq.prefill_pos = 3
        seq.prepare_resume()
        assert seq.prefill_tokens.tolist() == [1, 2, 3, 7, 8]
        assert seq.prefilling
        seq.prefill_pos = seq.prefill_tokens.size
        assert not seq.prefilling
        seq.note_token(10, 3.0)  # decodes further after the re-seat
        assert seq.prefill_tokens.tolist() == [1, 2, 3, 7, 8]
        assert not seq.prefilling


class TestTiersAndFairness:
    def _queue(self, **kw):
        return RequestQueue(budget=32, default_max_new_tokens=4, **kw)

    def test_tier_order_strict_fifo_within_tier(self):
        q = self._queue(num_tiers=3)
        a = q.submit([1], priority=2)
        b = q.submit([1], priority=0)
        c = q.submit([1], priority=1)
        d = q.submit([1], priority=0)
        order = [q.pop() for _ in range(4)]
        assert [r.uid for r in order] == [b.uid, d.uid, c.uid, a.uid]

    def test_priority_out_of_range_rejected(self):
        q = self._queue(num_tiers=2)
        with pytest.raises(ValueError, match="priority"):
            q.submit([1], priority=2)
        with pytest.raises(ValueError, match="priority"):
            q.submit([1], priority=-1)

    def test_weighted_fair_tenant_selection(self):
        """Weight 2:1 — over repeated seats tenant a receives ~2x the
        service of tenant b (service is charged in token units, so the
        pick sequence follows the weighted deficit exactly)."""
        q = self._queue(num_tiers=1,
                        tenant_weights={"a": 2.0, "b": 1.0})
        for _ in range(6):
            q.submit([1], tenant="a")
            q.submit([1], tenant="b")
        picks = []
        for _ in range(9):
            cand = q.next_candidate({})
            picks.append(cand.tenant)
            q.take(cand)
        # First pick ties at service 0 -> lexicographic "a"; from there
        # the 2:1 weights alternate a,a,b.
        assert picks.count("a") == 6 and picks.count("b") == 3

    def test_tenant_quota_falls_through_tiers(self):
        """A tier whose queued tenants are all at quota must not idle
        the slot — the next tier seats instead."""
        q = self._queue(num_tiers=2, tenant_quota=2)
        q.submit([1], priority=0, tenant="a")
        low = q.submit([1], priority=1, tenant="b")
        # tenant a already holds 2 slots -> tier 0 is quota-blocked.
        cand = q.next_candidate({"a": 2})
        assert cand.uid == low.uid
        # Quota freed -> tier 0 wins again.
        cand = q.next_candidate({"a": 1})
        assert cand.uid == 0

    def test_tier_aware_shed_prefers_best_effort(self):
        """On a full queue a high-tier submit sheds the NEWEST queued
        best-effort entry (surfaced via take_shed); an incoming
        best-effort submit on a queue full of high-tier work sheds
        ITSELF with the typed QueueFullError."""
        q = self._queue(num_tiers=2, max_depth=2)
        q.submit([1], priority=1)
        victim = q.submit([1], priority=1)
        keeper = q.submit([1], priority=0)  # sheds the newest tier-1
        shed = q.take_shed()
        assert [e.uid for e in shed] == [victim.uid]
        assert q.shed_by_tier == [0, 1]
        assert len(q) == 2  # the older tier-1 entry + the keeper
        with pytest.raises(QueueFullError):
            q.submit([1], priority=1)  # nothing below tier 1 to shed
        assert q.shed_by_tier == [0, 2]
        assert keeper.priority == 0

    def test_requeue_reseats_in_arrival_order(self):
        """A preempted resumption re-enters its tier ahead of younger
        same-tier work (uid order), so preemption never reorders a
        tenant's stream."""
        q = self._queue(num_tiers=2)
        old = q.submit([1], priority=1)
        young = q.submit([1], priority=1)
        cand = q.next_candidate({})
        assert cand.uid == old.uid
        q.take(cand)
        seq = ActiveSequence(request=old, slot=0)
        seq.note_token(4, 0.0)
        seq.prepare_resume()
        q.requeue(seq)
        heads = [q.pop() for _ in range(2)]
        assert isinstance(heads[0], ActiveSequence)
        assert heads[0].request.uid == old.uid
        assert heads[1].uid == young.uid

    def test_reserved_slots_hold_headroom_for_tier0(self):
        """SlotScheduler with reserved_slots=1 on 2 slots: best-effort
        fills only the unreserved slot; a tier-0 arrival takes the
        reserve without needing a preemption."""
        q = self._queue(num_tiers=2)
        q.submit([1], priority=1)
        q.submit([1], priority=1)
        sched = SlotScheduler(2, reserved_slots=1)
        seated = sched.admit(q)
        assert len(seated) == 1 and sched.num_active == 1
        assert len(q) == 1  # second best-effort blocked on the reserve
        q.submit([1], priority=0)
        seated = sched.admit(q)
        # Tier 0 ignores the reserve; the queued tier-1 stays blocked.
        assert [s.request.priority for s in seated] == [0]
        assert sched.num_active == 2 and len(q) == 1

    def test_take_tolerates_concurrent_shed(self):
        """A producer-side tier-aware shed can remove the scheduler's
        chosen candidate between next_candidate() and take() (separate
        lock sections): take() must report False — nothing removed,
        nothing charged — and the admission pass re-polls instead of
        crashing."""
        q = self._queue(num_tiers=2, max_depth=1)
        cand = q.submit([1], priority=1)
        picked = q.next_candidate({})
        assert picked.uid == cand.uid
        q.submit([1], priority=0)  # full queue: sheds the tier-1 entry
        assert [e.uid for e in q.take_shed()] == [cand.uid]
        assert q.take(picked) is False
        # The pass re-polls and seats the tier-0 entry normally.
        sched = SlotScheduler(1)
        seated = sched.admit(q)
        assert [s.request.priority for s in seated] == [0]

    def test_futile_preemption_is_bounded(self):
        """A candidate that could never seat even after evicting EVERY
        strictly-lower-tier active must not evict any of them (the
        engine's preempt_helps futility bound): best-effort progress is
        only thrown away when it buys an admission."""
        q = self._queue(num_tiers=2)
        q.submit([1], priority=1)
        q.submit([1], priority=1)
        sched = SlotScheduler(2)
        sched.admit(q)
        assert sched.num_active == 2
        q.submit([1] * 20, priority=0)  # too big for the whole pool
        preempted = []
        seated = sched.admit(
            q, on_preempt=preempted.append,
            preempt_helps=lambda entry, victims: False)
        assert seated == [] and preempted == []
        assert sched.num_active == 2  # nothing evicted for nothing

    def test_engine_futility_bound_keeps_best_effort_running(self, lm,
                                                             prompts):
        """Engine-level futility bound: a tier-0 candidate whose
        worst-case commitment exceeds available + EVERY preemptible
        page (most of the pool is pinned by non-preemptible tier-0
        work) must not evict the best-effort sequence — eviction is
        only paid when it buys an admission. The blocked candidate
        still seats later, once finished tier-0 work returns pages."""
        model, params = lm
        # 6-page pool (size 8). Tier-0 A commits 3 pages (9+8=17 tok),
        # tier-1 B commits 2 (3+8=11), leaving 1 available. Tier-0 C
        # needs 4 (24+8=32): 1 free + 2 preemptible (B) = 3 < 4 —
        # evicting B buys nothing, so B must keep decoding.
        eng = Engine(model, params, ServeConfig(
            max_batch=3, num_tiers=2, kv_page_size=8, kv_pages=6,
            max_len=32, max_new_tokens=8, prefill_chunk=4))
        a = eng.submit(np.arange(9, dtype=np.int32) % VOCAB,
                       priority=0, max_new_tokens=8)
        low = eng.submit(prompts[2], priority=1, max_new_tokens=8)
        for _ in range(4):
            eng.step()
        assert eng.scheduler.num_active == 2
        c = eng.submit(np.arange(24, dtype=np.int32) % VOCAB,
                       priority=0, max_new_tokens=8)
        eng.step()
        assert eng.stats()["requests_preempted"] == 0  # futile: skipped
        assert eng.scheduler.num_active == 2  # A and B still seated
        assert eng.phase == "overloaded"  # C is head-of-line blocked
        done = {f.uid: f for f in eng.run()}
        eng.pool.check_balanced()
        assert eng.stats()["requests_preempted"] == 0
        for uid in (a.uid, low.uid, c.uid):
            assert done[uid].tokens.size == 8

    def test_preemption_strictly_rank_ordered(self):
        """scheduler.admit only ever evicts STRICTLY lower tiers: an
        equal-tier candidate waits (no churn), and the victim is the
        worst tier's newest sequence."""
        q = self._queue(num_tiers=3)
        q.submit([1], priority=1)
        q.submit([1], priority=2)
        sched = SlotScheduler(2)
        sched.admit(q)
        assert sched.num_active == 2
        # Equal tier: no preemption, stays queued.
        q.submit([1], priority=2)
        assert sched.admit(q) == []
        assert len(q) == 1
        # Higher tier: evicts the tier-2 victim, not the tier-1 one;
        # the requeued victim cannot re-seat (both slots now hold
        # equal-or-higher tiers), so it waits with the other tier-2.
        q.submit([1], priority=0)
        preempted = []
        seated = sched.admit(q, on_preempt=preempted.append)
        assert [s.request.priority for s in seated] == [0]
        assert [p.request.priority for p in preempted] == [2]
        active = sorted(s.request.priority for s in sched.active())
        assert active == [0, 1]
        assert len(q) == 2


class TestTrafficScenarios:
    def test_scenarios_deterministic_sorted_admissible(self):
        from tools.traffic import SCENARIOS, make_scenario

        kw = dict(seed=5, requests=40, rate=200, mean_prompt_len=8,
                  max_prompt_len=40, max_new_tokens=16, vocab_size=64,
                  budget=56)
        for name, scen in SCENARIOS.items():
            a = make_scenario(name, **kw)
            b = make_scenario(name, **kw)
            assert len(a) == len(b) >= 1, name
            for x, y in zip(a, b):
                assert x.arrival_s == y.arrival_s, name
                assert np.array_equal(x.prompt, y.prompt), name
                assert (x.priority, x.tenant, x.max_new_tokens) == \
                    (y.priority, y.tenant, y.max_new_tokens), name
            assert all(a[i].arrival_s <= a[i + 1].arrival_s
                       for i in range(len(a) - 1)), name
            for r in a:
                assert 1 <= r.prompt.size <= 40, name
                assert r.prompt.size + r.max_new_tokens <= 56, name
                assert 0 <= r.priority < scen.num_tiers, name
            tiers = set(r.priority for r in a)
            assert len(tiers) == scen.num_tiers, (name, tiers)

    def test_unknown_scenario_raises(self):
        from tools.traffic import make_scenario

        with pytest.raises(ValueError, match="unknown scenario"):
            make_scenario("nope", seed=0, requests=1, rate=1.0,
                          mean_prompt_len=4, max_prompt_len=8,
                          max_new_tokens=4, vocab_size=8, budget=16)

    def test_different_seeds_differ(self):
        from tools.traffic import make_scenario

        kw = dict(requests=20, rate=100, mean_prompt_len=8,
                  max_prompt_len=30, max_new_tokens=8, vocab_size=64,
                  budget=40)
        a = make_scenario("bursty", seed=1, **kw)
        b = make_scenario("bursty", seed=2, **kw)
        assert [r.arrival_s for r in a] != [r.arrival_s for r in b]


class TestServeBenchOverloadCli:
    def test_overload_drill_selective_degradation(self, monkeypatch,
                                                  capsys):
        """The CI drill in miniature: two_tier_burst at an unsustainable
        rate under the deterministic --virtual-dt drive. Tier 0 must
        finish everything it submitted un-shed while tier 1 absorbs the
        shed/preempt pressure, and the SLA line must carry the per-tier
        keys the bench gate diffs."""
        from conftest import load_cli_module

        bench = load_cli_module("tools/serve_bench.py")
        monkeypatch.setattr("sys.argv", [
            "serve_bench.py", "--requests", "24", "--rate", "800",
            "--max-batch", "2", "--kv-pages", "24", "--num-layers", "1",
            "--num-heads", "2", "--hidden-dim", "32",
            "--model-max-len", "64", "--prompt-len", "8",
            "--max-new-tokens", "8", "--prefill-chunk", "8",
            "--scenario", "two_tier_burst", "--virtual-dt", "2",
            "--max-queue-depth", "6"])
        assert bench.main() == 0
        stats = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert stats["scenario"] == "two_tier_burst"
        for key in ("requests_preempted", "preempted_token_recompute",
                    "tier0_requests_finished", "tier1_requests_finished",
                    "tier0_requests_shed", "tier1_requests_shed",
                    "tier0_ttft_hist_p99_ms", "tier1_ttft_hist_p99_ms",
                    "requests_preempt_timed_out", "shed_at_submit"):
            assert key in stats, key
        # Selective degradation: the high tier is untouched while the
        # best-effort tier sheds and is preempted.
        assert stats["tier0_requests_shed"] == 0
        assert stats["tier1_requests_shed"] > 0
        assert stats["requests_preempted"] > 0
        assert stats["requests_timed_out"] == 0
        # two_tier_burst submits 40% tier-0 (see tools/traffic.py).
        assert stats["tier0_requests_finished"] == 10
        # Ordering claim, scale-free: the high tier's p99 beats the
        # best-effort tier's.
        assert (stats["tier0_ttft_hist_p99_ms"]
                < stats["tier1_ttft_hist_p99_ms"])


@pytest.mark.slow
class TestChaosComposition:
    def test_preempt_storm_during_speculation_and_hotswap(self, lm):
        """The composed drill: a preemption storm (best-effort work
        occupying every slot, tier-0 waves evicting it) runs WITH
        speculative decoding while a live weight hot-swap barrier fires
        mid-storm. Zero failed requests, pool balanced, and — because
        the swapped-in tree carries identical values — every output
        bitwise equal to the uninterrupted single-slot oracle."""
        model, params = lm
        from tools.traffic import make_scenario

        reqs = make_scenario(
            "preempt_storm", seed=7, requests=18, rate=500,
            mean_prompt_len=6, max_prompt_len=20, max_new_tokens=10,
            vocab_size=VOCAB, budget=MAX_LEN)
        cfg_kw = dict(max_new_tokens=10, prefill_chunk=4, spec_k=2,
                      kv_pages=30)
        eng = Engine(model, params, ServeConfig(
            max_batch=2, num_tiers=2, **cfg_kw))
        same_values = jax.tree.map(lambda a: np.asarray(a).copy(),
                                   params)
        submitted = 0
        it = 0
        uids = {}
        done = {}
        while submitted < len(reqs):
            vnow = it * 0.002
            while (submitted < len(reqs)
                   and reqs[submitted].arrival_s <= vnow):
                r = reqs[submitted]
                req = eng.submit(r.prompt,
                                 max_new_tokens=r.max_new_tokens,
                                 priority=r.priority, tenant=r.tenant)
                uids[submitted] = req.uid
                submitted += 1
                if submitted == 9:
                    # Same-values tree: the barrier machinery runs for
                    # real (validate + install + drafter re-point) but
                    # outputs stay comparable to the no-swap oracle.
                    eng.arm_swap(same_values, epoch=1)
            for fin in eng.step():
                done[fin.uid] = fin
            it += 1
        for fin in eng.drain():
            done[fin.uid] = fin
        eng.pool.check_balanced()
        stats = eng.stats()
        assert stats["requests_finished"] == len(reqs)
        assert stats["requests_preempted"] >= 1
        assert stats["requests_shed"] == 0
        assert stats["requests_timed_out"] == 0
        assert stats["requests_preempt_timed_out"] == 0
        assert stats["swaps_completed"] == 1
        assert stats["drafted_tokens"] > 0
        solo = _solo_outputs(
            model, params,
            [(r.prompt, r.max_new_tokens) for r in reqs], **cfg_kw)
        for i, r in enumerate(reqs):
            uid = uids[i]
            assert done[uid].tokens.tolist() == solo[uid], (i, uid)
