"""Device prefetcher: ordering, error propagation, and engine equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.config import (
    DataConfig,
    LMConfig,
    MeshSpec,
    TrainConfig,
)
from distributed_training_tpu.data.prefetch import (
    DevicePrefetcher,
    prefetch_to_mesh,
)
from distributed_training_tpu.train.lm_trainer import LMTrainer


def test_prefetcher_preserves_order_and_content():
    batches = [{"x": np.full((2,), i)} for i in range(10)]
    out = list(DevicePrefetcher(batches, lambda b: b, depth=3))
    assert len(out) == 10
    for i, b in enumerate(out):
        np.testing.assert_array_equal(b["x"], np.full((2,), i))


def test_prefetcher_reiterates():
    """Each __iter__ starts a fresh pass (epoch loop reuse)."""
    batches = [{"x": np.asarray([i])} for i in range(3)]
    pf = DevicePrefetcher(batches, lambda b: b, depth=2)
    assert [int(b["x"][0]) for b in pf] == [0, 1, 2]
    assert [int(b["x"][0]) for b in pf] == [0, 1, 2]


def test_prefetcher_propagates_worker_errors():
    def gen():
        yield {"x": np.zeros(1)}
        raise RuntimeError("augment exploded")

    it = iter(DevicePrefetcher(gen(), lambda b: b, depth=2))
    next(it)
    with pytest.raises(RuntimeError, match="augment exploded"):
        next(it)


def test_prefetcher_rejects_bad_depth():
    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher([], lambda b: b, depth=0)


def test_prefetch_to_mesh_places_on_shardings():
    from distributed_training_tpu.runtime.mesh import MeshConfig, create_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh(MeshConfig(data=-1))
    sh = {"x": NamedSharding(mesh, P("data"))}
    batches = [{"x": np.arange(16, dtype=np.float32)}]
    (placed,) = list(prefetch_to_mesh(batches, mesh, sh, depth=1))
    assert placed["x"].sharding.spec == P("data")


def test_trainer_prefetch_equivalent(tmp_path):
    """prefetch=2 and prefetch=0 produce identical training trajectories."""
    def run(prefetch):
        cfg = TrainConfig(model="transformer_lm").replace(
            num_epochs=1, log_interval=2,
            data=DataConfig(batch_size=8, max_steps_per_epoch=4,
                            prefetch=prefetch),
            lm=LMConfig(seq_len=32, num_layers=2, num_heads=2, hidden_dim=32,
                        max_len=64, train_sequences=128, eval_sequences=64),
            mesh=MeshSpec(data=-1),
        )
        return LMTrainer(cfg).fit()

    a, b = run(0), run(2)
    assert a["final_perplexity"] == pytest.approx(
        b["final_perplexity"], rel=1e-6)
    assert a["last_metrics"]["loss"] == pytest.approx(
        b["last_metrics"]["loss"], rel=1e-6)
