"""Radix-tree prefix cache tests (serving/prefix_cache.py).

Load-bearing properties, in order of importance:

1. **Bitwise neutrality** (the repo's signature invariant, extended):
   a cache-hit request — seated with its prefix pages aliased from the
   trie and only the tail prefilled — produces a token stream BITWISE
   identical to the same request served cold, greedy AND sampled,
   speculation on AND off. Reuse changes which pages a block table
   points at, never a gathered value or a sampled token.
2. **Exactly-once page release** (the shared-free bugfix satellite):
   a page aliased by the trie and N sequences holds N+1 references and
   returns to the free list exactly once — each holder's ``free``
   drops ITS reference, each seat's ``uncommit`` returns only what IT
   committed (a hit commits only the non-resident tail), and
   ``check_balanced`` audits the trie-held steady state.
3. **Eviction safety**: LRU reclaims only unreferenced leaves (never a
   page a live sequence aliases, never the chain a candidate is about
   to hit), under both the ``prefix_cache_pages`` cap and pool
   commitment pressure — and the pool drains balanced after the churn.
4. **Preempt-and-restore** (ROADMAP item 4 follow-on): a preempted
   victim's pages enter the trie at eviction, its re-seat hits them,
   and ``preempted_token_recompute`` drops to the divergent tail —
   while the output stays bitwise the uninterrupted run's.
5. **Swap flush**: KV cached under old weights never seeds a
   new-epoch request; old-epoch in-flight sequences free cleanly and
   never re-index their pages.

Engines compile real XLA programs, so the model is tiny and the
bitwise matrix covers every axis value (greedy/sampled × spec 0/2)
without the full product.
"""

import jax
import numpy as np
import pytest

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.serving import (
    Engine,
    PagePool,
    PrefixCache,
)

VOCAB = 31
MAX_LEN = 64
PS = 4  # kv page size under test: small, so short prompts span pages


@pytest.fixture(scope="module")
def lm():
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=1, num_heads=2,
        hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


def make_engine(lm, **kw):
    model, params = lm
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("kv_page_size", PS)
    kw.setdefault("prefill_chunk", 4)
    return Engine(model, params, ServeConfig(**kw))


PREAMBLE = (np.arange(1, 21, dtype=np.int32) * 3) % VOCAB  # 20 tokens


def _serve(eng, prompts, **submit_kw):
    """Submit ``prompts`` one at a time, each run to completion —
    uids follow submission order, so outputs are comparable across
    engines (fold_in(seed, uid) parity)."""
    out = []
    for p in prompts:
        eng.submit(p, **submit_kw)
        out.extend(eng.run())
    return {f.uid: f for f in out}


# -- pool refcounts (the shared-free / double-uncommit audit) ---------------
class TestSharedPages:
    def test_shared_page_freed_exactly_once(self):
        """Two holders (trie + a sequence) → two frees to release; the
        page hits the free list exactly once, and a third free raises
        like any double free."""
        pool = PagePool(num_pages=4, page_size=PS)
        (p,) = pool.alloc(1, committed=False)
        pool.incref([p])
        assert pool.refcount(p) == 2
        pool.free([p])                     # sequence finishes
        assert pool.refcount(p) == 1
        assert pool.num_free == 3          # still held by the trie
        pool.free([p])                     # trie evicts
        assert pool.refcount(p) == 0
        assert pool.num_free == 4
        with pytest.raises(ValueError, match="double free|not allocated"):
            pool.free([p])

    def test_uncommit_released_exactly_once_per_committer(self):
        """The double-uncommit audit: a hit request commits only its
        tail, so two sequences sharing a page each release exactly
        their OWN commitment — total commitment conserves."""
        pool = PagePool(num_pages=8, page_size=PS)
        pool.commit(3)                     # cold request: 3-page worst
        pages = pool.alloc(2)              # wrote 2, 1 commitment unused
        pool.incref([pages[0]])            # trie indexes page 0
        pool.free(pages, uncommit=1)       # cold finish: its own refund
        assert pool.committed == 0
        pool.commit(2)                     # hit request: tail-only commit
        pool.incref([pages[0]])            # ...aliases the cached page
        tail = pool.alloc(1)
        pool.free([pages[0]] + tail, uncommit=1)
        assert pool.committed == 0         # never released twice
        pool.free([pages[0]])              # trie lets go last
        pool.check_balanced()

    def test_incref_free_page_raises(self):
        pool = PagePool(num_pages=2, page_size=PS)
        with pytest.raises(ValueError, match="not allocated"):
            pool.incref([1])

    def test_check_balanced_audits_trie_pages(self):
        pool = PagePool(num_pages=4, page_size=PS)
        pages = pool.alloc(2, committed=False)
        pool.check_balanced(cached=set(pages))  # trie holds both: OK
        with pytest.raises(AssertionError, match="drift"):
            pool.check_balanced(cached={pages[0]})
        pool.incref([pages[0]])
        with pytest.raises(AssertionError, match="stranded"):
            pool.check_balanced(cached=set(pages))


# -- trie mechanics ---------------------------------------------------------
class TestTrie:
    def _pool_cache(self, max_pages=None):
        pool = PagePool(num_pages=16, page_size=PS)
        return pool, PrefixCache(PS, max_pages=max_pages)

    def test_page_granular_match_and_cap(self):
        pool, cache = self._pool_cache()
        toks = np.arange(10, dtype=np.int32)     # 2 full pages + 2 tail
        pages = pool.alloc(3, committed=False)
        adopted, _ = cache.insert_chain(toks, pages, pool)
        assert adopted == set(pages[:2])          # partial page never indexed
        pool.free([pages[2]])
        # Full-prefix probe: both pages; the fresh-request cap
        # (prompt - 1) keeps the last position un-aliased when the
        # prompt is exactly the cached chain.
        assert cache.probe(toks, max_tokens=10) == pages[:2]
        assert cache.probe(toks[:8], max_tokens=7) == pages[:1]
        assert cache.probe(toks[:3], max_tokens=3) == []
        # Divergent second page: only the shared first page matches.
        other = np.concatenate([toks[:4], toks[:4]])
        assert cache.probe(other, max_tokens=8) == pages[:1]

    def test_duplicate_insert_keeps_resident_page(self):
        pool, cache = self._pool_cache()
        toks = np.arange(8, dtype=np.int32)
        first = pool.alloc(2, committed=False)
        assert cache.insert_chain(toks, first, pool)[0] == set(first)
        dup = pool.alloc(2, committed=False)
        adopted, _ = cache.insert_chain(toks, dup, pool)
        assert adopted == set()                   # trie keeps the original
        pool.free(dup)
        assert cache.pages_held() == set(first)

    def test_lru_eviction_order_refs_and_pinning(self):
        pool, cache = self._pool_cache()
        chains = []
        for i in range(3):
            toks = (np.arange(8, dtype=np.int32) + 11 * i) % VOCAB
            pages = pool.alloc(2, committed=False)
            cache.insert_chain(toks, pages, pool)
            chains.append((toks, pages))
        # Touch chain 0 (recency) and alias chain 1 (a live reference).
        held = cache.claim(chains[0][0], pool, max_tokens=8)
        assert held == chains[0][1]
        seq_ref = cache.claim(chains[1][0], pool, max_tokens=8)
        # Pressure: need every free page back. Evictable = chain 2 only
        # (chain 0 pinned by the caller, chain 1 referenced).
        evicted = cache.evict_until(pool, 16, pinned=set(chains[0][1]))
        assert evicted == 2
        assert cache.pages_held() == set(chains[0][1] + chains[1][1])
        pool.free(held)
        pool.free(seq_ref)
        evicted = cache.evict_until(pool, 16)
        assert evicted == 4 and cache.num_pages == 0
        pool.check_balanced()

    def test_max_pages_cap_evicts_lru(self):
        pool, cache = self._pool_cache(max_pages=2)
        a = np.arange(8, dtype=np.int32)
        b = (np.arange(8, dtype=np.int32) + 13) % VOCAB
        pa = pool.alloc(2, committed=False)
        cache.insert_chain(a, pa, pool)
        pb = pool.alloc(2, committed=False)
        adopted, evicted = cache.insert_chain(b, pb, pool)
        assert adopted == set(pb) and evicted == 2  # a's chain aged out
        assert cache.num_pages == 2
        assert cache.probe(a, max_tokens=8) == []
        assert cache.probe(b, max_tokens=8) == pb

    def test_flush_respects_live_references(self):
        pool, cache = self._pool_cache()
        toks = np.arange(8, dtype=np.int32)
        pages = pool.alloc(2, committed=False)
        cache.insert_chain(toks, pages, pool)
        aliased = cache.claim(toks, pool, max_tokens=8)
        assert cache.flush(pool) == 2
        assert cache.num_pages == 0
        # The in-flight sequence still owns its aliased pages.
        assert pool.refcount(aliased[0]) == 1
        pool.free(aliased)
        pool.check_balanced()


# -- engine integration: the bitwise pin ------------------------------------
# Every axis value (greedy/sampled, spec 0/2) without the full product.
BITWISE_CASES = [(0.0, 0), (0.8, 0), (0.0, 2), (0.8, 2)]


class TestCacheHitBitwise:
    @pytest.mark.parametrize("temp,spec_k", BITWISE_CASES)
    def test_hit_bitwise_equals_cold(self, lm, temp, spec_k):
        """THE invariant: request B shares A's preamble; on the warm
        engine B seats with the preamble aliased from the trie and
        prefills only its tail — its tokens must equal the cold
        engine's bitwise, for every sampling/speculation mode."""
        prompts = [np.concatenate([PREAMBLE, np.asarray(s, np.int32)])
                   for s in ([3, 5], [7, 9, 11])]
        cold = make_engine(lm, temperature=temp, spec_k=spec_k)
        warm = make_engine(lm, temperature=temp, spec_k=spec_k,
                           prefix_cache=True)
        cold_out = _serve(cold, prompts)
        warm_out = _serve(warm, prompts)
        sw = warm.stats()
        assert sw["prefix_cache_hit_requests"] == 1
        # B's hit covers the preamble's full pages (20 tokens = 5 pages).
        assert sw["prefix_cache_hit_tokens"] == 20
        assert sw["ledger_tokens_prefix_hit"] == 20
        assert cold.stats()["prefix_cache_hit_tokens"] == 0
        for uid, fin in cold_out.items():
            assert np.array_equal(fin.tokens, warm_out[uid].tokens), uid
            assert fin.finish_reason == warm_out[uid].finish_reason
        # Reused positions bill to prefix_hit, never to prefill: the
        # two engines' prefill+hit totals cover the same positions.
        sc = cold.stats()
        assert (sw["ledger_tokens_prefill"] + sw["prefix_cache_hit_tokens"]
                == sc["ledger_tokens_prefill"])
        warm.check_balanced()
        cold.check_balanced()

    def test_identical_prompt_keeps_one_position_cold(self, lm):
        """A prompt ENTIRELY resident still prefills its last position:
        the first token samples from computed logits, never from
        memory. The hit caps at floor((prompt-1)/page)*page."""
        eng = make_engine(lm, prefix_cache=True)
        # 20-token prompt: cap 19 -> 4 full pages = 16 aliased tokens.
        out = _serve(eng, [PREAMBLE, PREAMBLE])
        cold = make_engine(lm)
        ref = _serve(cold, [PREAMBLE, PREAMBLE])
        st = eng.stats()
        assert st["prefix_cache_hit_tokens"] == 16
        for uid in ref:
            assert np.array_equal(ref[uid].tokens, out[uid].tokens)
        eng.check_balanced()

    def test_stats_keys_present_when_off(self, lm):
        eng = make_engine(lm)
        st = eng.stats()
        for key in ("prefix_cache_hit_tokens", "prefix_cache_hit_requests",
                    "prefix_cache_inserted_pages",
                    "prefix_cache_evicted_pages",
                    "prefix_cache_pages_held", "ledger_tokens_prefix_hit"):
            assert st[key] == 0

    def test_legacy_path_refuses(self, lm):
        with pytest.raises(ValueError, match="prefix_cache requires"):
            make_engine(lm, prefix_cache=True, kv_page_size=None)


class TestEvictionPressure:
    def test_pool_pressure_evicts_and_stays_balanced(self, lm):
        """Distinct prompts fill the trie until admission cannot commit
        a worst case; the LRU pressure path reclaims unreferenced trie
        pages, every request still completes, and the drained pool is
        balanced with the survivors accounted to the trie."""
        # Pool = 2 slots' worst case exactly: any trie residue blocks
        # the next admission, so eviction MUST run for later seats.
        eng = make_engine(lm, prefix_cache=True, max_len=32,
                          kv_pages=16, max_new_tokens=4)
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, VOCAB, size=12).astype(np.int32)
                   for _ in range(6)]
        out = _serve(eng, prompts)
        assert len(out) == 6
        st = eng.stats()
        assert st["prefix_cache_inserted_pages"] > 0
        assert st["prefix_cache_evicted_pages"] > 0
        eng.check_balanced()

    def test_cap_pressure_stays_balanced(self, lm):
        eng = make_engine(lm, prefix_cache=True, prefix_cache_pages=3,
                          max_new_tokens=4)
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, VOCAB, size=10).astype(np.int32)
                   for _ in range(4)]
        _serve(eng, prompts)
        st = eng.stats()
        assert st["prefix_cache_pages_held"] <= 3
        assert st["prefix_cache_evicted_pages"] > 0
        eng.check_balanced()


class TestPreemptAndRestore:
    @pytest.mark.parametrize("temp", [0.0, 0.8])
    def test_victim_reseat_hits_own_pages(self, lm, temp):
        """ROADMAP item 4 follow-on: the victim's committed pages enter
        the trie at eviction, so its re-seat aliases them back and
        preempted_token_recompute drops to the divergent tail — with
        the output still bitwise the no-preemption oracle's."""

        def run(prefix_cache):
            eng = make_engine(lm, max_batch=1, num_tiers=2,
                              temperature=temp, max_new_tokens=8,
                              prefix_cache=prefix_cache)
            low = eng.submit(PREAMBLE, priority=1, max_new_tokens=8)
            for _ in range(8):  # finish prefill, emit a few tokens
                eng.step()
            assert len(eng.scheduler.sequence(0).tokens) >= 1
            high = eng.submit(np.asarray([2, 4, 6], np.int32),
                              priority=0, max_new_tokens=4)
            done = {f.uid: f for f in eng.run()}
            eng.check_balanced()
            return eng, done, low, high

        e_off, d_off, lo_off, _ = run(False)
        e_on, d_on, lo_on, _ = run(True)
        assert np.array_equal(d_off[lo_off.uid].tokens,
                              d_on[lo_on.uid].tokens)
        s_off, s_on = e_off.stats(), e_on.stats()
        assert s_off["requests_preempted"] == s_on["requests_preempted"] >= 1
        # Cache off: the whole carried prefix recomputes. Cache on: the
        # re-seat hits the victim's own pages — only the page-unaligned
        # tail (and positions written after the eviction snapshot)
        # recompute.
        assert s_on["requests_preempted"] >= 1
        assert 0 < s_on["preempted_token_recompute"] \
            < s_off["preempted_token_recompute"]
        assert s_on["prefix_cache_hit_tokens"] > 0


class TestSwapFlush:
    def test_barrier_flushes_and_old_epoch_never_reindexes(self, lm):
        model, params = lm
        params2 = model.init(jax.random.PRNGKey(9),
                             np.zeros((1, 8), np.int32))["params"]
        eng = make_engine(lm, prefix_cache=True)
        _serve(eng, [np.concatenate([PREAMBLE, np.asarray([3], np.int32)])])
        assert eng.prefix_cache.num_pages > 0
        # In-flight across the barrier: seat a second preamble request,
        # let it hit, then swap mid-sequence.
        eng.submit(np.concatenate([PREAMBLE, np.asarray([8], np.int32)]))
        eng.step()
        assert eng.stats()["prefix_cache_hit_tokens"] == 20
        eng.arm_swap(params2, epoch=1)
        eng.step()  # barrier: trie flushed, epoch bumped
        assert eng.prefix_cache.num_pages == 0
        fins = eng.run()  # old-epoch sequence finishes under new weights
        assert fins
        # ...and did NOT re-index its stale-weight pages.
        assert eng.prefix_cache.num_pages == 0
        # A post-swap twin is COLD (no stale-KV hit), then repopulates.
        _serve(eng, [np.concatenate([PREAMBLE, np.asarray([5], np.int32)])])
        assert eng.stats()["prefix_cache_hit_tokens"] == 20  # unchanged
        assert eng.prefix_cache.num_pages > 0
        eng.check_balanced()


class TestScenario:
    def test_shared_prefix_deterministic_and_admissible(self):
        from tools.traffic import make_scenario

        kw = dict(seed=5, requests=40, rate=100.0, mean_prompt_len=16,
                  max_prompt_len=24, max_new_tokens=8, vocab_size=VOCAB,
                  budget=32)
        a = make_scenario("shared_prefix", **kw)
        b = make_scenario("shared_prefix", **kw)
        assert len(a) == 40
        for ra, rb in zip(a, b):
            assert ra.arrival_s == rb.arrival_s
            assert np.array_equal(ra.prompt, rb.prompt)
            assert ra.max_new_tokens == rb.max_new_tokens
            assert ra.tenant == rb.tenant
        for r in a:
            assert 1 <= r.prompt.size <= 24
            assert r.prompt.size + r.max_new_tokens <= 32
        # The point of the scenario: prompts actually share preambles.
        heads = {}
        for r in a:
            key = r.prompt[:8].tobytes()
            heads[key] = heads.get(key, 0) + 1
        assert max(heads.values()) >= 5, heads.values()
        c = make_scenario("shared_prefix", **{**kw, "seed": 6})
        assert any(not np.array_equal(ra.prompt, rc.prompt)
                   for ra, rc in zip(a, c))


class TestJournalColdStart:
    def test_recovery_cold_starts_trie(self, lm, tmp_path):
        """The trie is not journaled: a restart replays bitwise with an
        empty cache and repopulates as recovered work completes."""
        prompts = [np.concatenate([PREAMBLE, np.asarray(s, np.int32)])
                   for s in ([3], [9])]
        eng1 = make_engine(lm, prefix_cache=True,
                           journal_dir=str(tmp_path))
        eng1.recover()
        out1 = _serve(eng1, prompts)
        assert eng1.stats()["prefix_cache_hit_tokens"] == 20
        eng1.journal.shutdown()
        eng2 = make_engine(lm, prefix_cache=True,
                           journal_dir=str(tmp_path))
        report = eng2.recover()
        assert eng2.prefix_cache.num_pages == 0  # cold start
        redelivered = {f.uid: f for f in report["redelivered"]}
        for uid, fin in out1.items():
            assert np.array_equal(redelivered[uid].tokens, fin.tokens)
        # The replayed engine serves (and caches) fresh work normally.
        out2 = _serve(eng2, [prompts[0]])
        assert len(out2) == 1
        eng2.check_balanced()
        eng2.journal.shutdown()
