"""Quantized execution tests (serving/quantize.py; docs/SERVING.md
"Quantized execution").

Load-bearing properties, in order of importance:

1. **Determinism, not approximation-of-determinism**: quantization is
   round-to-nearest with per-channel scales computed from the weights
   alone (weights) or from each row's own K/V (cache) — so a quantized
   engine is bitwise-reproducible across runs, and a batched quantized
   run equals its own single-slot quantized oracle for every
   sampling/speculation mode. Quantization relocates the numerics; it
   never makes them batch- or timing-dependent.
2. **Bounded quality**: dequantized weights sit within half a scale
   step of the originals per channel, the fixed-seed eval loss moves
   by less than the documented bound, and greedy decode matches the
   fp32 engine's token streams at >= 0.98 per-token on the smoke
   geometry (wide hidden, small vocab — see the CI quantization
   drill).
3. **Off the hot path**: weights quantize ONCE at engine construction
   and at swap arm time (watcher thread); the compiled-program
   inventory stays at the paged engine's two programs, int8 KV
   included (quantize-on-scatter / dequantize-in-gather live inside
   the same jits).
4. **The serving plane composes**: hot-swap (validate/arm/barrier/
   rollback), preempt-and-restore, the prefix-cache trie, and journal
   recovery all operate on the quantized engine unchanged, bitwise
   against their own quantized oracles.

Engines compile real XLA programs, so the mechanics model is tiny;
the bitwise matrix covers every axis value (greedy/sampled x spec
0/2) pairwise in tier-1 and in full under ``-m slow``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import traverse_util

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.resilience.errors import SwapError
from distributed_training_tpu.serving import Engine, JournalCorruptError
from distributed_training_tpu.serving.quantize import (
    QuantizedTensor,
    dequantize_params,
    is_quantized,
    quantize_array,
    quantize_params,
    quantized_param_bytes,
    reduce_axes_for,
)

VOCAB = 31
MAX_LEN = 64
PS = 4


@pytest.fixture(scope="module")
def lm():
    """Mechanics model: tiny, so the bitwise matrix stays cheap."""
    model = get_model(
        "transformer_lm", num_classes=VOCAB, num_layers=1, num_heads=2,
        hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def lm_q():
    """Quality model: the CI drill's geometry — wide hidden (small
    relative quantization error), small vocab (wide top-2 logit gap),
    so greedy argmax survives int8 even at random init."""
    model = get_model(
        "transformer_lm", num_classes=16, num_layers=1, num_heads=2,
        hidden_dim=64, max_len=64)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


def make_engine(lm, **kw):
    model, params = lm
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("kv_page_size", PS)
    kw.setdefault("prefill_chunk", 4)
    return Engine(model, params, ServeConfig(**kw))


def _serve(eng, prompts, **submit_kw):
    """One request at a time, each run to completion — uids follow
    submission order, so outputs are comparable across engines
    (fold_in(seed, uid) parity)."""
    out = []
    for p in prompts:
        eng.submit(p, **submit_kw)
        out.extend(eng.run())
    return {f.uid: f for f in out}


PROMPTS = [np.asarray(s, np.int32)
           for s in ([3, 5, 7, 2], [11, 13, 4, 9, 1, 6], [8, 8, 8])]


# -- quantize_array / quantize_params mechanics -----------------------------
class TestQuantizeArray:
    def test_round_trip_bounded_per_channel(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (8, 16), jnp.float32)
        qt = quantize_array(w, (0,))
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (1, 16)
        assert int(jnp.max(jnp.abs(qt.q.astype(jnp.int32)))) <= 127
        # Round-to-nearest: every element within half a scale step.
        err = jnp.abs(qt.dequantize() - w)
        assert bool(jnp.all(err <= qt.scale / 2 + 1e-7))
        # Per-channel max hits the int8 rail exactly.
        assert bool(jnp.all(jnp.max(jnp.abs(qt.q), axis=0) == 127))

    def test_zero_channel_gets_unit_scale(self):
        w = jnp.zeros((4, 3), jnp.float32).at[:, 1].set(2.0)
        qt = quantize_array(w, (0,))
        assert float(qt.scale[0, 0]) == 1.0  # no div-by-zero sentinel
        assert bool(jnp.all(qt.dequantize()[:, 0] == 0.0))
        assert bool(jnp.all(qt.dequantize()[:, 1] == 2.0))

    def test_astype_dequantizes(self):
        """The duck-typed contract the model relies on: ``astype`` on a
        QuantizedTensor yields the dequantized array in that dtype, so
        existing ``kernel.astype(self.dtype)`` call-sites dequantize
        with zero model changes."""
        w = jax.random.normal(jax.random.PRNGKey(2), (6, 5), jnp.float32)
        qt = quantize_array(w, (0,))
        out = qt.astype(jnp.bfloat16)
        assert out.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.abs(out.astype(jnp.float32) - w) < 0.1))


class TestQuantizeParams:
    def test_tree_structure_and_coverage(self, lm):
        """Matmul weights quantize with the documented reduce axes;
        layernorms, biases, positional tables and the logits head stay
        untouched."""
        _, params = lm
        qp = quantize_params(params)
        assert is_quantized(qp) and not is_quantized(params)
        flat = traverse_util.flatten_dict(params, sep="/")
        qflat = traverse_util.flatten_dict(
            qp, sep="/",
            is_leaf=lambda _, v: isinstance(v, QuantizedTensor))
        assert set(flat) == set(qflat)
        n_quant = 0
        for path, leaf in flat.items():
            axes = reduce_axes_for(path)
            qleaf = qflat[path]
            if axes is None:
                # Untouched: same object semantics (dtype + values).
                assert not isinstance(qleaf, QuantizedTensor), path
                assert qleaf.dtype == leaf.dtype, path
                assert bool(jnp.all(qleaf == leaf)), path
            else:
                n_quant += 1
                assert isinstance(qleaf, QuantizedTensor), path
                assert qleaf.q.shape == leaf.shape, path
                expect_scale = tuple(
                    1 if a in axes else d
                    for a, d in enumerate(leaf.shape))
                assert qleaf.scale.shape == expect_scale, path
        # 1 layer: tok_embed + qkv + out + fc1 + fc2 = 5 quantized.
        assert n_quant == 5

    def test_quantized_param_bytes(self, lm):
        _, params = lm
        qp = quantize_params(params)
        expect = sum(
            leaf.nbytes
            for leaf in jax.tree_util.tree_leaves(
                qp, is_leaf=lambda v: isinstance(v, QuantizedTensor))
            if isinstance(leaf, QuantizedTensor))
        got = quantized_param_bytes(qp)
        assert got == expect > 0
        assert quantized_param_bytes(params) == 0

    def test_dequantize_params_restores_structure(self, lm):
        _, params = lm
        deq = dequantize_params(quantize_params(params))
        assert (jax.tree_util.tree_structure(deq)
                == jax.tree_util.tree_structure(params))
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(deq)):
            assert a.shape == b.shape and a.dtype == b.dtype
            assert bool(jnp.all(jnp.abs(a - b) <= 0.05))


# -- config gating ----------------------------------------------------------
class TestConfig:
    def test_kv_dtype_requires_paged(self):
        with pytest.raises(ValueError, match="paged"):
            ServeConfig(kv_dtype="int8", kv_page_size=None)

    def test_unknown_kv_dtype_rejected(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            ServeConfig(kv_dtype="fp8", kv_page_size=4)


# -- engine: determinism (the repo's signature invariant, quantized) --------
# Every axis value (greedy/sampled, spec 0/2) pairwise in tier-1; the
# remaining off-diagonal pairs run under -m slow.
BITWISE_CASES = [(0.0, 0), (0.8, 2)]
BITWISE_CASES_SLOW = [(0.8, 0), (0.0, 2)]


class TestQuantizedDeterminism:
    def _check_oracle(self, lm, temp, spec_k):
        """Batched quantized run == its own single-slot quantized
        oracle: quantization must not introduce batch-composition
        dependence (per-row cache scales depend only on that row's own
        K/V)."""
        kw = dict(temperature=temp, spec_k=spec_k,
                  quantize_weights=True, kv_dtype="int8")
        batched = make_engine(lm, max_batch=2, **kw)
        oracle = make_engine(lm, max_batch=1, **kw)
        out_b = _serve(batched, PROMPTS)
        out_o = _serve(oracle, PROMPTS)
        for uid, fin in out_o.items():
            assert np.array_equal(fin.tokens, out_b[uid].tokens), uid
            assert fin.finish_reason == out_b[uid].finish_reason
        batched.check_balanced()

    @pytest.mark.parametrize("temp,spec_k", BITWISE_CASES)
    def test_batch_equals_single_slot_oracle(self, lm, temp, spec_k):
        self._check_oracle(lm, temp, spec_k)

    @pytest.mark.slow
    @pytest.mark.parametrize("temp,spec_k", BITWISE_CASES_SLOW)
    def test_batch_equals_single_slot_oracle_full(self, lm, temp, spec_k):
        self._check_oracle(lm, temp, spec_k)

    def test_two_runs_bitwise_identical(self, lm):
        outs = []
        for _ in range(2):
            eng = make_engine(lm, temperature=0.8,
                              quantize_weights=True, kv_dtype="int8")
            outs.append(_serve(eng, PROMPTS))
        for uid, fin in outs[0].items():
            assert np.array_equal(fin.tokens, outs[1][uid].tokens)

    def test_compiled_inventory_stays_two(self, lm):
        """Quantize-on-scatter / dequantize-in-gather live INSIDE the
        paged engine's two programs — int8 KV grows the inventory by
        zero."""
        from distributed_training_tpu.observability.sanitizer import (
            check_engine_inventory,
        )

        eng = make_engine(lm, quantize_weights=True, kv_dtype="int8")
        _serve(eng, PROMPTS[:2])  # warm both shapes
        assert check_engine_inventory(eng) == {"fused": 1, "decode": 1}


class TestQuantizedTelemetry:
    def test_counters_on_and_off(self, lm):
        on = make_engine(lm, quantize_weights=True, kv_dtype="int8")
        off = make_engine(lm)
        s_on, s_off = on.stats(), off.stats()
        assert s_on["quantized_params_bytes"] > 0
        assert s_on["weight_quant_s"] > 0.0
        assert s_off["quantized_params_bytes"] == 0
        assert s_off["weight_quant_s"] == 0.0
        # Cache geometry is config-deterministic either way.
        assert s_on["kv_bytes_per_token"] > 0
        assert s_off["kv_bytes_per_token"] > 0
        # The headline: int8 pages + scale planes vs fp32 rows.
        ratio = s_on["kv_bytes_per_token"] / s_off["kv_bytes_per_token"]
        assert ratio <= 0.55, ratio

    def test_counters_survive_reset(self, lm):
        eng = make_engine(lm, quantize_weights=True, kv_dtype="int8")
        before = eng.stats()
        eng.reset_stats()
        after = eng.stats()
        assert after["quantized_params_bytes"] \
            == before["quantized_params_bytes"]
        assert after["weight_quant_s"] == before["weight_quant_s"]
        assert after["kv_bytes_per_token"] == before["kv_bytes_per_token"]


# -- hot-swap on the quantized engine ---------------------------------------
class TestQuantizedHotSwap:
    def test_arm_quantizes_and_barrier_applies(self, lm):
        """arm_swap receives the restore path's fp32 tree, quantizes it
        on the calling (watcher) thread, and the barrier installs a
        quantized tree — post-swap output bitwise equals an engine
        BUILT quantized on the new weights."""
        model, params = lm
        params2 = model.init(jax.random.PRNGKey(9),
                             np.zeros((1, 8), np.int32))["params"]
        eng = make_engine(lm, quantize_weights=True, kv_dtype="int8")
        quant_s0 = eng.stats()["weight_quant_s"]
        _serve(eng, [PROMPTS[0]])
        eng.arm_swap(params2, epoch=1)
        out = _serve(eng, [PROMPTS[1]])  # barrier applies at next step
        assert eng.weights_epoch == 1
        assert is_quantized(eng.params)
        assert eng.stats()["swaps_completed"] == 1
        assert eng.stats()["weight_quant_s"] > quant_s0  # arm re-quantized
        # Greedy is uid-independent: a fresh quantized engine on the
        # new weights is the oracle.
        oracle = make_engine((model, params2), quantize_weights=True,
                             kv_dtype="int8")
        ref = _serve(oracle, [PROMPTS[0], PROMPTS[1]])
        (fin,) = out.values()
        ref_fin = [f for f in ref.values() if f.uid == 1]
        assert np.array_equal(fin.tokens, ref_fin[0].tokens)

    def test_validate_swap_accepts_fp32_and_quantized(self, lm):
        model, params = lm
        eng = make_engine(lm, quantize_weights=True, kv_dtype="int8")
        eng.validate_swap(params)                  # the restore tree
        eng.validate_swap(quantize_params(params))  # an already-staged tree
        with pytest.raises(SwapError):
            eng.validate_swap({"wrong": np.zeros(3, np.float32)})

    def test_rollback_rearms_quantized_prev(self, lm):
        model, params = lm
        params2 = model.init(jax.random.PRNGKey(9),
                             np.zeros((1, 8), np.int32))["params"]
        eng = make_engine(lm, quantize_weights=True, kv_dtype="int8")
        out0 = _serve(eng, [PROMPTS[0]])
        eng.arm_swap(params2, epoch=1)
        _serve(eng, [PROMPTS[1]])
        assert eng.weights_epoch == 1
        eng.rollback()  # re-arms the already-quantized previous tree
        out2 = _serve(eng, [PROMPTS[0]])
        assert eng.weights_epoch == -1  # back to the construction epoch
        assert is_quantized(eng.params)
        # Greedy: rolled-back weights reproduce the original stream.
        (a,), (b,) = out0.values(), out2.values()
        assert np.array_equal(a.tokens, b.tokens)


# -- prefix cache + preemption on the quantized engine ----------------------
PREAMBLE = (np.arange(1, 21, dtype=np.int32) * 3) % VOCAB  # 20 tokens


class TestQuantizedReuse:
    @pytest.mark.parametrize("temp", [0.0, 0.8])
    def test_prefix_hit_bitwise_equals_cold_quantized(self, lm, temp):
        """A trie hit aliases QUANTIZED pages; the gathered values are
        identical to a cold quantized prefill of the same tokens, so
        the hit stays bitwise-neutral inside the quantized numerics."""
        prompts = [np.concatenate([PREAMBLE, np.asarray(s, np.int32)])
                   for s in ([3, 5], [7, 9, 11])]
        kw = dict(temperature=temp, quantize_weights=True,
                  kv_dtype="int8")
        cold = make_engine(lm, **kw)
        warm = make_engine(lm, prefix_cache=True, **kw)
        cold_out = _serve(cold, prompts)
        warm_out = _serve(warm, prompts)
        assert warm.stats()["prefix_cache_hit_tokens"] == 20
        for uid, fin in cold_out.items():
            assert np.array_equal(fin.tokens, warm_out[uid].tokens), uid
        warm.check_balanced()

    def test_preempt_restore_bitwise_quantized(self, lm):
        """Preempt-and-restore snapshots / re-seats int8 pages + scale
        planes as one unit: the victim completes bitwise-equal to the
        unpreempted quantized run."""

        def run(num_tiers):
            eng = make_engine(lm, max_batch=1, num_tiers=num_tiers,
                              max_new_tokens=8, quantize_weights=True,
                              kv_dtype="int8")
            low = eng.submit(PREAMBLE, priority=num_tiers - 1,
                             max_new_tokens=8)
            for _ in range(8):
                eng.step()
            if num_tiers > 1:
                eng.submit(np.asarray([2, 4, 6], np.int32), priority=0,
                           max_new_tokens=4)
            done = {f.uid: f for f in eng.run()}
            eng.check_balanced()
            return eng, done[low.uid]

        # tier 1 = no competitor (the uninterrupted oracle); tier 2 =
        # the preemption run.
        e1, fin1 = run(1)
        e2, fin2 = run(2)
        assert e2.stats()["requests_preempted"] >= 1
        assert e1.stats()["requests_preempted"] == 0
        assert np.array_equal(fin1.tokens, fin2.tokens)


# -- journal recovery on the quantized engine -------------------------------
class TestQuantizedJournal:
    def test_recovery_redelivers_bitwise(self, lm, tmp_path):
        kw = dict(quantize_weights=True, kv_dtype="int8",
                  journal_dir=str(tmp_path))
        eng1 = make_engine(lm, **kw)
        eng1.recover()
        out1 = _serve(eng1, PROMPTS)
        eng1.journal.shutdown()
        eng2 = make_engine(lm, **kw)
        report = eng2.recover()
        redelivered = {f.uid: f for f in report["redelivered"]}
        assert set(redelivered) == set(out1)
        for uid, fin in out1.items():
            assert np.array_equal(redelivered[uid].tokens, fin.tokens)
        eng2.journal.shutdown()

    def test_fingerprint_pins_quantization_mode(self, lm, tmp_path):
        """A journal written by a quantized engine must not replay into
        a full-precision one (different numerics, different streams) —
        the fingerprint catches it like a seed mismatch."""
        eng1 = make_engine(lm, quantize_weights=True, kv_dtype="int8",
                           journal_dir=str(tmp_path))
        eng1.recover()
        _serve(eng1, [PROMPTS[0]])
        eng1.journal.shutdown()
        eng2 = make_engine(lm, journal_dir=str(tmp_path))
        with pytest.raises(JournalCorruptError, match="fingerprint"):
            eng2.recover()


# -- quality bounds (the lm_q geometry; see the CI quantization drill) ------
class TestQuality:
    def test_eval_loss_delta_bounded(self, lm_q):
        model, params = lm_q
        qparams = quantize_params(params)
        rng = np.random.RandomState(0)
        batch = rng.randint(0, 16, size=(4, 32)).astype(np.int32)

        def ce(p):
            logits = model.apply({"params": p}, batch)
            lp = jax.nn.log_softmax(
                logits[:, :-1].astype(jnp.float32), axis=-1)
            tgt = batch[:, 1:]
            return float(-jnp.mean(
                jnp.take_along_axis(lp, tgt[..., None], axis=-1)))

        delta = abs(ce(qparams) - ce(params))
        # Measured 5.3e-4 on this fixed seed; 0.01 is ~20x headroom
        # while still catching any quantization-coverage breakage
        # (dropping a channel axis moves it by >0.1).
        assert delta <= 0.01, delta

    def test_greedy_exact_match_vs_fp32(self, lm_q):
        """>= 0.98 per-token greedy agreement with the fp32 engine on
        the smoke geometry (this prompt seed measures 128/128; the
        bound leaves room for platform-level float drift)."""
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 16, size=int(n)).astype(np.int32)
                   for n in rng.randint(8, 25, size=16)]

        def serve(quant):
            eng = make_engine(lm_q, max_batch=4, max_new_tokens=8,
                              quantize_weights=quant,
                              kv_dtype="int8" if quant else None)
            return {uid: f.tokens
                    for uid, f in _serve(eng, prompts).items()}

        a, b = serve(False), serve(True)
        match = total = 0
        for uid in a:
            total += max(len(a[uid]), len(b[uid]))
            match += sum(1 for x, y in zip(a[uid], b[uid]) if x == y)
        assert match / total >= 0.98, (match, total)
