"""ImageNet-recipe extensions: label smoothing + top-5 eval metric."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state, state_shardings
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.step import (
    cross_entropy_loss,
    make_eval_step,
    make_train_step,
)
from distributed_training_tpu.train.train_state import init_train_state


class TestLabelSmoothing:
    def test_matches_manual_formula(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(4, 7), jnp.float32)
        labels = jnp.asarray([0, 3, 6, 2], jnp.int32)
        eps = 0.1
        got = cross_entropy_loss(logits, labels, label_smoothing=eps)
        logp = jax.nn.log_softmax(logits)
        target = (jax.nn.one_hot(labels, 7) * (1 - eps) + eps / 7)
        want = -(target * logp).sum(-1).mean()
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)

    def test_zero_smoothing_is_plain_ce(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(4, 7), jnp.float32)
        labels = jnp.asarray([1, 2, 3, 4], jnp.int32)
        np.testing.assert_allclose(
            float(cross_entropy_loss(logits, labels, 0.0)),
            float(optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()), rtol=1e-6)

    def test_train_step_loss_reflects_smoothing(self, mesh):
        # ResNet's head has a non-zero init (ViT's is zero-init, making
        # initial logits uniform — where smoothed CE equals plain CE).
        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        rng_np = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(rng_np.rand(8, 16, 16, 3), jnp.float32),
            "label": jnp.asarray(rng_np.randint(0, 10, 8), jnp.int32),
        }

        def run(smoothing):
            state = init_train_state(
                model, jax.random.PRNGKey(0), (8, 16, 16, 3),
                optax.adam(1e-3),
                loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
            state = place_state(state, state_shardings(state, mesh, 0))
            step = make_train_step(mesh, donate=False,
                                   label_smoothing=smoothing)
            _, m = step(state, batch, jax.random.PRNGKey(1))
            return float(m["loss"])

        plain, smoothed = run(0.0), run(0.1)
        assert smoothed != pytest.approx(plain, rel=1e-4)
        # Smoothed CE against near-uniform initial logits is higher by
        # roughly nothing — the robust check is inequality above; also both
        # must be finite.
        assert np.isfinite(plain) and np.isfinite(smoothed)


class TestTop5Eval:
    def test_counts(self, mesh):
        model = get_model("resnet_micro", num_classes=10, stem="cifar")
        state = init_train_state(
            model, jax.random.PRNGKey(0), (8, 8, 8, 3), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = place_state(state, state_shardings(state, mesh, 0))
        step = make_eval_step(mesh)
        rng_np = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(rng_np.rand(8, 8, 8, 3), jnp.float32),
            "label": jnp.asarray(rng_np.randint(0, 10, 8), jnp.int32),
        }
        c1, c5, t = step(state, batch)
        assert float(t) == 8
        assert 0 <= float(c1) <= float(c5) <= 8

    def test_top5_from_known_logits(self):
        """Pin the top-5 membership math on a hand-built logits matrix."""
        logits = jnp.asarray([
            [9, 8, 7, 6, 5, 0, 0, 0],   # top5 = {0..4}
            [0, 1, 2, 3, 4, 5, 6, 7],   # top5 = {3..7}
        ], jnp.float32)
        labels = jnp.asarray([4, 0], jnp.int32)
        k = 5
        _, topk = jax.lax.top_k(logits, k)
        hit = jnp.any(topk == labels[:, None], axis=-1)
        np.testing.assert_array_equal(np.asarray(hit), [True, False])

    def test_trainer_records_top5(self, mesh, tmp_path):
        from distributed_training_tpu.config import DataConfig, TrainConfig
        from distributed_training_tpu.train.trainer import Trainer

        cfg = TrainConfig(
            model="resnet_micro", num_epochs=1, eval_every=1, log_interval=4,
            label_smoothing=0.1,
            data=DataConfig(dataset="synthetic_cifar", batch_size=4,
                            max_steps_per_epoch=2, prefetch=0),
        )
        tr = Trainer(cfg, mesh=mesh)
        tr.fit()
        assert set(tr.last_eval) == {"top1", "top5"}
        assert tr.last_eval["top5"] >= tr.last_eval["top1"]


class TestPreciseBN:
    def test_refresh_rescues_stale_stats_eval(self, tmp_path):
        """After a short high-LR run, raw EMA running stats lag the params
        badly enough that eval collapses while train accuracy is ~1.0;
        eval_precise_bn_batches re-estimates the stats with the final
        params and recovers eval (round-2 finding: 0.098 -> 0.96 on this
        exact setup at 256 steps)."""
        from distributed_training_tpu import TrainConfig, Trainer
        from distributed_training_tpu.config import DataConfig

        base = dict(
            model="resnet_micro", num_epochs=1, log_interval=32,
            eval_every=1,
            data=DataConfig(dataset="synthetic_cifar", batch_size=16,
                            max_steps_per_epoch=96))
        raw = Trainer(TrainConfig.from_plugin("torch_ddp").replace(
            **base, eval_precise_bn_batches=0)).fit()
        refreshed = Trainer(TrainConfig.from_plugin("torch_ddp").replace(
            **base, eval_precise_bn_batches=16)).fit()
        assert refreshed["final_acc"] > raw["final_acc"] + 0.2, (
            raw["final_acc"], refreshed["final_acc"])
        assert refreshed["final_acc"] > 0.5

    def test_refresh_is_true_average_no_stale_residue(self):
        """The refresh must fully replace the running stats with the
        average of the N per-batch moments — an EMA tick from the stale
        stats would leave a momentum**N residue (~59% at N=5, round-2
        advisor finding). Poisoning the stats with a huge constant and
        refreshing over few batches must erase the poison completely."""
        import jax
        import numpy as np

        from distributed_training_tpu import TrainConfig, Trainer
        from distributed_training_tpu.config import DataConfig
        from distributed_training_tpu.data.cifar10 import synthetic_cifar10
        from distributed_training_tpu.data.pipeline import ShardedDataLoader

        cfg = TrainConfig(
            model="resnet_micro", num_epochs=1,
            data=DataConfig(dataset="synthetic_cifar", batch_size=8,
                            max_steps_per_epoch=4, prefetch=0))
        tr = Trainer(cfg)
        poison = 1e4
        tr.state = tr.state.replace(batch_stats=jax.tree.map(
            lambda s: s + poison, tr.state.batch_stats))
        images, labels = synthetic_cifar10(64, True, seed=0)
        loader = ShardedDataLoader(
            images, labels, global_batch_size=8, augment="none")
        tr._refresh_batch_stats(loader, num_batches=4)
        # Activations are O(1); any stale residue of the 1e4 poison (even
        # 0.9**4 ~ 66%) would leave means in the thousands.
        for leaf in jax.tree.leaves(tr.state.batch_stats):
            assert np.all(np.abs(np.asarray(leaf)) < 100.0)
