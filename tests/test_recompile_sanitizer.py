"""Compiled-program sanitizer: the XLA inventory pins hold and trip.

The runtime half of the static-shape discipline (the AST half is
``tools/lint``'s ``static-shape`` rule): the serving engine's documented
inventory — paged = 2 compiled programs, legacy = 3, one shape per
program except the bucketed legacy prefill (docs/SERVING.md
"compiled-program inventory") — is pinned through
``Engine.compiled_programs()`` + ``check_engine_inventory``, and a warm
steady state must not compile at all (``CompileWatch``). The growth
case forces a retrace the way a real leak would appear (a prompt
landing in an unwarmed bucket) and asserts the sanitizer trips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_tpu.config import ServeConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.observability.sanitizer import (
    CompileWatch,
    RecompileError,
    check_engine_inventory,
    compile_count,
    jit_cache_size,
)
from distributed_training_tpu.serving import Engine

VOCAB = 32
MAX_LEN = 32


@pytest.fixture(scope="module")
def lm():
    model = get_model("transformer_lm", num_classes=VOCAB, num_layers=1,
                      num_heads=2, hidden_dim=16, max_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


def _submit(engine, lens, seed=0):
    rng = np.random.RandomState(seed)
    for l in lens:
        engine.submit(rng.randint(0, VOCAB, size=l).astype(np.int32))


class TestCompileWatch:
    def test_counts_backend_compiles_and_cache_hits_dont(self):
        x = jnp.arange(8, dtype=jnp.float32)  # materialized pre-watch
        f = jax.jit(lambda v: v * 2 + 1)
        with CompileWatch() as watch:
            f(x)
        assert watch.compiles >= 1
        with pytest.raises(RecompileError, match="must not retrace"):
            watch.check_no_growth("test window")
        watch.mark()
        f(x)  # same shape: cache hit
        assert watch.compiles == 0
        watch.check_no_growth("warm window")  # no raise
        watch.expect(0, "warm window")  # no raise
        assert jit_cache_size(f) == 1
        x9 = jnp.arange(9, dtype=jnp.float32)  # arange compiles too —
        watch.mark()                           # keep it outside the pin
        f(x9)  # new shape: retrace
        assert jit_cache_size(f) == 2
        assert watch.compiles == 1
        watch.expect(1, "one forced retrace")  # no raise
        with pytest.raises(RecompileError, match="expected exactly"):
            watch.expect(2, "wrong pin")

    def test_compile_count_monotonic(self):
        a = compile_count()
        jax.jit(lambda v: v - 3)(jnp.float32(1.0))
        b = compile_count()
        assert b > a >= 0


class TestEngineInventory:
    def test_paged_engine_pins_two_programs_one_shape(self, lm):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=4, temperature=0.0,
            prefill_chunk=4))
        _submit(eng, [3, 5, 7])
        assert len(eng.run()) == 3
        progs = eng.compiled_programs()
        # Both programs ran (chunked prefill rode the fused step; the
        # post-prefill iterations were decode-only) and each holds
        # exactly one trace.
        assert progs == {"fused": 1, "decode": 1}
        assert check_engine_inventory(eng) == progs

    def test_legacy_engine_pins_three_programs(self, lm):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=4, temperature=0.0,
            kv_page_size=None, prefill_bucket=8))
        _submit(eng, [3, 5, 7])  # one shared 8-token prefill bucket
        assert len(eng.run()) == 3
        progs = eng.compiled_programs()
        assert progs == {"prefill": 1, "admit": 1, "decode": 1}
        assert check_engine_inventory(eng, prefill_shapes=1) == progs

    def test_warm_paged_steady_state_never_compiles(self, lm):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=4, temperature=0.0,
            prefill_chunk=4))
        _submit(eng, [3, 5])
        eng.run()  # warm-up: both programs compiled
        with CompileWatch() as watch:
            _submit(eng, [3, 5, 7], seed=1)  # same shapes, new uids
            assert len(eng.run()) == 3
        watch.check_no_growth("warm paged serving")  # no raise
        check_engine_inventory(eng)

    def test_forced_extra_shape_trips_the_sanitizer(self, lm):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=4, temperature=0.0,
            kv_page_size=None, prefill_bucket=8))
        _submit(eng, [3, 5])
        eng.run()  # warm within the first bucket only
        check_engine_inventory(eng, prefill_shapes=1)
        with CompileWatch() as watch:
            _submit(eng, [13])  # lands in the UNWARMED second bucket
            eng.run()
        # The forced retrace is visible on both surfaces: the window
        # compiled, and the prefill program now holds two shapes.
        assert watch.compiles >= 1
        with pytest.raises(RecompileError, match="must not retrace"):
            watch.check_no_growth("legacy window with a cross-bucket "
                                  "prompt")
        assert eng.compiled_programs()["prefill"] == 2
        with pytest.raises(RecompileError, match="prefill"):
            check_engine_inventory(eng, prefill_shapes=1)

    def test_fixture_hands_out_a_marked_watch(self, lm, compile_watch):
        # The conftest fixture arms a watch before the test body; a
        # test that only touches warm code can assert silence.
        assert compile_watch.compiles == 0
        compile_watch.check_no_growth("fixture smoke")


class TestSpeculationInventory:
    """Speculation-on counts (docs/SERVING.md): the verify window IS
    the decode program at a wider fixed shape — the n-gram drafter
    changes NO count, a GPT drafter adds exactly one single-shape
    'draft' program, and the warm speculative steady state compiles
    nothing (varying accept lengths and proposal widths are masks,
    never shapes)."""

    def test_paged_spec_ngram_keeps_two_programs(self, lm):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=6, temperature=0.0,
            prefill_chunk=4, spec_k=2))
        _submit(eng, [3, 5, 7])
        assert len(eng.run()) == 3
        progs = eng.compiled_programs()
        assert progs == {"fused": 1, "decode": 1}
        assert check_engine_inventory(eng) == progs
        # Warm speculative serving: accept lengths vary per iteration,
        # shapes never do.
        with CompileWatch() as watch:
            _submit(eng, [3, 5, 7], seed=1)
            assert len(eng.run()) == 3
        watch.check_no_growth("warm speculative serving")

    def test_gpt_drafter_adds_one_draft_program(self, lm):
        model, params = lm
        eng = Engine(model, params, ServeConfig(
            max_batch=2, max_new_tokens=6, temperature=0.0,
            prefill_chunk=4, spec_k=2, spec_drafter="gpt",
            spec_draft_window=8))
        _submit(eng, [3, 5])
        assert len(eng.run()) == 2
        progs = eng.compiled_programs()
        assert progs == {"fused": 1, "decode": 1, "draft": 1}
        assert check_engine_inventory(eng) == progs
        with CompileWatch() as watch:
            _submit(eng, [3, 5], seed=1)
            assert len(eng.run()) == 2
        watch.check_no_growth("warm gpt-drafted serving")
