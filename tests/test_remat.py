"""Rematerialization (activation checkpointing) tests.

remat must be a pure memory/FLOPs trade: forward outputs, gradients, and
the resulting training trajectory are bit-compatible with the plain model
(same params, same math — only the backward's recompute schedule differs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_training_tpu.config import PrecisionConfig
from distributed_training_tpu.models import get_model
from distributed_training_tpu.parallel.sharding import place_state, state_shardings
from distributed_training_tpu.train.precision import LossScaleState
from distributed_training_tpu.train.step import make_train_step
from distributed_training_tpu.train.train_state import init_train_state

VIT_KW = dict(hidden_size=32, num_layers=2, num_heads=2, mlp_dim=64,
              patch_size=8, dropout_rate=0.0)


class TestEquivalence:
    # Per-model grad tolerance: remat's backward RECOMPUTES the saved
    # activations, and XLA associates the recomputed reductions in a
    # different order than the stored-activation backward. For the
    # transformer_lm the logits.sum() cotangent flows through the tied
    # embedding twice (tok_embed + head), where that reassociation
    # lands a handful of fp32 grad elements a few ulp apart (measured:
    # 1/1024 elements, 2.1e-6 abs / 5.5e-5 rel — pure float noise, not
    # a backward bug; real remat breakage is O(1) off and still trips
    # the loosened bound).
    @pytest.mark.parametrize("name,kw,shape,grad_tol", [
        ("vit_b16", VIT_KW, (2, 16, 16, 3),
         dict(rtol=1e-5, atol=1e-6)),
        ("resnet_micro", dict(stem="cifar"), (2, 8, 8, 3),
         dict(rtol=1e-5, atol=1e-6)),
        ("transformer_lm", dict(num_layers=2, num_heads=2, hidden_dim=32,
                                max_len=32), (2, 8),
         dict(rtol=2e-4, atol=1e-5)),
    ])
    def test_outputs_and_grads_match_plain(self, name, kw, shape, grad_tol):
        plain = get_model(name, num_classes=10, **kw)
        ckpt = get_model(name, num_classes=10, remat=True, **kw)
        if name == "transformer_lm":
            x = jax.random.randint(jax.random.PRNGKey(0), shape, 0, 10)
        else:
            x = jax.random.uniform(jax.random.PRNGKey(0), shape)
        variables = plain.init(jax.random.PRNGKey(1), x, train=False)
        params = variables["params"]
        # Param trees are layout-identical: remat only changes the backward.
        chex = __import__("chex")
        chex.assert_trees_all_equal_shapes(
            params, ckpt.init(jax.random.PRNGKey(1), x, train=False)["params"])

        out_a = plain.apply(variables, x, train=False)
        out_b = ckpt.apply(variables, x, train=False)
        np.testing.assert_allclose(out_a, out_b, rtol=1e-6, atol=1e-6)

        extra = {k: v for k, v in variables.items() if k != "params"}

        def loss_grads(m):
            def f(p):
                logits = m.apply({"params": p, **extra}, x, train=False)
                return logits.sum()
            return jax.grad(f)(params)

        ga, gb = loss_grads(plain), loss_grads(ckpt)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, **grad_tol),
            ga, gb)


class TestTrainStepIntegration:
    def test_vit_remat_train_step_matches_plain(self, mesh):
        batch = {
            "image": jnp.asarray(
                np.random.RandomState(0).rand(8, 16, 16, 3), jnp.float32),
            "label": jnp.asarray(
                np.random.RandomState(0).randint(0, 10, 8), jnp.int32),
        }

        def run(remat):
            model = get_model("vit_b16", num_classes=10, remat=remat, **VIT_KW)
            state = init_train_state(
                model, jax.random.PRNGKey(0), (8, 16, 16, 3),
                optax.adam(1e-3),
                loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
            state = place_state(state, state_shardings(state, mesh, 0))
            step = make_train_step(mesh, donate=False)
            new_state, m = step(state, batch, jax.random.PRNGKey(1))
            return jax.device_get(new_state.params), float(m["loss"])

        pa, la = run(False)
        pb, lb = run(True)
        assert la == pytest.approx(lb, rel=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
            pa, pb)

    def test_resnet_with_bn_remat_trains(self, mesh):
        """BatchNorm's mutable batch_stats must thread through nn.remat."""
        model = get_model("resnet_micro", num_classes=10, stem="cifar", remat=True)
        state = init_train_state(
            model, jax.random.PRNGKey(0), (8, 8, 8, 3), optax.adam(1e-3),
            loss_scale=LossScaleState.create(PrecisionConfig(dtype="fp32")))
        state = place_state(state, state_shardings(state, mesh, 0))
        step = make_train_step(mesh, donate=False)
        batch = {
            "image": jnp.asarray(
                np.random.RandomState(0).rand(8, 8, 8, 3), jnp.float32),
            "label": jnp.asarray(
                np.random.RandomState(0).randint(0, 10, 8), jnp.int32),
        }
        new_state, m = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
        changed = jax.tree.map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            jax.device_get(state.batch_stats),
            jax.device_get(new_state.batch_stats))
        assert max(jax.tree.leaves(changed)) > 0

    def test_lm_trainer_remat_pipeline_rejected(self, mesh):
        from distributed_training_tpu.config import (
            DataConfig,
            LMConfig,
            MeshSpec,
            TrainConfig,
        )
        from distributed_training_tpu.train.lm_trainer import LMTrainer

        # Round 3 closed this gap: PipelinedLM checkpoints each layer inside
        # its stage scan (parallel/pipeline.py), so remat + pipeline now
        # CONSTRUCTS instead of raising (this test pinned the old refusal
        # and was stale — the r3 suite snapshot missed it).
        cfg = TrainConfig(
            model="transformer_lm", remat=True,
            mesh=MeshSpec(data=-1, pipe=2),
            data=DataConfig(batch_size=4),
            lm=LMConfig(seq_len=16, vocab_size=32, num_layers=2, num_heads=2,
                        hidden_dim=16, max_len=32, num_microbatches=2),
        )
        trainer = LMTrainer(cfg)
        assert trainer.model.remat
        assert trainer.strategy == "pipeline"

    def test_generation_with_remat_model(self):
        """Decode path bypasses remat (no backward) and still works."""
        from distributed_training_tpu.inference import Generator, SampleConfig

        model = get_model("transformer_lm", num_classes=32, remat=True,
                          num_layers=2, num_heads=2, hidden_dim=32, max_len=32)
        tokens = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        out = Generator(model, params, SampleConfig(
            max_new_tokens=4, temperature=0.0))(np.array([[1, 2]]))
        assert out.shape == (1, 4)
